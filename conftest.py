# Root conftest: puts the repo root on sys.path so `escalator_tpu` imports
# without installation. Test-only environment setup lives in tests/conftest.py.
