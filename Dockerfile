# escalator-tpu controller image. For TPU nodepools, swap the base for an image
# with libtpu and jax[tpu]; the program is identical on XLA-CPU.
FROM python:3.12-slim

RUN apt-get update && apt-get install -y --no-install-recommends g++ \
    && rm -rf /var/lib/apt/lists/*

RUN pip install --no-cache-dir "jax[cpu]" numpy pyyaml msgpack grpcio \
    prometheus-client

WORKDIR /app
COPY pyproject.toml ./
COPY escalator_tpu ./escalator_tpu
RUN pip install --no-cache-dir -e . \
    # pre-build the native state store so first start needs no compiler warm-up
    && python -c "from escalator_tpu.native import statestore; assert statestore.available()"

EXPOSE 8080
# for non-k8s runtimes (docker/compose); k8s manifests use the probe endpoints
HEALTHCHECK --interval=30s --timeout=5s --start-period=120s \
  CMD python -c "import urllib.request; urllib.request.urlopen('http://127.0.0.1:8080/healthz', timeout=3)" || exit 1
ENTRYPOINT ["python", "-m", "escalator_tpu"]
