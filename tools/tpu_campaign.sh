#!/bin/bash
# TPU tunnel campaign (VERDICT r2 task 1): the axon tunnel wedges for hours at a
# time, so instead of one startup probe we retry all round. Every attempt is
# logged with a timestamp to TPU_ATTEMPTS.log (auditable evidence either way);
# when the tunnel answers, a full bench run is captured immediately to a
# timestamped file (the tunnel may wedge again before end-of-round).
cd "$(dirname "$0")/.." || exit 1
LOG=TPU_ATTEMPTS.log
INTERVAL="${TPU_CAMPAIGN_INTERVAL:-300}"
# traces whose dir name predates this cutoff document a superseded decide
# program (pre-combined-sort) — keep in sync with COMBINED_SORT_SINCE in
# tests/test_trace_artifact.py; bump BOTH when the traced program changes
TRACE_VINTAGE_CUTOFF="trace_20260730T183000Z"
while true; do
  TS=$(date -u +%FT%TZ)
  # probe in a fresh subprocess: a wedged tunnel hangs even jnp.ones(8), and no
  # in-process timeout can interrupt it (see jaxconfig.ensure_responsive_accelerator)
  if timeout 120 python - >/tmp/tpu_probe_out 2>&1 <<'EOF'
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform not in ("cpu",), f"cpu-only: {d}"
print(float(jnp.ones(8).sum()))
print(d[0])
EOF
  then
    echo "$TS probe OK: $(tail -1 /tmp/tpu_probe_out)" >> "$LOG"
    # round 15 hygiene: captures, stderr logs and partials land under
    # tpu_traces/ (bench.py's capture/partial summarizers glob both the
    # repo root — legacy — and tpu_traces/)
    mkdir -p tpu_traces
    CAP="tpu_traces/TPU_BENCH_$(date -u +%Y%m%dT%H%M%SZ).json"
    # campaign captures race a short tunnel window: fewer iters, skip the
    # CPU-only sharded subprocess (the end-of-round driver run does it all)
    # 55 min: r4 added configs (fused-tick compile, plugin round-trips, cfg9
    # retimes) that pushed a tunnel-weather-slowed session past the old 30;
    # r5's cfg13 (1M-pod store build + ~1M-lane decide compile + 8 ticks) and
    # the cfg9 pallas retimes add more — budget up again so a slow session
    # still lands its capture instead of timing out at the finish line
    # stall watchdog instead of one flat timeout: the tunnel can answer the
    # probe and wedge seconds later (observed 2026-07-31T03:15Z — probe OK,
    # bench stuck at the first compile with zero CPU for the full 55 min).
    # bench.py flushes a per-run partial file after every section (per-run so
    # a concurrent driver bench can't feed this watchdog a false progress
    # signal; TPU_PARTIAL_* so capture globs never confuse it with a full
    # TPU_BENCH_* capture); if it goes STALL_SEC without progress, kill the
    # bench, keep the partial as salvaged evidence, and fall back to probing
    # — a wedge costs the stall budget, not the whole bench budget. The
    # budget is generous (15 min) because the heaviest single gaps between
    # flushes — cfg13's 1M-pod build and one cfg9 row's four timing loops —
    # can take several minutes on a tunnel-weather-slowed session.
    PARTIAL="tpu_traces/TPU_PARTIAL_${CAP#tpu_traces/TPU_BENCH_}"
    rm -f "$PARTIAL"
    ESCALATOR_TPU_BENCH_ITERS=12 ESCALATOR_TPU_BENCH_SKIP_SHARDED=1 \
       ESCALATOR_TPU_BENCH_PARTIAL="$PARTIAL" \
       python bench.py > "$CAP" 2>"${CAP%.json}.stderr.log" &
    BPID=$!
    DEADLINE=$(( $(date +%s) + 3300 ))
    STALL_SEC="${TPU_CAMPAIGN_STALL_SEC:-900}"
    LAST=$(date +%s)
    KILLED=""
    while kill -0 "$BPID" 2>/dev/null; do
      sleep 20
      NOW=$(date +%s)
      if [ -f "$PARTIAL" ]; then
        M=$(stat -c %Y "$PARTIAL" 2>/dev/null || echo "$LAST")
        [ "$M" -gt "$LAST" ] && LAST="$M"
      fi
      if [ "$NOW" -ge "$DEADLINE" ]; then
        KILLED="deadline"; kill -9 "$BPID" 2>/dev/null; break
      fi
      if [ $(( NOW - LAST )) -ge "$STALL_SEC" ]; then
        KILLED="stalled ${STALL_SEC}s"; kill -9 "$BPID" 2>/dev/null; break
      fi
    done
    wait "$BPID" 2>/dev/null
    BENCH_RC=$?
    # a bench that finished during the last sleep window is a success even if
    # the watchdog then fired on the dead pid — don't relabel (and delete!) a
    # complete capture
    if [ -n "$KILLED" ] && [ "$BENCH_RC" != "0" ]; then
      BENCH_RC="killed ($KILLED)"
    fi
    if [ "$BENCH_RC" = "0" ]; then
      if grep -q "CPU fallback" "$CAP"; then
        echo "$(date -u +%FT%TZ) bench ran but degraded mid-run (kept $CAP)" >> "$LOG"
      else
        echo "$(date -u +%FT%TZ) bench CAPTURED on live device -> $CAP" >> "$LOG"
        cp "$CAP" tpu_traces/TPU_BENCH_CAPTURE.json
        # one device trace per impl per campaign while the window holds
        # (cheap next to the bench; evidence of what the TPU actually
        # executes — structure only, durations are profiler artifacts)
        # classify traces by the trace dir basename only — a checkout path
        # containing 'pallas' must not make every dir look like a pallas trace
        HAVE_XLA_TRACE=""
        for d in tpu_traces/trace_*; do
          [ -d "$d" ] || continue
          case "$(basename "$d")" in
            *-pallas) ;;
            *) # vintage gate: pre-cutoff traces document a superseded
               # program — a fresh window should still capture the current
               # one; the archived trace stays as evidence
               if [ "$(basename "$d")" \> "$TRACE_VINTAGE_CUTOFF" ] && \
                  ls "$d"/plugins/profile/*/*.trace.json.gz >/dev/null 2>&1; then
                 HAVE_XLA_TRACE=1
               fi ;;
          esac
        done
        if [ -z "$HAVE_XLA_TRACE" ]; then
          if bash tools/capture_tpu_profile.sh >> "$LOG" 2>&1; then
            echo "$(date -u +%FT%TZ) profiler trace captured (xla)" >> "$LOG"
          else
            echo "$(date -u +%FT%TZ) profiler trace FAILED (xla)" >> "$LOG"
          fi
        fi
        HAVE_PALLAS_TRACE=""
        for d in tpu_traces/trace_*-pallas; do
          [ -d "$d" ] || continue
          # same vintage gate as the xla guard above: a pre-combined-sort
          # pallas trace documents the superseded two-sort decide too
          if [ "$(basename "$d")" \> "$TRACE_VINTAGE_CUTOFF" ] && \
             ls "$d"/plugins/profile/*/*.trace.json.gz >/dev/null 2>&1; then
            HAVE_PALLAS_TRACE=1
          fi
        done
        if [ -z "$HAVE_PALLAS_TRACE" ]; then
          if ESCALATOR_TRACE_IMPL=pallas \
             bash tools/capture_tpu_profile.sh >> "$LOG" 2>&1; then
            echo "$(date -u +%FT%TZ) profiler trace captured (pallas)" >> "$LOG"
          else
            echo "$(date -u +%FT%TZ) profiler trace FAILED (pallas)" >> "$LOG"
          fi
        fi
      fi
    else
      # keep whatever sections completed before the wedge: a partial carrying
      # the fields a full capture never landed is still evidence (bench.py
      # summarizes TPU_PARTIAL_* into detail.tpu_partials)
      if grep -q '"cfg' "$PARTIAL" 2>/dev/null; then
        echo "$(date -u +%FT%TZ) bench $BENCH_RC; completed sections kept -> $PARTIAL" >> "$LOG"
      else
        rm -f "$PARTIAL"
        echo "$(date -u +%FT%TZ) bench $BENCH_RC with no completed sections (see ${CAP%.json}.stderr.log)" >> "$LOG"
      fi
      rm -f "$CAP"
    fi
  else
    echo "$(date -u +%FT%TZ) probe FAIL: $(tail -c 200 /tmp/tpu_probe_out | tr '\n' ' ')" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
