#!/bin/bash
# TPU tunnel campaign (VERDICT r2 task 1): the axon tunnel wedges for hours at a
# time, so instead of one startup probe we retry all round. Every attempt is
# logged with a timestamp to TPU_ATTEMPTS.log (auditable evidence either way);
# when the tunnel answers, a full bench run is captured immediately to a
# timestamped file (the tunnel may wedge again before end-of-round).
cd "$(dirname "$0")/.." || exit 1
LOG=TPU_ATTEMPTS.log
INTERVAL="${TPU_CAMPAIGN_INTERVAL:-300}"
while true; do
  TS=$(date -u +%FT%TZ)
  # probe in a fresh subprocess: a wedged tunnel hangs even jnp.ones(8), and no
  # in-process timeout can interrupt it (see jaxconfig.ensure_responsive_accelerator)
  if timeout 120 python - >/tmp/tpu_probe_out 2>&1 <<'EOF'
import jax, jax.numpy as jnp
d = jax.devices()
assert d and d[0].platform not in ("cpu",), f"cpu-only: {d}"
print(float(jnp.ones(8).sum()))
print(d[0])
EOF
  then
    echo "$TS probe OK: $(tail -1 /tmp/tpu_probe_out)" >> "$LOG"
    CAP="TPU_BENCH_$(date -u +%Y%m%dT%H%M%SZ).json"
    # campaign captures race a short tunnel window: fewer iters, skip the
    # CPU-only sharded subprocess (the end-of-round driver run does it all)
    # 55 min: r4 added configs (fused-tick compile, plugin round-trips, cfg9
    # retimes) that pushed a tunnel-weather-slowed session past the old 30;
    # r5's cfg13 (1M-pod store build + ~1M-lane decide compile + 8 ticks) and
    # the cfg9 pallas retimes add more — budget up again so a slow session
    # still lands its capture instead of timing out at the finish line
    if ESCALATOR_TPU_BENCH_ITERS=12 ESCALATOR_TPU_BENCH_SKIP_SHARDED=1 \
       timeout 3300 python bench.py > "$CAP" 2>"${CAP%.json}.stderr.log"; then
      if grep -q "CPU fallback" "$CAP"; then
        echo "$(date -u +%FT%TZ) bench ran but degraded mid-run (kept $CAP)" >> "$LOG"
      else
        echo "$(date -u +%FT%TZ) bench CAPTURED on live device -> $CAP" >> "$LOG"
        cp "$CAP" TPU_BENCH_CAPTURE.json
        # one device trace per impl per campaign while the window holds
        # (cheap next to the bench; evidence of what the TPU actually
        # executes — structure only, durations are profiler artifacts)
        # classify traces by the trace dir basename only — a checkout path
        # containing 'pallas' must not make every dir look like a pallas trace
        HAVE_XLA_TRACE=""
        for d in tpu_traces/trace_*; do
          [ -d "$d" ] || continue
          case "$(basename "$d")" in
            *-pallas) ;;
            *) ls "$d"/plugins/profile/*/*.trace.json.gz >/dev/null 2>&1 && HAVE_XLA_TRACE=1 ;;
          esac
        done
        if [ -z "$HAVE_XLA_TRACE" ]; then
          if bash tools/capture_tpu_profile.sh >> "$LOG" 2>&1; then
            echo "$(date -u +%FT%TZ) profiler trace captured (xla)" >> "$LOG"
          else
            echo "$(date -u +%FT%TZ) profiler trace FAILED (xla)" >> "$LOG"
          fi
        fi
        if [ -z "$(ls tpu_traces/trace_*-pallas/plugins/profile/*/*.trace.json.gz 2>/dev/null)" ]; then
          if ESCALATOR_TRACE_IMPL=pallas \
             bash tools/capture_tpu_profile.sh >> "$LOG" 2>&1; then
            echo "$(date -u +%FT%TZ) profiler trace captured (pallas)" >> "$LOG"
          else
            echo "$(date -u +%FT%TZ) profiler trace FAILED (pallas)" >> "$LOG"
          fi
        fi
      fi
    else
      echo "$(date -u +%FT%TZ) bench run failed/timed out (see ${CAP%.json}.stderr.log)" >> "$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) probe FAIL: $(tail -c 200 /tmp/tpu_probe_out | tr '\n' ' ')" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
