#!/bin/bash
# Capture a jax.profiler trace of the batched decision on the real TPU —
# evidence of what the device actually executes (MXU/fusion layout). Run when
# the tunnel answers (check: tail TPU_ATTEMPTS.log). Output: a timestamped
# trace dir + a one-line summary JSON for the audit trail.
# ESCALATOR_TRACE_IMPL=pallas traces the fused MXU sweep instead of the
# default XLA scatter path (trace dir gets a -pallas suffix).
# NOTE (docs/performance.md): trace durations are profiler-mode artifacts on
# this tunnel; the trace documents STRUCTURE (which ops run), not timings.
set -e
cd "$(dirname "$0")/.."
IMPL="${ESCALATOR_TRACE_IMPL:-xla}"
SUFFIX=""; [ "$IMPL" != "xla" ] && SUFFIX="-$IMPL"
OUT="tpu_traces/trace_$(date -u +%Y%m%dT%H%M%SZ)$SUFFIX"
mkdir -p "$OUT"
# a failed capture must not leave an empty dir that satisfies the campaign's
# once-per-impl guard forever
trap 'rm -rf "$OUT"' ERR
timeout 600 python - "$OUT" "$IMPL" <<'EOF'
import json
import sys

import numpy as np

out_dir = sys.argv[1]
import jax

import bench as B
from escalator_tpu.ops.kernel import decide_jit

device = jax.devices()[0]
assert device.platform not in ("cpu",), f"not a TPU: {device}"
rng = np.random.default_rng(0)
now = np.int64(1_700_000_000)
cluster = jax.device_put(
    B._rng_cluster_arrays(rng, 2048, 100_000, 50_000, mixed=True,
                          heterogeneous=True, tainted_frac=0.1,
                          cordoned_frac=0.02),
    device,
)
impl = sys.argv[2]
jax.block_until_ready(decide_jit(cluster, now, impl=impl))  # compile first
with jax.profiler.trace(out_dir):
    for _ in range(10):
        jax.block_until_ready(decide_jit(cluster, now, impl=impl))
print(json.dumps({"trace_dir": out_dir, "device": str(device), "impl": impl,
                  "shape": "2048g/100kpods/50knodes", "iters": 10}))
EOF
