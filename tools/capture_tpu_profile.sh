#!/bin/bash
# Capture a jax.profiler trace of the batched decision on the real TPU —
# evidence of what the device actually executes (MXU/fusion layout). Run when
# the tunnel answers (check: tail TPU_ATTEMPTS.log). Output: a timestamped
# trace dir + a one-line summary JSON for the audit trail.
set -e
cd "$(dirname "$0")/.."
OUT="tpu_traces/trace_$(date -u +%Y%m%dT%H%M%SZ)"
mkdir -p "$OUT"
timeout 600 python - "$OUT" <<'EOF'
import json
import sys

import numpy as np

out_dir = sys.argv[1]
import jax

import bench as B
from escalator_tpu.ops.kernel import decide_jit

device = jax.devices()[0]
assert device.platform not in ("cpu",), f"not a TPU: {device}"
rng = np.random.default_rng(0)
now = np.int64(1_700_000_000)
cluster = jax.device_put(
    B._rng_cluster_arrays(rng, 2048, 100_000, 50_000, mixed=True,
                          heterogeneous=True, tainted_frac=0.1,
                          cordoned_frac=0.02),
    device,
)
jax.block_until_ready(decide_jit(cluster, now))  # compile outside the trace
with jax.profiler.trace(out_dir):
    for _ in range(10):
        jax.block_until_ready(decide_jit(cluster, now))
print(json.dumps({"trace_dir": out_dir, "device": str(device),
                  "shape": "2048g/100kpods/50knodes", "iters": 10}))
EOF
