"""Render docs/metrics-dashboard.svg — a static preview of the Grafana
dashboard (docs/grafana-dashboard.json) over one synthetic scale cycle.

The reference ships a screenshot of its live dashboard (docs/metrics.md links
docs/metrics-dashboard.png); this repo has no live Grafana to screenshot, so
the preview is rendered deterministically from a simulated six-hour
scale-up/scale-down cycle instead — same panels, same metric names, plausible
shapes. Regenerate with: python tools/render_dashboard_preview.py

The LATENCY panels are not hand-drawn shapes: per-tick latency samples for
each scrape window flow through the REAL streaming log-bucket histogram
engine (escalator_tpu.observability.histograms.LogHistogram — the same code
behind `escalator_tpu_tick_phase_hist_seconds`), and the plotted series are
its rolling-window p99s, i.e. exactly what the round-13 Grafana
`histogram_quantile(0.99, ...)` queries would render. The tail-dumps panel
counts the samples that breach the tail watchdog's `4 x rolling p99` rule
on the same windows.

Styling follows a fixed mark spec: 2px round-capped lines, hairline solid
gridlines one step off the surface, text in ink tokens (never series colors),
legend for every multi-series panel, sparing direct end-labels. Series hues
are a validated colorblind-safe categorical palette in fixed slot order.
"""

from __future__ import annotations

import math
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))
from escalator_tpu.observability.histograms import LogHistogram  # noqa: E402

W = 1180   # canvas height is derived from the panel grid in main()
PANEL_W, PANEL_H = 560, 270
PAD = 20
PLOT_L, PLOT_T, PLOT_R, PLOT_B = 46, 34, 10, 52

SURFACE = "#fcfcfb"
GRID = "#e8e7e4"
INK = "#0b0b0b"
INK2 = "#52514e"
# categorical slots, fixed order (validated palette; see dataviz notes)
S1, S2, S3, S4 = "#2a78d6", "#eb6834", "#1baf7a", "#eda100"

T = 72  # samples over 6h (5-min scrape)


def cycle():
    """One synthetic scale cycle: pending spike -> scale-up -> drain ->
    taint -> reap. Returns dict of named series, each length T."""
    s = {k: [] for k in (
        "nodes", "untainted", "tainted", "cordoned", "cpu", "mem", "delta",
        "pods", "evicted", "target", "actual", "maxsize", "lock", "lockrate",
        "lag", "run_a", "run_b", "pend_a",
        "ph_run", "ph_pend", "ph_succ", "ph_fail")}
    nodes, tainted = 14.0, 2.0
    for i in range(T):
        x = i / (T - 1)
        # demand wave: quiet -> burst at x~0.25 -> drain after x~0.6
        burst = math.exp(-((x - 0.35) / 0.16) ** 2)
        pods = 40 + 260 * burst + 6 * math.sin(i * 1.7)
        cpu = min(97.0, 22 + 68 * burst + 3 * math.sin(i * 2.3))
        mem = min(92.0, 18 + 55 * burst + 3 * math.cos(i * 1.9))
        delta = 0
        if cpu > 70 and nodes < 26:
            delta = min(4, int((cpu - 70) / 6) + 1)
            nodes += delta
            tainted = max(0.0, tainted - 1)
        elif cpu < 30 and nodes > 12:
            delta = -1
            tainted = min(nodes - 10, tainted + 1)
            if tainted > 3:
                nodes -= 1
                tainted -= 1
        s["nodes"].append(nodes)
        s["untainted"].append(nodes - tainted - 1)
        s["tainted"].append(tainted)
        s["cordoned"].append(1)
        s["cpu"].append(cpu)
        s["mem"].append(mem)
        s["delta"].append(delta)
        s["pods"].append(pods)
        s["evicted"].append(max(0.0, 0.4 * (tainted - 1) + 0.1 * math.sin(i)))
        s["target"].append(nodes)
        s["actual"].append(s["nodes"][max(0, i - 2)])  # provider lags 2 ticks
        s["maxsize"].append(30)
        locked = 1.0 if (0 < delta and cpu > 70) else 0.0
        s["lock"].append(locked)
        s["lockrate"].append(0.2 + 1.4 * locked)
        s["lag"].append(95 + 40 * burst + 8 * math.sin(i * 1.3))
        s["run_a"].append(30 + 180 * burst)
        s["run_b"].append(25 + 20 * math.sin(i * 0.6) ** 2)
        s["pend_a"].append(max(0.0, 90 * burst - 20))
        s["ph_run"].append(55 + 195 * burst)
        s["ph_pend"].append(max(0.0, 95 * burst - 15))
        s["ph_succ"].append(8 + 0.9 * i)
        s["ph_fail"].append(2 + 0.03 * i)
    return s


def _burst(i):
    """The demand wave cycle() uses, shared so the latency windows see the
    same load shape the rest of the dashboard plots."""
    x = i / (T - 1)
    return math.exp(-((x - 0.35) / 0.16) ** 2)


#: (median_s, lognormal sigma, burst gain) per latency series — medians echo
#: the committed cfg16/cfg6 recorder columns so the preview's magnitudes
#: match what a real deployment scrapes
_LATENCY_SPEC = {
    "decide": (1.6e-3, 0.18, 2.2),
    "pack": (3.1e-3, 0.12, 1.4),
    "event_drain": (2.6e-4, 0.25, 1.2),
    "scatter": (1.3e-3, 0.20, 1.3),
    "delta_decide": (9.2e-3, 0.22, 1.9),
    "e2e": (2.1e-2, 0.20, 1.8),
}


def latency_cycle(ticks_per_window=30, window=3):
    """Per-window p99 series THROUGH THE REAL HISTOGRAM ENGINE: for every
    scrape window, per-tick latency samples (lognormal around the spec
    medians, burst-scaled, with occasional 8-20x outlier ticks standing in
    for recompiles/GC) are recorded into a LogHistogram; the plotted value
    is the p99 of the last ``window`` windows' merged histograms — i.e.
    what `histogram_quantile(0.99, rate(..._bucket[15m]))` renders. Also
    returns the tail-dump count series: samples breaching the tail
    watchdog's `4 x rolling p99` rule, at most one dump per window (the
    rate limiter)."""
    rnd = random.Random(13)
    p99 = {k: [] for k in _LATENCY_SPEC}
    dumps = []
    hists = {k: [] for k in _LATENCY_SPEC}
    for i in range(T):
        b = _burst(i)
        for k, (med, sig, gain) in _LATENCY_SPEC.items():
            mu = math.log(med * (1.0 + (gain - 1.0) * b))
            h = LogHistogram()
            window_samples = []
            for _ in range(ticks_per_window):
                v = rnd.lognormvariate(mu, sig)
                if rnd.random() < 0.03:   # a recompile/GC outlier tick
                    v *= rnd.uniform(8.0, 20.0)
                h.record(v)
                window_samples.append(v)
            hists[k].append(h)
            merged = LogHistogram()
            for hh in hists[k][-window:]:
                merged.merge(hh)
            p99[k].append(merged.quantile(0.99))
            if k == "e2e":
                prior = LogHistogram()
                for hh in hists[k][-window - 1:-1]:
                    prior.merge(hh)
                rolling = prior.quantile(0.99)
                breach = rolling is not None and any(
                    v > 4.0 * rolling for v in window_samples)
                dumps.append(1.0 if breach else 0.0)
    return p99, dumps


def fleet_cycle():
    """Synthetic fleet-service series for the round-14 panel: micro-batch
    size p50/p99 tracking the traffic bursts (coalescing deepens under
    load), a slowly-ramping resident-tenant count with occasional
    mass-eviction dips, and admission rejects that appear only when a
    burst saturates the bounded queue. Batch sizes are plain order
    statistics (they are counts, not latencies — the log-bucket engine's
    1 µs..10 s domain is for the latency panels)."""
    rnd = random.Random(21)
    p50, p99, tenants, rejects = [], [], [], []
    tcount = 120.0
    for i in range(T):
        b = _burst(i)
        lam = 2.0 + 60.0 * b
        samples = sorted(
            max(1, min(128, int(rnd.gauss(lam, lam * 0.35 + 0.5))))
            for _ in range(40))
        p50.append(float(samples[len(samples) // 2]))
        p99.append(float(samples[min(len(samples) - 1,
                                     int(len(samples) * 0.99))]))
        tcount = min(1000.0, tcount * 1.02)
        if rnd.random() < 0.03:
            tcount *= 0.85          # a mass eviction + compact
        tenants.append(tcount)
        rejects.append(
            max(0.0, rnd.gauss((b - 0.65) * 60, 2.0)) if b > 0.65 else 0.0)
    return p50, p99, tenants, rejects


def fleet_slo_cycle(ticks_per_window=30, window=3):
    """Synthetic per-priority-class request p99s THROUGH THE REAL
    HISTOGRAM ENGINE (the round-16 panel): critical requests ride the
    weighted-fair fast lane (small, burst-insensitive p99), standard
    tracks the batch cadence, and the best-effort batch class absorbs the
    queue wait under bursts — plus an overlap-saved ms/s series that rises
    with load (more in-flight device time to hide prep under) and drops to
    zero in the trough (nothing to overlap)."""
    rnd = random.Random(34)
    spec = {"critical": (8e-3, 0.15, 1.3), "standard": (3.5e-2, 0.2, 2.0),
            "batch": (1.2e-1, 0.3, 3.5)}
    p99 = {k: [] for k in spec}
    hists = {k: [] for k in spec}
    overlap = []
    for i in range(T):
        b = _burst(i)
        for k, (med, sig, gain) in spec.items():
            mu = math.log(med * (1.0 + (gain - 1.0) * b))
            h = LogHistogram()
            for _ in range(ticks_per_window):
                h.record(rnd.lognormvariate(mu, sig))
            hists[k].append(h)
            merged = LogHistogram()
            for hh in hists[k][-window:]:
                merged.merge(hh)
            p99[k].append(merged.quantile(0.99))
        overlap.append(max(0.0, rnd.gauss(3.0 + 22.0 * b, 1.5)))
    return p99, overlap


def fleet_cache_cycle():
    """Synthetic round-18 digest-cache panel: the hit fraction IS the
    fleet's live idle fraction — high in the trough (steady tenants
    re-send unchanged frames, answered from the per-tenant cache without
    a dispatch), dipping under the burst (churn invalidates digests) —
    plus per-class hit rates scaled by the 10/60/30 class mix."""
    rnd = random.Random(89)
    frac_pct, crit, std = [], [], []
    for i in range(T):
        b = _burst(i)
        f = max(0.05, min(0.97, 0.9 - 0.62 * b + rnd.gauss(0, 0.015)))
        served = 40.0 + 140.0 * b   # decides/s offered
        frac_pct.append(100.0 * f)
        crit.append(0.1 * served * f)
        std.append(0.6 * served * f)
    return frac_pct, crit, std


def fleet_tail_cycle():
    """Synthetic round-18 batched-order-tail panel: per-window tail batch
    size p50/p99 (order statistics — they are counts, not latencies, so
    no log-bucket engine) tracking the scale-down drain wave, plus the
    tail dispatch rate — AT MOST one per micro-batch, so it follows the
    batch cadence only while anything drains and sits at zero for a
    steady fleet."""
    rnd = random.Random(144)
    p50, p99, rate = [], [], []
    for i in range(T):
        x = i / (T - 1)
        drain = math.exp(-((x - 0.72) / 0.10) ** 2)  # post-burst drain
        lam = 1.0 + 46.0 * drain
        samples = sorted(max(1, int(rnd.gauss(lam, 0.4 * lam + 0.5)))
                         for _ in range(40))
        p50.append(float(samples[len(samples) // 2]))
        p99.append(float(samples[min(len(samples) - 1,
                                     int(len(samples) * 0.99))]))
        rate.append(max(0.0, rnd.gauss(2.0 + 18.0 * drain, 0.8))
                    if drain > 0.04 else 0.0)
    return p50, p99, rate


def decision_health_cycle(ticks_per_window=30, window=3):
    """Synthetic round-19 decision-health panel: flap detections cluster on
    the demand wave's FLANKS — where utilisation hovers around the 30/70
    thresholds and nodes_delta alternates sign tick over tick — not at its
    peak (a steady scale-up is not a flap), split by watchdog klass (sign
    alternation vs status churn); explain mismatches are a hard zero by
    construction (the explain kernel shares the decide math core, so any
    non-zero cell is a finding, not noise); and the explain-hook overhead
    p99 runs THROUGH THE REAL HISTOGRAM ENGINE — per-tick hook timings
    (lognormal around the ~40 us stage cost, nudged by load) recorded into
    a LogHistogram per scrape window, plotted as the rolling-window p99
    the <1 % overhead gate bounds."""
    rnd = random.Random(233)
    flap_sign, flap_status, mism, hook_p99_us = [], [], [], []
    hists = []
    for i in range(T):
        b = _burst(i)
        # the wave's slope: largest on the flanks, ~0 at peak and trough
        edge = abs(_burst(min(T - 1, i + 1)) - _burst(max(0, i - 1)))
        flap_sign.append(max(0.0, rnd.gauss(26.0 * edge, 0.4)))
        flap_status.append(max(0.0, rnd.gauss(9.0 * edge, 0.25)))
        mism.append(0.0)
        mu = math.log(4.2e-5 * (1.0 + 0.35 * b))
        h = LogHistogram()
        for _ in range(ticks_per_window):
            h.record(rnd.lognormvariate(mu, 0.3))
        hists.append(h)
        merged = LogHistogram()
        for hh in hists[-window:]:
            merged.merge(hh)
        hook_p99_us.append(merged.quantile(0.99) * 1e6)
    return flap_sign, flap_status, mism, hook_p99_us


def journey_cycle(ticks_per_window=30, window=3):
    """Synthetic per-stage request-journey p99s THROUGH THE REAL HISTOGRAM
    ENGINE (the round-17 panel): the critical class's five journey stages
    — under load the admission (queue-wait) stage absorbs the burst while
    dispatch stays flat (the fused program's width is the batch, not the
    queue) — plus the SLO budget-burn series: the fraction of windowed
    samples over the class target divided by the 1% a p99 SLO allows,
    exactly the scheduler's `fleet_slo_budget_burn{klass}` computation."""
    rnd = random.Random(55)
    spec = {"admission": (2.5e-3, 0.3, 9.0), "batch_assembly": (1.2e-3,
            0.2, 1.5), "dispatch": (6.0e-3, 0.15, 1.2),
            "unpack": (8e-4, 0.2, 1.3)}
    target_s = 0.060    # the preview class's p99 target (60 ms)
    p99 = {k: [] for k in spec}
    hists = {k: [] for k in spec}
    burn = []
    for i in range(T):
        b = _burst(i)
        e2e_samples = []
        for k, (med, sig, gain) in spec.items():
            mu = math.log(med * (1.0 + (gain - 1.0) * b))
            h = LogHistogram()
            vals = [rnd.lognormvariate(mu, sig)
                    for _ in range(ticks_per_window)]
            for v in vals:
                h.record(v)
            hists[k].append(h)
            merged = LogHistogram()
            for hh in hists[k][-window:]:
                merged.merge(hh)
            p99[k].append(merged.quantile(0.99))
            if not e2e_samples:
                e2e_samples = vals
            else:
                e2e_samples = [a + v for a, v in zip(e2e_samples, vals)]
        over = sum(1 for v in e2e_samples if v > target_s)
        burn.append((over / len(e2e_samples)) / 0.01)
    return p99, burn


def nice_ticks(lo, hi, n=4):
    if hi <= lo:
        hi = lo + 1
    raw = (hi - lo) / n
    mag = 10 ** math.floor(math.log10(raw))
    step = min(x for x in (1, 2, 5, 10) if x * mag >= raw) * mag
    t0 = math.floor(lo / step) * step
    out = []
    t = t0
    while t <= hi + 1e-9:
        if t >= lo - 1e-9:
            out.append(t)
        t += step
    return out


def fmt(v):
    if v >= 1000:
        return f"{v:,.0f}"
    if v == int(v):
        return f"{int(v)}"
    return f"{v:g}"


class Panel:
    def __init__(self, x, y, title):
        self.x, self.y, self.title = x, y, title
        self.parts = [
            f'<g transform="translate({x},{y})">',
            f'<rect width="{PANEL_W}" height="{PANEL_H}" fill="{SURFACE}" '
            f'stroke="{GRID}" rx="4"/>',
            f'<text x="14" y="22" fill="{INK}" font-size="13" '
            f'font-weight="600">{title}</text>',
        ]
        self.pw = PANEL_W - PLOT_L - PLOT_R
        self.ph = PANEL_H - PLOT_T - PLOT_B

    def px(self, i):
        return PLOT_L + self.pw * i / (T - 1)

    def py(self, v, lo, hi):
        return PLOT_T + self.ph * (1 - (v - lo) / (hi - lo))

    def axes(self, lo, hi, unit=""):
        for tv in nice_ticks(lo, hi):
            y = self.py(tv, lo, hi)
            self.parts.append(
                f'<line x1="{PLOT_L}" y1="{y:.1f}" x2="{PLOT_L + self.pw}" '
                f'y2="{y:.1f}" stroke="{GRID}" stroke-width="1"/>')
            self.parts.append(
                f'<text x="{PLOT_L - 6}" y="{y + 4:.1f}" fill="{INK2}" '
                f'font-size="10" text-anchor="end">{fmt(tv)}{unit}</text>')
        # time labels sit just under the plot, clear of the legend row below
        for frac, lab in ((0, "12:00"), (0.5, "15:00"), (1, "18:00")):
            x = PLOT_L + self.pw * frac
            self.parts.append(
                f'<text x="{x:.1f}" y="{PLOT_T + self.ph + 14}" fill="{INK2}" '
                f'font-size="10" text-anchor="middle">{lab}</text>')

    def line(self, series, color, lo, hi):
        pts = " ".join(
            f"{self.px(i):.1f},{self.py(v, lo, hi):.1f}"
            for i, v in enumerate(series))
        self.parts.append(
            f'<polyline points="{pts}" fill="none" stroke="{color}" '
            f'stroke-width="2" stroke-linejoin="round" stroke-linecap="round"/>')

    def end_label(self, series, label, lo, hi):
        """Sparing direct label at the line's endpoint, in ink (text never
        wears the series color)."""
        y = self.py(series[-1], lo, hi)
        self.parts.append(
            f'<text x="{PLOT_L + self.pw - 4:.1f}" y="{y - 6:.1f}" '
            f'fill="{INK2}" font-size="10" text-anchor="end">{label}</text>')

    def legend(self, entries):
        x = PLOT_L
        for color, label in entries:
            self.parts.append(
                f'<rect x="{x}" y="{PANEL_H - 22}" width="10" height="10" '
                f'rx="2" fill="{color}"/>')
            self.parts.append(
                f'<text x="{x + 14}" y="{PANEL_H - 13}" fill="{INK2}" '
                f'font-size="10">{label}</text>')
            x += 14 + 7 * len(label) + 16

    def done(self):
        self.parts.append("</g>")
        return "\n".join(self.parts)


def timeseries_panel(x, y, title, series, unit="", labels=()):
    """series: list of (values, color, legend_label)."""
    p = Panel(x, y, title)
    lo = min(0.0, min(min(vals) for vals, _, _ in series) * 1.15)
    hi = max(max(vals) for vals, _, _ in series) * 1.15 or 1.0
    p.axes(lo, hi, unit)
    for vals, color, _ in series:
        p.line(vals, color, lo, hi)
    if len(series) > 1:
        p.legend([(c, l) for _, c, l in series])
    for vals, _, lab in (series[i] for i in labels):
        p.end_label(vals, lab, lo, hi)
    return p.done()


def main():
    s = cycle()
    p99, tail_dumps = latency_cycle()
    fleet_p50, fleet_p99, fleet_tenants, fleet_rejects = fleet_cycle()
    slo_p99, slo_overlap = fleet_slo_cycle()
    stage_p99, budget_burn = journey_cycle()
    cache_frac, cache_crit, cache_std = fleet_cache_cycle()
    tail_p50, tail_p99, tail_rate = fleet_tail_cycle()
    flap_sign, flap_status, prov_mism, hook_p99_us = decision_health_cycle()
    panels, grid = [], [
        ("Node counts by state",
         [(s["nodes"], S1, "total"), (s["untainted"], S2, "untainted"),
          (s["tainted"], S3, "tainted"), (s["cordoned"], S4, "cordoned")],
         "", (0,)),
        ("Utilisation (%)",
         [(s["cpu"], S1, "cpu"), (s["mem"], S2, "mem")], "%", (0,)),
        ("Scale delta", [(s["delta"], S1, "delta")], "", ()),
        ("Pods",
         [(s["pods"], S1, "considered"), (s["evicted"], S2, "evicted/s")],
         "", (0,)),
        ("Provider sizes",
         [(s["target"], S1, "target"), (s["actual"], S2, "actual"),
          (s["maxsize"], S3, "max")], "", (2,)),
        ("Scale lock",
         [(s["lock"], S1, "locked"), (s["lockrate"], S2, "locked checks/s")],
         "", ()),
        ("Node registration lag (p90)", [(s["lag"], S1, "p90")], "s", ()),
        ("Solver latency (p99)",
         [(p99["decide"], S1, "decide"), (p99["pack"], S2, "pack")],
         "s", ()),
        ("Running Pods (by namespace)",
         [(s["run_a"], S1, "buildeng running"), (s["run_b"], S2,
           "shared running"), (s["pend_a"], S3, "buildeng pending")], "", ()),
        ("Pod Phase",
         [(s["ph_run"], S1, "Running"), (s["ph_pend"], S2, "Pending"),
          (s["ph_succ"], S3, "Succeeded"), (s["ph_fail"], S4, "Failed")],
         "", (0,)),
        # round 13: the two tail panels the Grafana board gained — phase
        # p99s and the e2e-tick p99 + tail-dump rate, all through the real
        # log-bucket engine (see latency_cycle)
        ("Tick phase latency (p99)",
         [(p99["event_drain"], S1, "event_drain"),
          (p99["scatter"], S2, "scatter"),
          (p99["delta_decide"], S3, "delta_decide")], "s", ()),
        ("Tail: e2e p99 / tail dumps",
         [(p99["e2e"], S1, "e2e tick p99 (s)"),
          (tail_dumps, S2, "tail dumps (window)")], "", ()),
        # round 14: the fleet continuous-batching panel — batch-size
        # quantiles, resident tenants, admission rejects (see fleet_cycle)
        ("Fleet: batch size / tenants / rejects",
         [(fleet_p50, S1, "batch p50"), (fleet_p99, S2, "batch p99"),
          (fleet_tenants, S3, "tenants"),
          (fleet_rejects, S4, "rejects (window)")], "", (2,)),
        # round 16: the priority-class SLO panel — per-class request p99
        # through the real log-bucket engine + the pipelined scheduler's
        # overlap-saved rate (see fleet_slo_cycle)
        ("Fleet: class p99 / overlap saved",
         [(slo_p99["critical"], S1, "critical p99 (s)"),
          (slo_p99["standard"], S2, "standard p99 (s)"),
          (slo_p99["batch"], S3, "batch p99 (s)"),
          (slo_overlap, S4, "overlap saved ms/s")], "", (3,)),
        # round 17: the request-journey panel — per-stage p99s through the
        # real log-bucket engine (queue wait absorbs the burst, dispatch
        # stays flat) + the SLO error-budget burn rate (see journey_cycle)
        ("Fleet: journey stages (critical p99) / budget burn",
         [(stage_p99["admission"], S1, "admission (queue wait)"),
          (stage_p99["dispatch"], S2, "dispatch"),
          (stage_p99["batch_assembly"], S3, "batch_assembly"),
          (budget_burn, S4, "budget burn (x allotment)")], "", (3,)),
        # round 18: the digest-cache panel — hit fraction (= the fleet's
        # live idle fraction) + per-class hit rates (see fleet_cache_cycle)
        ("Fleet: digest cache hit rate",
         [(cache_frac, S1, "hit fraction (%)"),
          (cache_crit, S2, "critical hits/s"),
          (cache_std, S3, "standard hits/s")], "", (0,)),
        # round 18: the batched order-tail panel — tail batch size
        # quantiles + the at-most-one-per-micro-batch dispatch rate
        # (see fleet_tail_cycle)
        ("Fleet: order-tail batch size / dispatches",
         [(tail_p50, S1, "tail batch p50"),
          (tail_p99, S2, "tail batch p99"),
          (tail_rate, S3, "tail dispatches/s")], "", ()),
        # round 19: the decision-health panel — flap-watchdog fire rate by
        # klass (clustered on the wave's flanks, where deltas alternate),
        # the always-zero explain-mismatch count, and the explain-hook
        # overhead p99 through the real log-bucket engine
        # (see decision_health_cycle)
        ("Decision health: flaps / mismatches / explain overhead",
         [(flap_sign, S1, "flaps/s (delta_sign)"),
          (flap_status, S2, "flaps/s (status_churn)"),
          (prov_mism, S3, "explain mismatches"),
          (hook_p99_us, S4, "explain hook p99 (µs)")], "", (2,)),
    ]
    for i, (title, series, unit, labels) in enumerate(grid):
        x = PAD + (i % 2) * (PANEL_W + PAD)
        y = 46 + (i // 2) * (PANEL_H + PAD)
        panels.append(timeseries_panel(x, y, title, series, unit, labels))
    rows = (len(grid) + 1) // 2
    height = 46 + rows * (PANEL_H + PAD) + PAD

    svg = "\n".join([
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
        f'height="{height}" '
        f'viewBox="0 0 {W} {height}" font-family="system-ui, sans-serif">',
        f'<rect width="{W}" height="{height}" fill="#f5f4f2"/>',
        f'<text x="{PAD}" y="30" fill="{INK}" font-size="17" '
        'font-weight="700">escalator-tpu dashboard preview '
        '(synthetic scale cycle)</text>',
        *panels,
        "</svg>",
    ])
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "..", "docs", "metrics-dashboard.svg")
    with open(out, "w") as f:
        f.write(svg)
    print(f"wrote {os.path.normpath(out)} ({len(svg)} bytes)")


if __name__ == "__main__":
    main()
