"""Benchmark harness: the five BASELINE.md configs on the local accelerator.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...detail}.
Headline = config 4 (2048 nodegroups / 100k pods) scale-decision latency in ms,
vs the 50 ms target from BASELINE.json (vs_baseline > 1 means faster than target).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _rng_cluster_arrays(
    rng: np.random.Generator,
    num_groups: int,
    num_pods: int,
    num_nodes: int,
    mixed: bool = False,
    heterogeneous: bool = False,
    tainted_frac: float = 0.0,
    cordoned_frac: float = 0.0,
    now: int = 1_700_000_000,
):
    """Directly synthesize packed ClusterArrays (numpy fast path; building 100k
    Python Pod objects would only measure the object builder)."""
    from escalator_tpu.core.arrays import NO_TAINT_TIME, ClusterArrays, GroupArrays, NodeArrays, PodArrays

    G, P, N = num_groups, num_pods, num_nodes
    groups = GroupArrays(
        min_nodes=np.zeros(G, np.int32),
        max_nodes=np.full(G, 10**6, np.int32),
        taint_lower=np.full(G, 30, np.int32),
        taint_upper=np.full(G, 45, np.int32),
        scale_up_thr=np.full(G, 70, np.int32),
        slow_rate=np.ones(G, np.int32),
        fast_rate=np.full(G, 2, np.int32),
        locked=np.zeros(G, bool),
        requested_nodes=np.zeros(G, np.int32),
        cached_cpu_milli=np.full(G, 4000, np.int64),
        cached_mem_bytes=np.full(G, 16 * 10**9, np.int64),
        soft_grace_sec=np.full(G, 300, np.int64),
        hard_grace_sec=np.full(G, 900, np.int64),
        emptiest=np.zeros(G, bool),
        valid=np.ones(G, bool),
    )
    if mixed:
        pod_cpu = rng.choice([100, 250, 500, 1000, 2000], P).astype(np.int64)
        pod_mem = rng.choice([10**8, 5 * 10**8, 10**9, 4 * 10**9], P).astype(np.int64)
    else:
        pod_cpu = np.full(P, 500, np.int64)
        pod_mem = np.full(P, 10**9, np.int64)
    # group-contiguous layout, as the packer / native store emit (pods and nodes
    # are appended per group): required by the Pallas windowed-sweep fast path
    pod_group = np.sort(rng.integers(0, G, P)).astype(np.int32)
    node_group = np.sort(rng.integers(0, G, N)).astype(np.int32)
    if heterogeneous:
        node_cpu = rng.choice([2000, 4000, 8000, 16000], N).astype(np.int64)
        node_mem = rng.choice([8, 16, 32, 64], N).astype(np.int64) * 10**9
    else:
        node_cpu = np.full(N, 4000, np.int64)
        node_mem = np.full(N, 16 * 10**9, np.int64)
    tainted = rng.random(N) < tainted_frac
    cordoned = (~tainted) & (rng.random(N) < cordoned_frac)
    taint_time = np.where(
        tainted, now - rng.integers(0, 2000, N), NO_TAINT_TIME
    ).astype(np.int64)

    pods = PodArrays(
        group=pod_group,
        cpu_milli=pod_cpu,
        mem_bytes=pod_mem,
        node=rng.integers(-1, N, P).astype(np.int32),
        valid=np.ones(P, bool),
    )
    nodes = NodeArrays(
        group=node_group,
        cpu_milli=node_cpu,
        mem_bytes=node_mem,
        creation_ns=rng.integers(1, 10**15, N).astype(np.int64),
        tainted=tainted,
        cordoned=cordoned,
        no_delete=rng.random(N) < 0.02,
        taint_time_sec=taint_time,
        valid=np.ones(N, bool),
    )
    return ClusterArrays(groups=groups, pods=pods, nodes=nodes)


def _time_decide(cluster, now, iters=20, impl="xla"):
    import jax

    from escalator_tpu.ops.kernel import decide_jit

    out = decide_jit(cluster, now, impl=impl)  # compile + warm
    jax.block_until_ready(out)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = decide_jit(cluster, now, impl=impl)
        jax.block_until_ready(out)
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times))


def main() -> None:
    # probe-and-degrade: a wedged accelerator tunnel must not hang the bench
    # (shared helper — also guards the CLI; pins XLA-CPU itself on failure)
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    degraded = not ensure_responsive_accelerator()
    import jax

    from escalator_tpu.ops import kernel as _kernel  # noqa: F401 registers pytrees

    now = np.int64(1_700_000_000)
    rng = np.random.default_rng(0)
    device = jax.devices()[0]
    put = lambda c: jax.device_put(c, device)

    detail = {}
    # 1. single nodegroup, 500 pods, uniform
    detail["cfg1_1ng_500pods_ms"] = _time_decide(
        put(_rng_cluster_arrays(rng, 1, 500, 100)), now
    )
    # 2. single nodegroup, 50k pods, mixed requests
    detail["cfg2_1ng_50kpods_ms"] = _time_decide(
        put(_rng_cluster_arrays(rng, 1, 50_000, 2_000, mixed=True)), now
    )
    # 3. 64 nodegroups, heterogeneous instance types
    detail["cfg3_64ng_hetero_ms"] = _time_decide(
        put(
            _rng_cluster_arrays(rng, 64, 20_000, 5_000, mixed=True, heterogeneous=True)
        ),
        now,
    )
    # 4. HEADLINE: 2048 nodegroups, 100k pods
    headline_cluster = put(
        _rng_cluster_arrays(
            rng, 2048, 100_000, 50_000, mixed=True, heterogeneous=True,
            tainted_frac=0.1, cordoned_frac=0.02,
        )
    )
    headline = _time_decide(headline_cluster, now)
    detail["cfg4_2048ng_100kpods_ms"] = headline
    # same config through the fused Pallas aggregation sweep (ops/pallas_kernel);
    # meaningless in interpret mode, so skipped on the CPU fallback
    if not degraded:
        try:
            detail["cfg4_pallas_ms"] = _time_decide(
                headline_cluster, now, impl="pallas"
            )
        except Exception as e:  # pragma: no cover - robust to platform gaps
            detail["cfg4_pallas_error"] = str(e)
    # 5. scale-down ordering: 10k pods, heavy taint/cordon masking
    detail["cfg5_scaledown_10kpods_ms"] = _time_decide(
        put(
            _rng_cluster_arrays(
                rng, 64, 10_000, 10_000, tainted_frac=0.4, cordoned_frac=0.1
            )
        ),
        now,
    )

    # 6. native incremental path: 100k-pod store, 1% churn per tick, decide from
    # zero-copy views (the event-driven controller tick; no O(cluster) repack)
    try:
        from escalator_tpu.native.statestore import NativeStateStore

        store = NativeStateStore(pod_capacity=1 << 17, node_capacity=1 << 16)
        store.upsert_pods_batch(
            [f"p{i}" for i in range(100_000)],
            rng.integers(0, 2048, 100_000),
            np.full(100_000, 500), np.full(100_000, 10**9),
        )
        store.upsert_nodes_batch(
            [f"n{i}" for i in range(50_000)],
            rng.integers(0, 2048, 50_000),
            np.full(50_000, 4000), np.full(50_000, 16 * 10**9),
        )
        pods_v, nodes_v = store.as_pod_node_arrays()
        base = _rng_cluster_arrays(rng, 2048, 1, 1)
        from escalator_tpu.core.arrays import ClusterArrays
        from escalator_tpu.ops.device_state import DeviceClusterCache
        from escalator_tpu.ops.kernel import decide_jit

        cluster = ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v)
        store.drain_dirty()  # initial load is covered by the full upload
        cache = DeviceClusterCache(cluster, device=device)
        out = decide_jit(cache.cluster, now)
        jax.block_until_ready(out)
        # warm the scatter for the churn bucket size
        cache.apply_dirty(np.arange(1000, dtype=np.int64), np.empty(0, np.int64))
        times = []
        for t in range(10):
            churn_uids = [f"p{(t * 1000 + i) % 100_000}" for i in range(1000)]
            churn_groups = rng.integers(0, 2048, 1000)
            churn_cpu = np.full(1000, 250)
            churn_mem = np.full(1000, 10**9)
            t0 = time.perf_counter()
            store.upsert_pods_batch(  # 1% churn, one native call
                churn_uids, churn_groups, churn_cpu, churn_mem
            )
            pod_dirty, node_dirty = store.drain_dirty()
            cache.apply_dirty(pod_dirty, node_dirty)
            out = decide_jit(cache.cluster, now)
            jax.block_until_ready(out)
            times.append((time.perf_counter() - t0) * 1e3)
        detail["cfg6_native_tick_1pct_churn_ms"] = float(np.median(times))
    except Exception as e:  # pragma: no cover
        detail["cfg6_native_tick_error"] = str(e)

    target_ms = 50.0
    print(
        json.dumps(
            {
                "metric": "scale_decision_latency_2048ng_100kpods",
                "value": round(headline, 3),
                "unit": "ms",
                "vs_baseline": round(target_ms / headline, 2),
                "device": str(device)
                + (" (accelerator unreachable; CPU fallback)" if degraded else ""),
                "detail": {k: round(v, 3) for k, v in detail.items()},
            }
        )
    )


if __name__ == "__main__":
    main()
