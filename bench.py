"""Benchmark harness: BASELINE.md configs + sharded/incremental extensions.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...detail}.

HEADLINE (round-4 redefinition, per VERDICT r3 item 1): the headline ``value``
is an END-TO-END tick at the BASELINE shape (2048 nodegroups / 100k pods) —
host delta-ingest (C++ store upsert + dirty-drain) + host->device scatter +
decide — i.e. what a production tick actually costs, not just the device
kernel. ``headline_scope`` names exactly what is inside the number. When the
native store is unavailable the fallback headline is the full-upload tick
(device_put of the whole cluster + decide), also end-to-end. The kernel-only
number (rounds 1-3's headline) remains in ``detail.cfg4_kernel_only_ms``.
``vs_baseline`` = 50 ms target / headline (>1 means faster than target).

Configs:
  cfg1-cfg5   the five BASELINE.md shapes (single device, kernel-only)
  cfg4_phases transfer / aggregate / decide breakdown of the headline shape
  cfg4_e2e    full-upload end-to-end tick (device_put + decide per iteration)
  cfg6        native incremental tick (C++ store, 1% churn) with a phase
              breakdown (upsert/drain/scatter/decide), a churn sweep
              (0.1/1/10%), the full-reupload comparison it replaces, and
              the fused single-dispatch + packed-transfer variants priced
              alongside the default two-call/per-column path.
              Its store is a CONVERGED cluster: every group's utilization
              sits in the no-action band and no node is tainted, so the
              lazy-orders protocol's light decide (no node sort) is the
              steady-state path the headline measures. cfg6_drain_start
              prices the first tick of a drain episode (light + ordered
              re-dispatch, the protocol's worst case); cfg4 (10% tainted)
              prices the always-ordered busy path kernel-only
  cfg7        mesh-sharded decider, 8192 groups / 1M pods: device-count
              scaling curve 1->2->4->8 (subprocess on a virtual CPU mesh when
              the main run has a single device; see the printed confound note)
  cfg8        pod-axis sharding, one giant group with 1M pods: BUSY-tick
              (ordered, group-block-sharded tail via ops.order_tail) and
              STEADY-tick (lazy light) curves, the legacy replicated-sort
              row as before/after, and a sweep/tail phase split for both
              tail formulations (see podaxis.py for the crossover model)
  cfg9        pallas-vs-xla aggregation matrix on >=3 shapes (TPU only):
              contiguous 100k lanes, churned/interleaved store layout,
              1M-lane single group — with a computed conclusion string,
              per-row xla re-times and a cfg4 control re-time (tunnel
              sessions showed a steady-state per-program penalty on
              late-loaded programs; the diagnostics make it identifiable)
  cfg10       FFD bin-packing (ops.binpack, blocked formulation) at 2048
              groups: adversarial mixed row + compressible replicaset row,
              each with the histogram prepass's compression stats
  cfg11       what-if delta sweep (ops.simulate) at the headline shape
  cfg12       gRPC compute-plugin round-trip at the headline shape (codec +
              localhost transport + decide, the non-Python-shell price)
  cfg13       long-context stretch: native incremental tick at 1M pods /
              100k nodes / 2048 groups on ONE chip (1% churn), cfg6-style
              phase split — the measured single-chip point the v5e-8
              extrapolation in docs/performance.md anchors on
  cfg14       incremental vs full decide (round-8 tentpole: persistent
              group aggregates + dirty-group compaction) across the churn
              sweep at 100k and 1M pods, with per-tick dirty-group counts,
              bit-exact scale-delta parity per sweep point, and the
              refresh-audit cost priced alongside
  cfg16       STREAMING e2e tick (round-12 tentpole, the current headline):
              watch-delta ingestion + one-crossing packed dirty drain
              (event_drain) + delta decide at 100k and 1M pods, per-tick
              decision-digest parity vs the re-list path, per-phase columns
              from the flight recorder, and the recorded-workload replay
              row (the noise-immune before/after; also standalone via
              ``--recorded <dump> <snap>``)
  cfg17       FLEET decision service (round-14 tentpole): C=1k tenants
              (~100 pods each) through the continuous-batching scheduler —
              decisions/sec, per-tenant p50/p99 request latency, mean
              micro-batch size, per-tick 13-column bit-parity for EVERY
              tenant vs its standalone decide, and the one-dispatch-per-
              micro-batch proof from flight-recorder phase counts
  cfg18       SCALE-OUT partition sweep (round-20 tentpole): N=1 vs N=2
              fleet partition subprocesses behind the consistent-hash
              router — aggregate decisions/sec at the host-bound
              high-idle arm and the device-bound full-churn arm,
              per-class p99 per partition, core-gated >=1.5x scaling bar
              (also standalone via ``--cfg18``, which merges into the
              existing BENCH_FULL_LATEST.json)

Tail truth (round 13): every recorder-sourced per-phase column is a
p50/p99/p999/min dict (``_recorder_phase_stats``), e2e churn rows carry
``total_p99``/``total_p999``, cfg14/cfg15 decide rows carry ``*_p99_ms``/
``*_p999_ms``, and the cfg16 headline's ``within_bar`` asserts the bar
against the p99 (median kept as ``within_bar_median``). ``--smoke`` adds
the tail loop — histogram-vs-np.percentile accuracy, the tail-capture
fire path, and a ``debug-trace`` Perfetto round-trip — writing
TAIL_SMOKE_LATEST.json + TRACE_SMOKE_LATEST.trace.json for CI.

The full record is also written to BENCH_FULL_LATEST.json (named in the
stdout line) so a driver that tail-grabs stdout can never truncate the
artifact (round-4's BENCH_r04.json lost everything before cfg8 that way).
Mid-run, every completed section is flushed to a partial file
(ESCALATOR_TPU_BENCH_PARTIAL, default BENCH_PARTIAL_LATEST.json; removed on
success): the tunnel can wedge mid-bench, and a killed run's completed
sections are salvaged by tools/tpu_campaign.sh as TPU_PARTIAL_<ts>.json —
summarized into later artifacts' ``detail.tpu_partials``.

Timing notes: values are medians over N iters (min alongside) — CPU numbers on
a shared VM drift several percent between runs, which round 2 mislabelled as a
code regression (back-to-back reruns of both trees showed round-2 HEAD faster;
see CHANGELOG r3). TPU probing retries (ESCALATOR_TPU_PROBE_ATTEMPTS, default 3)
because the tunnel wedges and recovers; every attempt lands in TPU_ATTEMPTS.log.
Cross-capture spread: every TPU_BENCH_*.json in the repo root (written by
tools/tpu_campaign.sh) is summarized into ``detail.tpu_captures`` so one bench
artifact carries the evidence of independent TPU sessions.
"""

from __future__ import annotations

import itertools
import json
import os
import subprocess
import sys
import time

import numpy as np

ITERS = int(os.environ.get("ESCALATOR_TPU_BENCH_ITERS", "30"))


def _rng_cluster_arrays(
    rng: np.random.Generator,
    num_groups: int,
    num_pods: int,
    num_nodes: int,
    mixed: bool = False,
    heterogeneous: bool = False,
    tainted_frac: float = 0.0,
    cordoned_frac: float = 0.0,
    now: int = 1_700_000_000,
):
    """Directly synthesize packed ClusterArrays (numpy fast path; building 100k
    Python Pod objects would only measure the object builder)."""
    from escalator_tpu.core.arrays import NO_TAINT_TIME, ClusterArrays, GroupArrays, NodeArrays, PodArrays

    G, P, N = num_groups, num_pods, num_nodes
    groups = GroupArrays(
        min_nodes=np.zeros(G, np.int32),
        max_nodes=np.full(G, 10**6, np.int32),
        taint_lower=np.full(G, 30, np.int32),
        taint_upper=np.full(G, 45, np.int32),
        scale_up_thr=np.full(G, 70, np.int32),
        slow_rate=np.ones(G, np.int32),
        fast_rate=np.full(G, 2, np.int32),
        locked=np.zeros(G, bool),
        requested_nodes=np.zeros(G, np.int32),
        cached_cpu_milli=np.full(G, 4000, np.int64),
        cached_mem_bytes=np.full(G, 16 * 10**9, np.int64),
        soft_grace_sec=np.full(G, 300, np.int64),
        hard_grace_sec=np.full(G, 900, np.int64),
        emptiest=np.zeros(G, bool),
        valid=np.ones(G, bool),
    )
    if mixed:
        pod_cpu = rng.choice([100, 250, 500, 1000, 2000], P).astype(np.int64)
        pod_mem = rng.choice([10**8, 5 * 10**8, 10**9, 4 * 10**9], P).astype(np.int64)
    else:
        pod_cpu = np.full(P, 500, np.int64)
        pod_mem = np.full(P, 10**9, np.int64)
    # group-contiguous layout, as the packer / native store emit (pods and nodes
    # are appended per group): required by the Pallas windowed-sweep fast path
    pod_group = np.sort(rng.integers(0, G, P)).astype(np.int32)
    node_group = np.sort(rng.integers(0, G, N)).astype(np.int32)
    if heterogeneous:
        node_cpu = rng.choice([2000, 4000, 8000, 16000], N).astype(np.int64)
        node_mem = rng.choice([8, 16, 32, 64], N).astype(np.int64) * 10**9
    else:
        node_cpu = np.full(N, 4000, np.int64)
        node_mem = np.full(N, 16 * 10**9, np.int64)
    tainted = rng.random(N) < tainted_frac
    cordoned = (~tainted) & (rng.random(N) < cordoned_frac)
    taint_time = np.where(
        tainted, now - rng.integers(0, 2000, N), NO_TAINT_TIME
    ).astype(np.int64)

    pods = PodArrays(
        group=pod_group,
        cpu_milli=pod_cpu,
        mem_bytes=pod_mem,
        node=rng.integers(-1, N, P).astype(np.int32),
        valid=np.ones(P, bool),
    )
    nodes = NodeArrays(
        group=node_group,
        cpu_milli=node_cpu,
        mem_bytes=node_mem,
        creation_ns=rng.integers(1, 10**15, N).astype(np.int64),
        tainted=tainted,
        cordoned=cordoned,
        no_delete=rng.random(N) < 0.02,
        taint_time_sec=taint_time,
        valid=np.ones(N, bool),
    )
    return ClusterArrays(groups=groups, pods=pods, nodes=nodes)


def _timeit(fn, iters=ITERS):
    """(median_ms, min_ms) of fn(); fn must block on its own result."""
    fn()  # warm (compile)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append((time.perf_counter() - t0) * 1e3)
    return float(np.median(times)), float(np.min(times))


def _series_stats(values) -> dict:
    """The round-13 tail-truth column set for a millisecond series:
    p50/p99/p999/min. np.percentile (linear interpolation) is the ground
    truth the streaming log-bucket histograms are validated against in
    --smoke; the bench columns use it directly since the full series is in
    hand here."""
    arr = np.asarray(values, dtype=float)
    return {
        "p50": round(float(np.percentile(arr, 50)), 3),
        "p99": round(float(np.percentile(arr, 99)), 3),
        "p999": round(float(np.percentile(arr, 99.9)), 3),
        "min": round(float(arr.min()), 3),
    }


def _phase_stats_from_records(records) -> dict:
    """Per-phase p50/p99/p999/min across tick records (flight-recorder
    form): the ONE summarizer behind every recorder-sourced bench column —
    round 13 consolidated the two former median helpers (the recorder
    summarizer and the smoke section's inline dict) into this."""
    by_phase: dict = {}
    n = 0
    for rec in records:
        n += 1
        for p in rec["phases"]:
            if p["path"] == rec["root"]:  # the root total, reported separately
                continue
            by_phase.setdefault(p["name"], []).append(p["ms"])
    out = {k: _series_stats(v) for k, v in by_phase.items()}
    out["_ticks"] = n
    return out


def _recorder_phase_stats(root_name: str) -> dict:
    """Per-phase tail stats across the flight-recorder entries whose root is
    ``root_name`` — the bench's per-phase columns come from the SAME
    recorder production ships (not a parallel timing path), so a recorder
    regression is visible as a missing/zero bench column."""
    from escalator_tpu.observability import RECORDER

    return _phase_stats_from_records(
        [r for r in RECORDER.snapshot() if r["root"] == root_name])


def _time_decide_med_min(cluster, now, iters=ITERS, impl="xla"):
    import jax

    from escalator_tpu.ops.kernel import decide_jit

    return _timeit(
        lambda: jax.block_until_ready(decide_jit(cluster, now, impl=impl)),
        iters=iters,
    )


def _time_decide(cluster, now, iters=ITERS, impl="xla"):
    return _time_decide_med_min(cluster, now, iters=iters, impl=impl)[0]


def _phase_breakdown(host_cluster, dev_cluster, now, device) -> dict:
    """transfer (host->device), aggregate (segment sums), decide (full kernel)
    for the headline shape — the split round-1 asked for to show where the
    tick budget goes (reference cost model: the per-tick O(cluster) walks at
    pkg/k8s/util.go:27-51 have no transfer phase at all)."""
    import jax

    from escalator_tpu.ops import kernel

    G = host_cluster.groups.valid.shape[0]
    N = host_cluster.nodes.valid.shape[0]

    transfer_med, transfer_min = _timeit(
        lambda: jax.block_until_ready(jax.device_put(host_cluster, device)),
        iters=max(5, ITERS // 3),
    )

    @jax.jit
    def aggregates_only(c):
        return (
            kernel.aggregate_pods(c.pods, c.nodes.group, G, N, "xla"),
            kernel.aggregate_nodes(c.nodes, G, "xla"),
        )

    agg_med, agg_min = _timeit(
        lambda: jax.block_until_ready(aggregates_only(dev_cluster)))
    decide_med, decide_min = _timeit(
        lambda: jax.block_until_ready(kernel.decide_jit(dev_cluster, now)))
    return {
        "transfer_ms": round(transfer_med, 3),
        "aggregate_ms": round(agg_med, 3),
        "decide_total_ms": round(decide_med, 3),
        "decide_tail_ms": round(decide_med - agg_med, 3),
    }


def _cfg6_native(rng, now, device, detail: dict, degraded: bool):
    """Native incremental tick: phase breakdown + churn sweep + the
    full-reupload alternative it replaces (the O(changes) claim, measured).
    Returns the churned device-resident cluster (slot-reused, group-interleaved
    layout) so cfg9 can time pallas-vs-xla on the layout the on-device sort
    path was built for."""
    import jax

    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.ops.device_state import DeviceClusterCache
    from escalator_tpu.ops.kernel import decide_jit, native_tick_impl

    # STEADY-STATE load (round 5): balanced round-robin assignment with
    # every group's utilization inside the (taint_upper 45, scale_up 70)
    # no-action band — 48-49 pods x 1140m on 24-25 nodes x 4000m puts every
    # group at 54.7-58.2% cpu. This is what the headline always claimed to
    # measure ("incremental tick at 1% churn" = a CONVERGED cluster between
    # scaling events); the previous random 500m load averaged ~25%
    # utilization — a fleet-wide drain scenario re-decided every tick — and
    # under the lazy-orders protocol that is a different (two-dispatch)
    # program, priced separately below as cfg6_drain_start. Round-robin also
    # makes the lane layout maximally group-interleaved, preserving the
    # churned-layout story cfg9 inherits from this store.
    store = NativeStateStore(pod_capacity=1 << 17, node_capacity=1 << 16)
    store.upsert_pods_batch(
        [f"p{i}" for i in range(100_000)],
        np.arange(100_000, dtype=np.int64) % 2048,
        np.full(100_000, 1140), np.full(100_000, 10**9),
    )
    store.upsert_nodes_batch(
        [f"n{i}" for i in range(50_000)],
        np.arange(50_000, dtype=np.int64) % 2048,
        np.full(50_000, 4000), np.full(50_000, 16 * 10**9),
    )
    pods_v, nodes_v = store.as_pod_node_arrays()
    base = _rng_cluster_arrays(rng, 2048, 1, 1)
    cluster = ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v)
    store.drain_dirty()  # initial load is covered by the full upload
    cache = DeviceClusterCache(cluster, device=device)
    # same impl the native backend picks for this store (pallas on TPU —
    # the churned slot-reused layout is where the sorted MXU sweep wins)
    impl = native_tick_impl(device.platform)
    detail["cfg6_decide_impl"] = impl
    jax.block_until_ready(decide_jit(cache.cluster, now, impl=impl))

    if not degraded:
        # evidence the churned store layout still takes the MXU-sorted path
        # (slot reuse interleaves groups; the on-device sort restores windows)
        try:
            from escalator_tpu.ops import pallas_kernel as pk

            pv = store.pod_views()
            report = pk.path_report(
                np.where(pv["valid"], pv["group"], 0), pv["valid"],
                {"cpu": pv["cpu_milli"]},
            )
            detail["cfg6_pallas_path"] = report["path"]
        except Exception as e:  # pragma: no cover
            detail["cfg6_pallas_path"] = f"error: {e}"

    sweep = _native_tick_sweep(
        store, cache, impl, rng, now, num_pods=100_000, num_groups=2048,
        schedule=[("0.1pct", 100), ("1pct", 1000), ("10pct", 10_000)],
        iters=10, churn_cpu=1140, stable_groups=True, spans_root="cfg6")
    detail["cfg6_native_tick_1pct_churn_ms"] = sweep["1pct"]["total"]
    detail["cfg6_phases_1pct"] = sweep["1pct"]
    detail["cfg6_churn_sweep"] = {k: v["total"] for k, v in sweep.items()}
    # round 12 (satellite): per-phase host columns for every e2e churn row,
    # read FROM the flight recorder (the channel production ships) — the
    # host tail is attributable in the committed artifact, not only in
    # local runs with the manual perf_counter splits above
    detail["cfg6_recorder_phases_ms"] = {
        lab: _recorder_phase_stats(f"cfg6_{lab}")
        for lab in ("0.1pct", "1pct", "10pct")
    }
    # sweep rows must be comparable: the variants ran interleaved with
    # per-variant warm ticks, and an inversion (0.1% benching slower than
    # 1%) is flagged in the artifact
    detail["cfg6_churn_sweep_monotonicity"] = _sweep_monotonicity(
        detail["cfg6_churn_sweep"])
    detail["cfg6_host_ms_1pct"] = round(
        sweep["1pct"]["upsert"] + sweep["1pct"]["drain"], 3)

    # the fused single-dispatch alternative (scatter+decide in ONE device
    # program, DeviceClusterCache.apply_dirty_and_decide): the native backend
    # defaults to the two-call path on a claim of "measured faster" — keep
    # that claim measured, per capture, in the artifact
    try:
        # with_orders=False: on this steady-state store the two-call path
        # dispatches the light program every tick (the sweep above), so the
        # comparable fused figure is the light fused program
        detail["cfg6_fused_tick_1pct_ms"] = _time_fused_tick(
            store, cache, impl, rng, now, churn_cpu=1140, stable_groups=True,
            with_orders=False)
    except Exception as e:  # pragma: no cover
        detail["cfg6_fused_tick_error"] = str(e)

    # the packed-transfer alternative (delta batch as TWO byte buffers
    # instead of sixteen per-column arrays, apply_dirty_packed): per-transfer
    # latency is a transport property, so price both layouts per capture —
    # the per-column default flips only if a device capture says so
    try:
        pk_phases = _native_tick_phases(
            store, cache, impl, rng, now, num_pods=100_000, num_groups=2048,
            n_churn=1000, iters=10, packed=True, churn_cpu=1140,
            stable_groups=True)
        detail["cfg6_packed_transfer_tick_1pct_ms"] = pk_phases["total"]
        detail["cfg6_packed_transfer_scatter_ms"] = pk_phases["scatter"]
    except Exception as e:  # pragma: no cover
        detail["cfg6_packed_transfer_error"] = str(e)

    # the alternative the incremental path replaces: re-upload the whole
    # cluster every tick (the reference's O(cluster) re-walk analog)
    host_cluster = ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v)

    def full_reupload():
        dev = jax.device_put(host_cluster, device)
        jax.block_until_ready(decide_jit(dev, now))

    full_med, _ = _timeit(full_reupload, iters=10)
    detail["cfg6_full_reupload_ms"] = round(full_med, 3)

    # drain-start tick: rewrite most lanes cheap so every group falls below
    # taint_lower — the FIRST tick of a drain episode pays the lazy
    # protocol's worst case, light decide + ordered re-dispatch (ticks after
    # it see tainted nodes and dispatch once, ordered — cfg4's shape). This
    # is the scenario the pre-round-5 cfg6 store accidentally measured every
    # tick; keep it priced so the two-dispatch cost stays visible. Runs
    # LAST, after the reupload baseline read its (zero-copy!) views of the
    # steady store; the steady values are then restored through the normal
    # scatter path so cfg9 inherits the converged store on the churned
    # (slot-reused, round-robin-interleaved) layout it wants.
    try:
        store.upsert_pods_batch(
            [f"p{i}" for i in range(60_000)],
            np.arange(60_000, dtype=np.int64) % 2048,
            np.full(60_000, 100), np.full(60_000, 10**8),
        )
        drain = _native_tick_phases(
            store, cache, impl, rng, now, num_pods=100_000, num_groups=2048,
            n_churn=1000, iters=5, churn_cpu=100, stable_groups=True)
        detail["cfg6_drain_start_tick_ms"] = drain["total"]
        detail["cfg6_drain_start_decide_ms"] = drain["decide"]
    except Exception as e:  # pragma: no cover
        detail["cfg6_drain_start_error"] = str(e)
    finally:
        store.upsert_pods_batch(
            [f"p{i}" for i in range(60_000)],
            np.arange(60_000, dtype=np.int64) % 2048,
            np.full(60_000, 1140), np.full(60_000, 10**9),
        )
        pod_dirty, node_dirty = store.drain_dirty()
        cache.apply_dirty(pod_dirty, node_dirty)
        jax.block_until_ready(cache.cluster.pods.cpu_milli)
    return cache.cluster


def _native_tick_phases(store, cache, impl, rng, now, num_pods, num_groups,
                        n_churn, iters=10, packed=False,
                        churn_cpu=250, stable_groups=False) -> dict:
    """Single-variant wrapper over :func:`_native_tick_sweep` — median
    per-phase ms for one churn size (cfg13, the packed-transfer row, the
    drain row)."""
    return _native_tick_sweep(
        store, cache, impl, rng, now, num_pods, num_groups,
        [("only", n_churn)], iters=iters, packed=packed,
        churn_cpu=churn_cpu, stable_groups=stable_groups)["only"]


def _native_tick_sweep(store, cache, impl, rng, now, num_pods, num_groups,
                       schedule, iters=10, packed=False,
                       churn_cpu=250, stable_groups=False,
                       spans_root=None) -> dict:
    """Median per-phase ms (upsert/drain/scatter/decide/total) over ``iters``
    incremental ticks of pod upserts against a loaded store, for every
    ``(label, n_churn)`` variant in ``schedule`` — the one measurement
    protocol cfg6 and cfg13 both use (upserts wrap within ``num_pods``
    existing uids so the store never grows mid-timing).

    Variants run INTERLEAVED round-robin (one tick of each per round), not
    as sequential blocks: this rig's throughput drifts over a run
    (cgroup CPU shares, thermal neighbors), and sequential blocks hand the
    first variant the coldest slice — the round-9 artifact benched the
    cfg6 0.1% row 28% SLOWER than the 1% row that way. Interleaving gives
    every variant the same drift exposure, so only genuine work differences
    separate the medians (the monotonicity self-check in _cfg6_native flags
    what remains).
    ``packed=True`` routes the scatter through apply_dirty_packed (two byte
    buffers instead of sixteen per-column transfers) so captures price both
    transfer layouts.

    ``spans_root`` (round 12, satellite): when set, every timed tick also
    runs under a flight-recorder timeline ``{spans_root}_{label}`` with the
    production phase names (upsert / event_drain / scatter / decide), so
    the committed artifact's per-phase host columns come from the SAME
    recorder production ships (``_recorder_phase_stats``), not only this
    loop's manual perf_counter splits.

    The decide phase runs the SAME lazy-orders protocol the native backend
    uses (kernel.lazy_orders_decide): the gate's ``tainted_any`` is
    re-evaluated from the store view on every tick (outside the timed
    window), exactly as the backend does pre-dispatch — so a store whose
    churn taints nodes mid-loop prices the real dispatch sequence, not the
    tick-0 one (ADVICE r5). The current bench stores hold no tainted nodes,
    so a steady-state tick prices the light program + the host delta check,
    and any tick whose deltas go negative honestly pays the ordered
    re-dispatch inside its timed window."""
    import contextlib

    import jax

    from escalator_tpu.observability import spans as _spans
    from escalator_tpu.ops.kernel import decide_jit, lazy_orders_decide

    nodes_view = store.as_pod_node_arrays()[1]
    apply_fn = cache.apply_dirty_packed if packed else cache.apply_dirty
    # warm each variant's scatter-bucket program, and the light decide
    # program the lazy protocol dispatches on steady-state ticks (the full
    # program is warmed by the callers' own decide timing)
    for _, n_churn in schedule:
        apply_fn(np.arange(n_churn, dtype=np.int64), np.empty(0, np.int64))
    jax.block_until_ready(
        decide_jit(cache.cluster, now, impl=impl, with_orders=False))
    results = {lab: {"upsert": [], "drain": [], "scatter": [], "decide": [],
                     "total": []} for lab, _ in schedule}
    # round -1 is an UNTIMED full warm round (one tick per variant): the
    # first variant used to eat residual compile/warmup inside its timed
    # loop (uid-string interning, first-touch store paths, gather buffers
    # for the bucket), which made the cfg6 0.1% row bench SLOWER than 1%
    for t in range(-1, iters):
        for lab, n_churn in schedule:
            phases = results[lab]
            # the store views are live; re-read the gate per tick like the
            # backend does (cheap O(N) host mask, outside the timed window)
            tainted_any = bool(
                (np.asarray(nodes_view.tainted)
                 & np.asarray(nodes_view.valid)).any())
            idx = (t * n_churn + np.arange(n_churn)) % num_pods
            uids = [f"p{i}" for i in idx]
            # stable_groups churns a pod IN PLACE in its round-robin group
            # (cfg6's steady-state store must keep every group's pod count
            # and so its utilization band); cfg13's store sits far from any
            # threshold, so cross-group churn is harmless there
            groups = idx % num_groups if stable_groups else rng.integers(
                0, num_groups, n_churn)
            # churn at the caller's base request magnitude so a steady-state
            # store STAYS in its utilization band across the timing loop
            # (cfg6); stores far from a threshold (cfg13) keep the default
            cpu = np.full(n_churn, churn_cpu)
            mem = np.full(n_churn, 10**9)
            use_spans = bool(spans_root) and t >= 0
            sp = (_spans.span if use_spans
                  else lambda *_a, **_k: contextlib.nullcontext())
            root_ctx = (_spans.span(f"{spans_root}_{lab}") if use_spans
                        else contextlib.nullcontext())
            with root_ctx:
                t0 = time.perf_counter()
                with sp("upsert"):
                    store.upsert_pods_batch(uids, groups, cpu, mem)
                t1 = time.perf_counter()
                with sp("event_drain"):
                    pod_dirty, node_dirty = store.drain_dirty()
                t2 = time.perf_counter()
                with sp("scatter", kind="device"):
                    apply_fn(pod_dirty, node_dirty)
                    _spans.fence(jax.block_until_ready(
                        cache.cluster.pods.cpu_milli))
                t3 = time.perf_counter()
                with sp("decide", kind="device"):
                    _spans.fence(lazy_orders_decide(
                        lambda w: jax.block_until_ready(
                            decide_jit(cache.cluster, now, impl=impl,
                                       with_orders=w)),
                        tainted_any,
                    )[0])
                t4 = time.perf_counter()
            if t < 0:
                continue   # warm round: never timed
            phases["upsert"].append((t1 - t0) * 1e3)
            phases["drain"].append((t2 - t1) * 1e3)
            phases["scatter"].append((t3 - t2) * 1e3)
            phases["decide"].append((t4 - t3) * 1e3)
            phases["total"].append((t4 - t0) * 1e3)
    out = {}
    for lab, ph in results.items():
        row = {k: round(float(np.median(v)), 3) for k, v in ph.items()}
        # round 13: every e2e churn row carries its tail columns too — the
        # honest acceptance statistic per ROADMAP item 4 (a median hides a
        # scatter-bucket recompile or a GC pause; the p99/p999 don't)
        tail_stats = _series_stats(ph["total"])
        row["total_p99"] = tail_stats["p99"]
        row["total_p999"] = tail_stats["p999"]
        out[lab] = row
    return out


def _sweep_monotonicity(sweep_totals: dict) -> str:
    """Self-check for a churn sweep: total tick time must not DECREASE as
    the churn fraction grows (a smaller-churn row benching slower than a
    bigger one means warmup leaked into its timed loop, not that less work
    costs more). Keys must be ordered smallest-churn-first. Returns "ok" or
    a description of every inversion."""
    items = list(sweep_totals.items())
    bad = [
        f"{k1} ({v1} ms) > {k2} ({v2} ms)"
        for (k1, v1), (k2, v2) in zip(items, items[1:])
        if v1 > v2
    ]
    return "ok" if not bad else "INVERSION: " + "; ".join(bad)


def _time_fused_tick(store, cache, impl, rng, now, n_churn=1000,
                     iters=10, churn_cpu=250, stable_groups=False,
                     with_orders=True) -> float:
    """Median ms of the fused scatter+decide tick (ONE device dispatch via
    DeviceClusterCache.apply_dirty_and_decide) under the same churn the
    two-call phase loop measures. Upserts wrap within the store's current
    pod count so capacity never grows mid-timing. ``with_orders=False``
    prices the lazy-orders light program — the comparable figure on a
    steady-state store, where the two-call path dispatches light every
    tick."""
    import jax

    num_pods = int(np.asarray(cache.cluster.pods.valid).sum())
    groups_n = int(cache.cluster.groups.valid.shape[0])
    # (no explicit warm-up needed: _timeit's warm call compiles the fused
    # program for this bucket size before timing starts)

    tick_no = itertools.count(1)

    def fused_tick():
        idx = (next(tick_no) * n_churn + np.arange(n_churn)) % num_pods
        uids = [f"p{i}" for i in idx]
        store.upsert_pods_batch(
            uids,
            idx % groups_n if stable_groups else rng.integers(
                0, groups_n, n_churn),
            np.full(n_churn, churn_cpu), np.full(n_churn, 10**9))
        pod_dirty, node_dirty = store.drain_dirty()
        out = cache.apply_dirty_and_decide(
            pod_dirty, node_dirty, now, impl=impl, with_orders=with_orders)
        jax.block_until_ready(out)

    med, _ = _timeit(fused_tick, iters=iters)
    return round(med, 3)


def _cfg13_native_1M(rng, now, device, detail: dict, degraded: bool) -> None:
    """cfg13 (VERDICT r4 item 4): the long-context axis stretched — a native
    incremental tick at 1M pods / 100k nodes / 2048 groups on ONE chip. Same
    phase structure as cfg6 (upsert+drain+scatter+decide at 1% churn = 10k pod
    upserts/tick); the decide at this shape is the 1M-lane program cfg9 times
    kernel-only. This is the measured single-chip ceiling point the v5e-8
    extrapolation in docs/performance.md anchors on. Reference stake: the
    per-tick O(cluster) walk at pkg/k8s/util.go:27-38 scales linearly with
    pod count on the host; here only the 10k churned lanes cross PCIe.

    NOTE: the device cluster is padded to store capacity (1<<20 = 1,048,576
    pod lanes), so the decide program here is a ~1.05M-lane program at 2048
    groups — close to, but NOT the same jit program as, cfg9's exact-1M-lane
    single-group row; don't equate the two timings lane-for-lane."""
    import jax

    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.ops.device_state import DeviceClusterCache
    from escalator_tpu.ops.kernel import decide_jit, native_tick_impl

    P, N, G = 1_000_000, 100_000, 2048
    store = NativeStateStore(pod_capacity=1 << 20, node_capacity=1 << 17)
    # batch the initial load in 100k chunks (uid list construction dominates
    # otherwise; the load itself is not what cfg13 times)
    for lo in range(0, P, 100_000):
        hi = lo + 100_000
        store.upsert_pods_batch(
            [f"p{i}" for i in range(lo, hi)],
            rng.integers(0, G, hi - lo),
            np.full(hi - lo, 500), np.full(hi - lo, 10**9),
        )
    store.upsert_nodes_batch(
        [f"n{i}" for i in range(N)],
        rng.integers(0, G, N),
        np.full(N, 4000), np.full(N, 16 * 10**9),
    )
    pods_v, nodes_v = store.as_pod_node_arrays()
    base = _rng_cluster_arrays(rng, G, 1, 1)
    cluster = ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v)
    store.drain_dirty()
    cache = DeviceClusterCache(cluster, device=device)
    impl = native_tick_impl(device.platform)
    detail["cfg13_decide_impl"] = impl
    jax.block_until_ready(decide_jit(cache.cluster, now, impl=impl))

    # degraded sessions still record the field (CPU evidence that the path
    # runs) but at 3 ticks — the full 8 at 1M lanes on the 1-core host can
    # push a campaign capture past its timeout for no device signal
    med = _native_tick_phases(store, cache, impl, rng, now, num_pods=P,
                              num_groups=G, n_churn=10_000,
                              iters=3 if degraded else 8)
    detail["cfg13_native_tick_1Mpods_1pct_churn_ms"] = med["total"]
    detail["cfg13_phases_1pct"] = med


def _cfg14_incremental_vs_full(rng, now, device, detail: dict,
                               degraded: bool) -> None:
    """cfg14 (round 8): the INCREMENTAL decide (persistent device-resident
    group aggregates + dirty-group compaction, ops.device_state.
    IncrementalDecider) priced against the full-recompute decide across the
    churn sweep (0.1/1/10%) at both the BASELINE 100k-pod shape and the
    1M-pod stretch shape, recording dirty-group counts per tick. Decide
    phase ONLY (the upsert/drain/scatter phases are already O(churn),
    cfg6): per tick, the incremental path dispatches its lazy-light
    delta_decide on the compacted dirty rows while the full path re-runs
    the whole light program — same resident cluster, so scale-delta parity
    is asserted bit-exact at every sweep point (recorded, and locked at
    tiny scale by --smoke / tier-1). The acceptance bar: 0.1%-churn
    incremental decide >= 5x faster than the full decide on the same rig in
    the same session."""
    import jax

    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.ops.device_state import DeviceClusterCache, IncrementalDecider
    from escalator_tpu.ops.kernel import GROUP_DECISION_FIELDS, decide_jit

    shapes = [
        # (label, pods, nodes, groups, per-pod cpu keeping every group in
        #  the (45, 70) no-action band under round-robin, timed ticks)
        ("100k", 100_000, 50_000, 2048, 1140, 10),
        ("1M", 1_000_000, 100_000, 2048, 230, 3 if degraded else 5),
    ]
    cfg14 = {}
    for label, P, N, G, cpu_m, iters in shapes:
        store = NativeStateStore(
            pod_capacity=1 << (P - 1).bit_length(),
            node_capacity=1 << (N - 1).bit_length(),
        )
        for lo in range(0, P, 100_000):
            hi = min(P, lo + 100_000)
            store.upsert_pods_batch(
                [f"p{i}" for i in range(lo, hi)],
                np.arange(lo, hi, dtype=np.int64) % G,
                np.full(hi - lo, cpu_m), np.full(hi - lo, 10**9),
            )
        store.upsert_nodes_batch(
            [f"n{i}" for i in range(N)], np.arange(N, dtype=np.int64) % G,
            np.full(N, 4000), np.full(N, 16 * 10**9),
        )
        pods_v, nodes_v = store.as_pod_node_arrays()
        base = _rng_cluster_arrays(rng, G, 1, 1)
        store.drain_dirty()
        cache = DeviceClusterCache(
            ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v),
            device=device,
        )
        inc = IncrementalDecider(cache, refresh_every=0)
        inc.decide(now, False)   # bootstrap: full decide seeds the columns
        jax.block_until_ready(
            decide_jit(cache.cluster, now, with_orders=False))
        from escalator_tpu.observability import spans

        rows = {}
        for frac, n_churn in (("0.1pct", P // 1000), ("1pct", P // 100),
                              ("10pct", P // 10)):
            delta_ms, full_ms, dirty = [], [], []
            parity = "ok"
            root = f"cfg14_{label}_{frac}"
            for t in range(iters + 1):   # tick 0 warms the delta bucket
                idx = (t * n_churn + np.arange(n_churn)) % P
                store.upsert_pods_batch(
                    [f"p{i}" for i in idx], idx % G,
                    np.full(n_churn, cpu_m), np.full(n_churn, 10**9))
                pd, nd = store.drain_dirty()
                inc.apply_gathered(cache.gather_deltas(pd, nd))
                t0 = time.perf_counter()
                # the named root makes each timed decide a flight-recorder
                # tick; the IncrementalDecider's own delta_decide span nests
                # under it, so the per-phase columns below come from the
                # recorder, not a side timing path
                with spans.span(root):
                    out_i, _ordered = inc.decide(now, False)
                t1 = time.perf_counter()
                full = jax.block_until_ready(
                    decide_jit(cache.cluster, now, with_orders=False))
                t2 = time.perf_counter()
                if t > 0:
                    delta_ms.append((t1 - t0) * 1e3)
                    full_ms.append((t2 - t1) * 1e3)
                    dirty.append(inc.last_dirty_count)
                for f in GROUP_DECISION_FIELDS:
                    if not np.array_equal(np.asarray(getattr(out_i, f)),
                                          np.asarray(getattr(full, f))):
                        parity = f"MISMATCH: {f} at tick {t}"
            inc_med = float(np.median(delta_ms))
            full_med = float(np.median(full_ms))
            inc_tail = _series_stats(delta_ms)
            full_tail = _series_stats(full_ms)
            rows[frac] = {
                "incremental_decide_ms": round(inc_med, 3),
                "incremental_decide_p99_ms": inc_tail["p99"],
                "incremental_decide_p999_ms": inc_tail["p999"],
                "full_decide_ms": round(full_med, 3),
                "full_decide_p99_ms": full_tail["p99"],
                "full_decide_p999_ms": full_tail["p999"],
                "dirty_groups_median": int(np.median(dirty)),
                "speedup": round(full_med / inc_med, 2) if inc_med else None,
                "parity": parity,
                "recorder_phases_ms": _recorder_phase_stats(root),
            }
        # the refresh audit, priced: the O(cluster) self-check a production
        # cadence amortizes (and proof the maintained state held)
        t0 = time.perf_counter()
        audit_ok = inc.refresh()
        rows["refresh_audit_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
        rows["refresh_audit_ok"] = bool(audit_ok)
        # round 10: the audit tick priced OFF the critical path — per-tick
        # latency with the cadence firing, synchronous vs background (the
        # p99-style row: an audit tick should cost a normal tick)
        try:
            rows["background_audit"] = _background_audit_row(
                store, cache, inc, now, P, G, cpu_m,
                iters=8 if degraded else 12)
        except Exception as e:  # pragma: no cover
            rows["background_audit_error"] = str(e)
        if label == "100k":
            # observability overhead bound: the same 1%-churn steady tick
            # (scatter + delta decide) with span recording on vs off — the
            # instrumentation's acceptance bar is < 1% of the tick it
            # measures, priced here in the artifact it gates
            rows["observability_overhead"] = _observability_overhead(
                store, cache, inc, now, P, G, cpu_m,
                iters=10 if degraded else 20)
        # round 15: per-cfg HBM truth — what this shape's owners actually
        # hold on device vs their executable budgets, per sweep row
        from escalator_tpu.observability import resources as _res

        rows["resource_owners"] = {
            name: {"nbytes": r["nbytes"], "budget_bytes": r["budget_bytes"]}
            for name, r in _res.RESOURCES.snapshot().items()
            if r.get("kind") == "device" and r["nbytes"]}
        cfg14[label] = rows
        del inc, cache, store, pods_v, nodes_v
    detail["cfg14_incremental_vs_full"] = cfg14
    detail["cfg14_speedup_0p1pct_100k"] = cfg14["100k"]["0.1pct"]["speedup"]
    detail["cfg14_observability_overhead_pct"] = (
        cfg14["100k"]["observability_overhead"]["overhead_pct"])


def _cfg15_ordered_incremental(rng, now, device, detail: dict,
                               degraded: bool) -> None:
    """cfg15 (round 10): the drain-churn sweep — ORDERED ticks priced with
    the incremental order path (persistent per-lane sort keys + the last
    permutation, repaired by a dirty-lane rank merge, ops.order_tail)
    against (a) the full-sort ordered decide it replaces and (b) the
    incremental LIGHT tick, at the BASELINE 100k pods / 50k nodes / 2048
    groups shape. Each ordered tick flips taints on a rotating node subset
    (the drain-churn that keeps every tick ordered) plus 0.1% pod churn;
    parity vs the full ordered ``decide_jit`` is asserted BIT-EXACT on
    every field of every tick (the full sort runs there — tainted lanes
    exist — so even the order arrays compare whole, not just windows).
    The ISSUE-5 bar: incremental ordered decide <= 2x the light decide."""
    import jax

    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.ops.device_state import DeviceClusterCache, IncrementalDecider
    from escalator_tpu.ops.kernel import decide_jit

    P, N, G = 100_000, 50_000, 2048
    iters = 8 if degraded else 12
    n_churn = P // 1000          # 0.1% pod churn per tick
    n_taint = 128                # rotating taint churn: the ordered driver
    store = NativeStateStore(pod_capacity=1 << 17, node_capacity=1 << 16)
    for lo in range(0, P, 100_000):
        hi = min(P, lo + 100_000)
        store.upsert_pods_batch(
            [f"p{i}" for i in range(lo, hi)],
            np.arange(lo, hi, dtype=np.int64) % G,
            np.full(hi - lo, 1140), np.full(hi - lo, 10**9),
        )
    store.upsert_nodes_batch(
        [f"n{i}" for i in range(N)], np.arange(N, dtype=np.int64) % G,
        np.full(N, 4000), np.full(N, 16 * 10**9),
        creation_ns=rng.integers(1, 10**15, N),
    )
    pods_v, nodes_v = store.as_pod_node_arrays()
    base = _rng_cluster_arrays(rng, G, 1, 1)
    store.drain_dirty()
    cache = DeviceClusterCache(
        ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v),
        device=device,
    )
    inc = IncrementalDecider(cache, refresh_every=0)
    inc.decide(now, False)       # bootstrap: seeds the decision columns

    def churn_pods(t):
        idx = (t * n_churn + np.arange(n_churn)) % P
        store.upsert_pods_batch([f"p{i}" for i in idx], idx % G,
                                np.full(n_churn, 1140),
                                np.full(n_churn, 10**9))

    def flip_taints(t):
        # taint a fresh window, untaint the previous one: ~2*n_taint lanes
        # change their sort keys per tick — a rolling drain
        new = (t * n_taint + np.arange(n_taint)) % N
        old = ((t - 1) * n_taint + np.arange(n_taint)) % N
        clear = np.setdiff1d(old, new)
        store.upsert_nodes_batch(
            [f"n{i}" for i in new], new % G,
            np.full(n_taint, 4000), np.full(n_taint, 16 * 10**9),
            creation_ns=creation[new], tainted=np.ones(n_taint, bool),
            taint_time_sec=np.full(n_taint, int(now) - 100),
        )
        if clear.size:
            store.upsert_nodes_batch(
                [f"n{i}" for i in clear], clear % G,
                np.full(clear.size, 4000), np.full(clear.size, 16 * 10**9),
                creation_ns=creation[clear],
            )

    creation = np.asarray(store.as_pod_node_arrays()[1].creation_ns)[:N].copy()

    def apply_store_deltas():
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd))

    # ---- phase A: LIGHT ticks (no taints anywhere) — the 2x bar's base ----
    light_ms = []
    for t in range(iters + 1):
        churn_pods(t)
        apply_store_deltas()
        t0 = time.perf_counter()
        out, ordered = inc.decide(now, False)
        if t > 0:
            light_ms.append((time.perf_counter() - t0) * 1e3)
        assert not ordered, "cfg15 light phase unexpectedly ordered"

    # full light decide, for scale (the pre-incremental steady tick)
    full_light_med, _ = _timeit(
        lambda: jax.block_until_ready(
            decide_jit(cache.cluster, now, with_orders=False)),
        iters=max(5, iters // 2))

    # ---- phase B: ORDERED ticks under rolling taint churn ------------------
    # The rolling taint windows drift the dirty-GROUP count across a
    # power-of-two bucket boundary every few ticks, and a bucket's first
    # tick pays a delta_decide/order_repair compile (~1-2 s on this CPU) —
    # steady-state medians must not eat those, so ticks that compiled are
    # excluded (counted in `compile_contaminated_ticks`), exactly the
    # flight recorder's per-tick compile_events signal.
    from escalator_tpu.observability import jaxmon

    jaxmon.install()
    inc_ms, full_ms, dirty_lanes = [], [], []
    contaminated = 0
    parity = "ok"
    for t in range(iters + 2):    # ticks 0-1 warm the repair programs
        churn_pods(1000 + t)
        flip_taints(t)
        apply_store_deltas()
        c0 = jaxmon.snapshot()["compile_events"]
        t0 = time.perf_counter()
        out, ordered = inc.decide(now, True)
        t1 = time.perf_counter()
        assert ordered, "cfg15 ordered phase ran light"
        full = jax.block_until_ready(decide_jit(cache.cluster, now))
        t2 = time.perf_counter()
        if t >= 2:
            if jaxmon.snapshot()["compile_events"] > c0:
                contaminated += 1
            else:
                inc_ms.append((t1 - t0) * 1e3)
                full_ms.append((t2 - t1) * 1e3)
                dirty_lanes.append(inc.last_order_dirty_count)
        for f in out.__dataclass_fields__:
            if not np.array_equal(np.asarray(getattr(out, f)),
                                  np.asarray(getattr(full, f))):
                parity = f"MISMATCH: {f} at tick {t}"
    if not inc_ms:   # every tick compiled (pathological): report them all
        inc_ms = full_ms = [float("nan")]
        dirty_lanes = [0]
    inc_med = float(np.median(inc_ms))
    full_med = float(np.median(full_ms))
    light_med = float(np.median(light_ms))
    inc_tail15 = _series_stats(inc_ms)
    detail["cfg15_ordered_incremental"] = {
        "ordered_incremental_ms": round(inc_med, 3),
        "ordered_incremental_p99_ms": inc_tail15["p99"],
        "ordered_incremental_p999_ms": inc_tail15["p999"],
        "ordered_full_sort_ms": round(full_med, 3),
        "light_incremental_ms": round(light_med, 3),
        "full_light_decide_ms": round(full_light_med, 3),
        "ordered_over_light": round(inc_med / light_med, 2) if light_med else None,
        "ordered_full_over_light": round(full_med / light_med, 2)
        if light_med else None,
        "speedup_vs_full_sort": round(full_med / inc_med, 2) if inc_med else None,
        "order_dirty_lanes_median": int(np.median(dirty_lanes)),
        "order_paths": dict(inc.order_stats),
        "compile_contaminated_ticks": contaminated,
        "timed_ticks": len(inc_ms),
        "parity": parity,
    }
    detail["cfg15_ordered_over_light"] = (
        detail["cfg15_ordered_incremental"]["ordered_over_light"])
    del inc, cache, store, pods_v, nodes_v


def _recorded_workload_bench(entries, leaves, meta, passes=3) -> dict:
    """The PR-6 'refactor bonus', claimed (round 12): a NOISE-IMMUNE perf
    harness that replays a recorded ``TickInputLog`` ring
    (observability/replay.py) through the real backend stack. Every pass
    restores the decider from the same snapshot and re-executes the same
    byte-exact ``(idx, values)`` batches — so two code versions replaying
    the same bundle differ only by code, never by workload generation or
    churn randomness. Times TWO arms per tick on identical state: the
    incremental ``delta_decide`` path (after) and the full light recompute
    it replaced (before), asserting the recorded digests still reproduce.
    Medians are over all ticks x passes; the min is the stall-resistant
    estimate (cfg9 convention)."""
    import jax

    from escalator_tpu.observability import replay as replaymod
    from escalator_tpu.ops import device_state as ds
    from escalator_tpu.ops.kernel import decide_jit

    base_tick = int(meta.get("tick", 0))
    todo = sorted((e for e in entries if int(e["tick"]) > base_tick),
                  key=lambda e: int(e["tick"]))
    decoded = [[replaymod.decode_batch(enc) for enc in e.get("batches", ())]
               for e in todo]
    delta_ms, full_ms = [], []
    digests_ok = True
    for pass_no in range(passes + 1):   # pass 0 warms every program, untimed
        warm = pass_no == 0
        _cache, inc = ds.restore_decider(
            leaves, meta, refresh_every=0, background=False,
            post_restore_audit=False)
        for e, batches in zip(todo, decoded, strict=True):
            for gathered, groups in batches:
                inc.apply_gathered(gathered, groups)
            t0 = time.perf_counter()
            out, _ordered = inc.decide(
                int(e["now_sec"]), bool(e["tainted_any"]), _record=False)
            t1 = time.perf_counter()
            full = jax.block_until_ready(decide_jit(
                _cache.cluster, np.int64(e["now_sec"]), with_orders=False))
            t2 = time.perf_counter()
            if replaymod.decision_digest(out) != e.get("digest"):
                digests_ok = False
            if replaymod.decision_digest(full) != e.get("digest"):
                digests_ok = False
            if not warm:
                delta_ms.append((t1 - t0) * 1e3)
                full_ms.append((t2 - t1) * 1e3)
    d_med = float(np.median(delta_ms)) if delta_ms else float("nan")
    f_med = float(np.median(full_ms)) if full_ms else float("nan")
    return {
        "recorded_ticks": len(todo),
        "passes": passes,
        "delta_decide_ms": round(d_med, 3),
        "delta_decide_min_ms": round(float(np.min(delta_ms)), 3)
        if delta_ms else None,
        "full_decide_ms": round(f_med, 3),
        "full_decide_min_ms": round(float(np.min(full_ms)), 3)
        if full_ms else None,
        "speedup": round(f_med / d_med, 2) if d_med else None,
        "digest_parity": "ok" if digests_ok else "DIGEST MISMATCH",
    }


def run_recorded(dump_path: str, snapshot_path: str, passes: int = 5) -> dict:
    """``python bench.py --recorded <flight-dump.json> <state.snap>``: the
    recorded-workload bench over an ARBITRARY replay bundle (any flight
    dump whose ``tick_inputs`` ring was recorded after the snapshot —
    exactly what ``escalator-tpu debug-replay`` consumes, but timed).
    Use to price a code change on a captured production workload without
    workload-generation noise."""
    import json as _json

    from escalator_tpu.ops import snapshot as snaplib

    with open(dump_path) as f:
        doc = _json.load(f)
    entries = doc.get("tick_inputs") or []
    if not entries:
        raise SystemExit(f"{dump_path} carries no tick_inputs ring "
                         "(record with ESCALATOR_TPU_RECORD_INPUTS=1)")
    leaves, meta = snaplib.read_snapshot(snapshot_path)
    out = {"recorded_bench": True, "dump": dump_path,
           "snapshot": snapshot_path}
    out.update(_recorded_workload_bench(entries, leaves, meta, passes=passes))
    return out


def _cfg16_streaming(rng, now, device, detail: dict, degraded: bool) -> None:
    """cfg16 (round-12 tentpole): the STREAMING e2e tick — the number the
    headline now reports. Watch-delta ingestion (store batch upsert standing
    in for the watch thread), ONE-crossing packed dirty drain
    (``event_drain``: statestore.drain_dirty_packed — drain + per-column
    gather + bucket pad in a single native call, vectorized numpy on the
    fallback store), the [G]/[N] host assembly (``triple_build``: here the
    lazy-orders gate mask; the group-row repack is priced in the backend
    path, cfg6 recorder columns), the aggregate-maintaining scatter, and
    the dirty-group-compacted ``delta_decide`` — at the BASELINE 100k-pod
    shape and the 1M stretch shape.

    Parity: every tick's decision digest (and status/delta columns) are
    asserted bit-exact against the RE-LIST path — a fresh full upload of
    the store's world + the full light recompute, i.e. what a tick that
    re-listed and re-packed everything would have decided. (Object-level
    ingestion parity — WatchBridge vs filtered listers over a live client —
    is locked at smoke/test scale, bench.py --smoke and
    tests/test_event_ingest_parity.py, where building 10^6 Python objects
    isn't the bottleneck being measured.)

    Acceptance bars (ISSUE 7): steady e2e tick <= 25 ms at 100k pods /
    2048 groups, <= 100 ms at 1M, on the CPU rig. Also claims the PR-6
    refactor bonus: the 100k shape's recorded-workload replay row
    (``_recorded_workload_bench``) is the noise-immune before/after."""
    import jax

    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import make_state_store, store_kind
    from escalator_tpu.observability import replay as replaymod
    from escalator_tpu.observability import spans
    from escalator_tpu.ops.device_state import DeviceClusterCache, IncrementalDecider
    from escalator_tpu.ops.kernel import decide_jit

    # 100 timed ticks at the headline shape (round 13): the row's bar is now
    # asserted on the p99, and a p99 over ~12 samples IS the max — one
    # stolen-core burst on this shared rig (observed: a single 94 ms tick in
    # an 18 ms steady run) would bust the bar with no code regression.
    # n=100 puts p99 at the 2nd-worst tick, tolerating exactly one outlier;
    # p999 still reports the true max. The 1M stretch row keeps few iters
    # (each parity arm re-uploads 1M pods); its p99~max caveat is noted in
    # docs/performance.md.
    shapes = [
        ("100k", 100_000, 50_000, 2048, 1140, 100, 25.0),
        ("1M", 1_000_000, 100_000, 2048, 230, 3 if degraded else 6, 100.0),
    ]
    cfg16 = {}
    for label, P, N, G, cpu_m, iters, bar_ms in shapes:
        store = make_state_store(
            pod_capacity=1 << (P - 1).bit_length(),
            node_capacity=1 << (N - 1).bit_length(),
        )
        for lo in range(0, P, 100_000):
            hi = min(P, lo + 100_000)
            store.upsert_pods_batch(
                [f"p{i}" for i in range(lo, hi)],
                np.arange(lo, hi, dtype=np.int64) % G,
                np.full(hi - lo, cpu_m), np.full(hi - lo, 10**9),
            )
        store.upsert_nodes_batch(
            [f"n{i}" for i in range(N)], np.arange(N, dtype=np.int64) % G,
            np.full(N, 4000), np.full(N, 16 * 10**9),
        )
        pods_v, nodes_v = store.as_pod_node_arrays()
        base = _rng_cluster_arrays(rng, G, 1, 1)
        host_cluster = ClusterArrays(groups=base.groups, pods=pods_v,
                                     nodes=nodes_v)
        store.drain_dirty()
        cache = DeviceClusterCache(host_cluster, device=device)
        inc = IncrementalDecider(cache, refresh_every=0)
        inc.decide(now, False)      # bootstrap: seeds the decision columns
        # warm the re-list parity arm's program (full light decide)
        jax.block_until_ready(
            decide_jit(cache.cluster, now, with_orders=False))
        n_churn = P // 100
        root = f"cfg16_{label}"
        nodes_valid = np.asarray(nodes_v.valid)
        nodes_tainted = np.asarray(nodes_v.tainted)
        totals = []
        parity = "ok"
        import contextlib

        for t in range(iters + 2):   # ticks 0-1 warm drain bucket + scatter
            idx = (t * n_churn + np.arange(n_churn)) % P
            uids = [f"p{i}" for i in idx]
            groups_rr = idx % G
            cpu = np.full(n_churn, cpu_m)
            mem = np.full(n_churn, 10**9)
            # warm ticks (0-1, compile-contaminated) stay OUT of the
            # recorder: the row's recorder_phases_ms must decompose the
            # same tick population e2e_tick_ms medians over
            timed = t >= 2
            sp = (spans.span if timed
                  else lambda *_a, **_k: contextlib.nullcontext())
            root_ctx = (spans.span(root) if timed
                        else contextlib.nullcontext())
            t0 = time.perf_counter()
            with root_ctx:
                with sp("upsert"):
                    store.upsert_pods_batch(uids, groups_rr, cpu, mem)
                with sp("event_drain"):
                    gathered = store.drain_dirty_packed()
                with sp("triple_build"):
                    tainted_any = bool(
                        (nodes_valid & nodes_tainted).any())
                with sp("scatter", kind="device"):
                    # dispatch-only, as in the backend: the delta decide's
                    # fence absorbs the scatter tail
                    inc.apply_gathered(gathered)
                out_i, _ordered = inc.decide(now, tainted_any)
            total_ms = (time.perf_counter() - t0) * 1e3
            # re-list parity arm, OUTSIDE the timed window: full upload of
            # the store's world + full light recompute = what a re-listing
            # tick would have decided
            full = jax.block_until_ready(decide_jit(
                jax.device_put(host_cluster, device), now,
                with_orders=False))
            if (replaymod.decision_digest(out_i)
                    != replaymod.decision_digest(full)):
                parity = f"DIGEST MISMATCH at tick {t}"
            for f in ("status", "nodes_delta"):
                if not np.array_equal(np.asarray(getattr(out_i, f)),
                                      np.asarray(getattr(full, f))):
                    parity = f"MISMATCH: {f} at tick {t}"
            if t >= 2:
                totals.append(total_ms)
        med = float(np.median(totals))
        tick_tail = _series_stats(totals)
        row = {
            "e2e_tick_ms": round(med, 3),
            "e2e_tick_min_ms": round(float(np.min(totals)), 3),
            # round 13 (ROADMAP item 4): the HEADLINE bar is asserted
            # against the p99, not the median — an SLO is a tail statement.
            # within_bar_median is kept alongside so regressions in either
            # statistic stay attributable.
            "e2e_tick_p99_ms": tick_tail["p99"],
            "e2e_tick_p999_ms": tick_tail["p999"],
            "churned_pods_per_tick": n_churn,
            "store": store_kind(store),
            "digest_parity_vs_relist": parity,
            "bar_ms": bar_ms,
            "within_bar": bool(tick_tail["p99"] <= bar_ms),
            "within_bar_median": bool(med <= bar_ms),
            "recorder_phases_ms": _recorder_phase_stats(root),
        }
        if label == "100k":
            # recorded-workload replay bench (satellite: the PR-6 bonus):
            # snapshot, record 6 streaming ticks, replay the ring through
            # both decide arms — the noise-immune before/after for this PR
            try:
                leaves, meta = inc.snapshot_state()
                replaymod.INPUT_LOG.clear()
                replaymod.INPUT_LOG.set_enabled(True)
                try:
                    for t in range(1000, 1006):
                        idx = (t * n_churn + np.arange(n_churn)) % P
                        store.upsert_pods_batch(
                            [f"p{i}" for i in idx], idx % G,
                            np.full(n_churn, cpu_m), np.full(n_churn, 10**9))
                        pd, nd = store.drain_dirty()
                        inc.apply_gathered(cache.gather_deltas(pd, nd))
                        inc.decide(now, False)
                    entries = replaymod.INPUT_LOG.snapshot()
                finally:
                    replaymod.INPUT_LOG.set_enabled(False)
                    replaymod.INPUT_LOG.clear()
                row["recorded_replay"] = _recorded_workload_bench(
                    entries, leaves, meta, passes=2 if degraded else 3)
            except Exception as e:  # pragma: no cover
                row["recorded_replay_error"] = str(e)
        # assign per shape, not after both: a failure at the 1M stretch
        # shape (e.g. store allocation on a constrained rig) must not
        # discard the finished 100k row — the headline's source
        cfg16[label] = row
        detail["cfg16_streaming"] = cfg16
        detail[f"cfg16_streaming_tick_{label}_1pct_ms"] = row["e2e_tick_ms"]
        detail[f"cfg16_streaming_tick_{label}_1pct_p99_ms"] = (
            row["e2e_tick_p99_ms"])
        del inc, cache, store, pods_v, nodes_v, host_cluster


#: cfg17 priority-class bars (ms): the declared per-class p99 targets at the
#: C=10k drain model on this rig — critical drains first (weight 4), batch
#: is best-effort (no bar). Breaches also count the Prometheus counter.
_CFG17_CLASS_BARS = {"critical": 4000.0, "standard": 15000.0, "batch": None}


def _cfg17_fleet(rng, now, device, detail: dict, degraded: bool) -> None:
    """cfg17 (round-14 tentpole, round-16 rewrite): the FLEET decision
    service at C=10k tenants (~100 pods each, 4 groups, 20 nodes) through
    the real pipelined continuous-batching scheduler, swept over the mesh
    shard count (1/2/4[/8] forced host devices). Per shard count the tick
    is the saturated DRAIN MODEL: all C requests enqueue against a paused
    scheduler, one resume drains them — decisions/sec is the drain rate
    and per-request latency includes real queue wait (so at saturation the
    p99 approaches the full drain window; that IS the service's number at
    this offered load). Reports per-class (critical/standard/batch)
    p50/p99 against the declared bars, an overlap on/off A-B pair at the
    widest mesh, 13-column bit-parity on a 64-tenant random sample per
    timed tick (the EVERY-tenant-every-tick contract lives in the
    tests/test_fleet.py soak — 10k standalone reference decides per tick
    would dwarf the bench), and the one-dispatch-per-micro-batch proof
    from flight-recorder phase counts. Round 18: the timed drain ships
    STREAMING DELTA frames (each resident tenant's positional churn, the
    production shape after fleet streaming ingestion), the ordered tail
    is proven at-most-one batched dispatch per micro-batch, and an
    idle-fraction sweep measures the digest fast path's decisions/sec and
    cache-hit rate. NOTE on this rig: with few physical
    cores the host prep dominates wall clock, so decisions/sec stays
    ~flat across shard counts — the honest per-device signal is the
    fleet_step device-phase shrink (each shard executes C/S tenants)."""
    import threading

    from escalator_tpu.fleet import (
        DecideRequest,
        DeltaFrame,
        FleetEngine,
        FleetScheduler,
        PriorityClass,
    )
    from escalator_tpu.fleet import service as _fsvc
    from escalator_tpu.observability import RECORDER
    from escalator_tpu.ops import kernel as _k
    import jax

    C, Gt, Pt, Nt = 10_000, 4, 100, 20
    # 3 timed ticks x C per-request latency samples: the per-tenant/class
    # p99 columns aggregate 30k samples (stable to well under a bucket
    # width); tick-wall medians remain 3-sample (the honest knob on a rig
    # where one more tick costs ~8 s x 5 sweep arms)
    timed_ticks = 3
    parity_sample = 64
    classes = tuple(
        PriorityClass(name, weight=w, queue_share=share, p99_target_ms=bar)
        for name, w, share, bar in (
            ("critical", 4, 1.0, _CFG17_CLASS_BARS["critical"]),
            ("standard", 2, 1.0, _CFG17_CLASS_BARS["standard"]),
            ("batch", 1, 1.0, _CFG17_CLASS_BARS["batch"]),
        ))
    # tenant -> class: 10% critical, 60% standard, 30% batch (deterministic)
    def klass_of(t: int) -> str:
        m = t % 10
        return "critical" if m == 0 else ("batch" if m >= 7 else "standard")

    # a mostly-HEALTHY fleet: steady tenants have scale-down disabled
    # (taint thresholds 0 — utilization sits between the thresholds, so
    # decisions are 0/positive deltas and the light one-dispatch path
    # serves them), while 2% are DRAINING (tainted nodes + live scale-down
    # thresholds) and pay the per-tenant ordered follow-up — the
    # production shape: drains are rare, batches stay one dispatch
    bases = []
    for t in range(C):
        draining = t % 50 == 0
        c = _rng_cluster_arrays(
            np.random.default_rng(1000 + t), Gt, Pt, Nt,
            tainted_frac=0.3 if draining else 0.0)
        if not draining:
            c.groups.taint_lower[:] = 0
            c.groups.taint_upper[:] = 0
        bases.append(c)

    def fresh(t, tick):
        b = bases[t]
        copy = lambda soa: type(soa)(  # noqa: E731
            **{f: np.array(getattr(soa, f))
               for f in soa.__dataclass_fields__})
        c = type(b)(groups=copy(b.groups), pods=copy(b.pods),
                    nodes=copy(b.nodes))
        if tick:
            # ~1% churn per tenant per tick
            c.pods.cpu_milli[(tick * 7) % Pt] += 10 * tick
        return c

    # round 18: the timed drain ships STREAMING DELTA frames for resident
    # tenants — the production shape after fleet streaming ingestion — so
    # the per-request host cost is O(churn), not O(P+N) diff. Bootstrap
    # (and every tenant's first request per engine arm) stays a full
    # frame. Delta construction is the CLIENT's cost (the controller's
    # store twin drains it incrementally in production) and happens before
    # the timed window opens, like the cluster builds themselves.
    prev_clusters: dict = {}

    def _delta_of(prev, new):
        def take(soa, idx):
            return type(soa)(**{
                f: np.asarray(getattr(soa, f))[idx]
                for f in soa.__dataclass_fields__})
        pidx = _fsvc._changed_rows(prev.pods, new.pods).astype(np.int32)
        nidx = _fsvc._changed_rows(prev.nodes, new.nodes).astype(np.int32)
        gchanged = len(_fsvc._changed_rows(prev.groups, new.groups)) > 0
        return DeltaFrame(
            shapes=(Gt, Pt, Nt),
            pod_idx=pidx, pod_vals=take(new.pods, pidx),
            node_idx=nidx, node_vals=take(new.nodes, nidx),
            groups=new.groups if gchanged else None)

    def run_tick(sched, tick, timed: bool, prng):
        nowi = int(now) + 60 * tick
        clusters = [fresh(t, tick) for t in range(C)]
        deltas = [None] * C
        for t in range(C):
            pv = prev_clusters.get(t)
            if pv is not None:
                deltas[t] = _delta_of(pv, clusters[t])
        lat = [None] * C
        done = threading.Event()
        remaining = [C]
        lock = threading.Lock()
        t0 = time.perf_counter()

        def make_cb(t, t_sub):
            def cb(_fut):
                lat[t] = time.perf_counter() - t_sub
                with lock:
                    remaining[0] -= 1
                    if not remaining[0]:
                        done.set()
            return cb

        sched.pause()
        futs = []
        for t in range(C):
            t_sub = time.perf_counter()
            if deltas[t] is not None:
                f = sched.submit(f"tenant{t}", None, nowi,
                                 klass=klass_of(t), delta=deltas[t])
            else:
                f = sched.submit(f"tenant{t}", clusters[t], nowi,
                                 klass=klass_of(t))
            f.add_done_callback(make_cb(t, t_sub))
            futs.append(f)
        sched.resume()
        assert done.wait(timeout=1200), "fleet tick did not complete"
        wall = time.perf_counter() - t0
        results = [f.result() for f in futs]
        for t in range(C):
            prev_clusters[t] = clusters[t]
        if timed:
            # 13-column bit-parity on a random tenant sample, this tick
            for t in prng.choice(C, size=parity_sample, replace=False):
                ref = _k.decide_jit(jax.device_put(clusters[t]),
                                    np.int64(nowi))
                for fld in _k.GROUP_DECISION_FIELDS:
                    got = np.asarray(getattr(results[t].arrays, fld))
                    want = np.asarray(getattr(ref, fld))
                    assert np.array_equal(got, want), (
                        f"cfg17 parity: tick {tick} tenant {t} {fld}")
        return wall, lat, results

    def measure(engine, sched, first_tick, prng):
        """Warm (bootstrap happened outside), then run the timed drain
        ticks; returns (row, next_tick)."""
        walls, lats, batch_sizes = [], [], []
        served = 0
        timed_recs = []
        prep_recs = []
        last_seq = RECORDER.total_recorded
        tick = first_tick
        for i in range(timed_ticks):
            wall, lat, results = run_tick(sched, tick, timed=True, prng=prng)
            tick += 1
            walls.append(wall)
            lats.extend(lat)
            batch_sizes.extend(r.batch_size for r in results)
            served += len(results)
            # harvest this tick's batch records NOW: the 256-record ring
            # can evict a whole tick's worth across the full timed window
            # (fleet_prep is its OWN root — prepare runs on the PREP
            # thread, outside any fleet_batch root)
            fresh_recs = [r for r in RECORDER.snapshot()
                          if r.get("seq", 0) > last_seq]
            timed_recs.extend(
                r for r in fresh_recs if r["root"] == "fleet_batch")
            prep_recs.extend(
                r for r in fresh_recs if r["root"] == "fleet_prep")
            last_seq = RECORDER.total_recorded
        # one-dispatch proof: every fleet_batch record in the timed window
        # carries exactly ONE fleet_step device phase
        steps_per_batch = [
            sum(1 for p in r["phases"] if p["name"] == "fleet_step")
            for r in timed_recs]
        assert steps_per_batch and all(s == 1 for s in steps_per_batch), (
            f"cfg17: fleet_step phases per batch {set(steps_per_batch)}")
        # round 18: the ordered tail is AT MOST ONE batched dispatch per
        # micro-batch (every draining tenant rides it), never a per-tenant
        # re-dispatch train
        tails_per_batch = [
            sum(1 for p in r["phases"] if p["name"] == "fleet_order_tail")
            for r in timed_recs]
        assert all(c <= 1 for c in tails_per_batch), (
            f"cfg17: fleet_order_tail phases per batch "
            f"{set(tails_per_batch)}")
        lat_ms = np.array(lats) * 1e3
        overlap_host = [r.get("overlap_host_ms") for r in timed_recs
                        if r.get("overlap_host_ms") is not None]
        overlap_saved = [r.get("overlap_saved_ms") for r in timed_recs
                         if r.get("overlap_saved_ms") is not None]
        row = {
            "decisions_per_sec": round(served / sum(walls), 1),
            "tick_wall_ms": round(float(np.median(walls)) * 1e3, 3),
            "per_tenant_p50_ms": round(float(np.percentile(lat_ms, 50)), 3),
            "per_tenant_p99_ms": round(float(np.percentile(lat_ms, 99)), 3),
            "mean_batch_size": round(float(np.mean(batch_sizes)), 1),
            "batches_observed": len(timed_recs),
            "one_dispatch_per_batch": True,
            "parity_sampled": parity_sample * timed_ticks,
            # timed records only: the ring also holds the warm ticks, whose
            # fleet_step phases carry the one-time compiles
            "fleet_step_ms": _phase_stats_from_records(timed_recs).get(
                "fleet_step"),
            # recorder-sourced per-phase columns, full pipeline decomposed:
            # batch_assembly = the fleet_prep root (diff + twin adoption +
            # operand assembly, on the PREP thread), host_diff = its
            # fleet_diff sub-phase, unpack = result repack on the dispatch
            # thread. fleet_step above is the fused device program.
            "batch_assembly_ms": _series_stats(
                [r["duration_ms"] for r in prep_recs]) if prep_recs
            else None,
            "host_diff_ms": _phase_stats_from_records(prep_recs).get(
                "fleet_diff"),
            "unpack_ms": _phase_stats_from_records(timed_recs).get(
                "fleet_unpack"),
            "order_tail_ms": _phase_stats_from_records(timed_recs).get(
                "fleet_order_tail"),
            "order_tail_dispatches_per_batch_max": (
                max(tails_per_batch) if tails_per_batch else 0),
            "streamed_delta_requests": True,
            # recorder-proven pipeline overlap: prep wall per batch, and
            # how much of it ran under an in-flight device program
            "overlap_host_ms_total": round(float(np.sum(overlap_host)), 1),
            "overlap_saved_ms_total": round(float(np.sum(overlap_saved)), 1),
        }
        per_class = {}
        # [timed_ticks, C]: tenant t's samples sit at column t of every
        # timed tick — the class columns aggregate ALL ticks' samples
        lat_by_tick = lat_ms.reshape(timed_ticks, C)
        for name in ("critical", "standard", "batch"):
            mask = np.array([klass_of(t) == name for t in range(C)])
            cls_lat = lat_by_tick[:, mask].ravel()
            bar = _CFG17_CLASS_BARS[name]
            p99 = float(np.percentile(cls_lat, 99))
            per_class[name] = {
                "p50_ms": round(float(np.percentile(cls_lat, 50)), 3),
                "p99_ms": round(p99, 3),
                "p99_bar_ms": bar,
                "within_bar": (True if bar is None else bool(p99 <= bar)),
                "breaches": sched.class_breaches[name],
            }
        row["classes"] = per_class
        return row, tick

    # ---- the shard sweep: 1/2/4(/8) mesh shards over the forced host
    # devices, each arm its own engine (arenas are per-mesh) --------------
    n_dev = len(jax.devices())
    shard_counts = [s for s in (1, 2, 4, 8) if s <= n_dev]
    sweep = {}
    headline = None
    for S in shard_counts:
        prng = np.random.default_rng(170 + S)
        prev_clusters.clear()  # fresh engine arm: first frames are full
        engine = FleetEngine(num_groups=Gt, pod_capacity=128,
                             node_capacity=32, max_tenants=C, num_shards=S)
        sched = FleetScheduler(engine, max_batch=128, flush_ms=5.0,
                               queue_limit=4 * C, per_tenant_inflight=2,
                               classes=classes, default_class="standard",
                               pipeline=True)
        try:
            # bootstrap (full-lane buckets) + one churn warm tick (steady
            # 64-lane buckets): the timed window measures the steady state,
            # not either shape's one-time compile
            run_tick(sched, 0, timed=False, prng=prng)
            run_tick(sched, 1, timed=False, prng=prng)
            row, next_tick = measure(engine, sched, 2, prng)
            row["shards"] = S
            row["buckets"] = engine.buckets
            row["ordered_redispatches"] = engine.ordered_redispatches
            from escalator_tpu.observability import resources as _res

            arena = _res.RESOURCES.snapshot().get("fleet_arenas")
            if arena:
                row["arena_bytes"] = arena["nbytes"]
                row["arena_budget_bytes"] = arena["budget_bytes"]
            sweep[f"S{S}"] = row
            if S == shard_counts[-1]:
                headline = dict(row)
                # ---- overlap A-B pair on the SAME warm engine: a fresh
                # non-pipelined scheduler over the already-resident arenas
                sched.shutdown()
                sched = FleetScheduler(
                    engine, max_batch=128, flush_ms=5.0, queue_limit=4 * C,
                    per_tenant_inflight=2, classes=classes,
                    default_class="standard", pipeline=False)
                run_tick(sched, next_tick, timed=False, prng=prng)
                off_row, _ = measure(engine, sched, next_tick + 1, prng)
                sweep["overlap_off"] = {
                    "shards": S, "pipeline": False,
                    "decisions_per_sec": off_row["decisions_per_sec"],
                    "tick_wall_ms": off_row["tick_wall_ms"],
                    "per_tenant_p99_ms": off_row["per_tenant_p99_ms"],
                }
                headline["overlap_speedup_vs_off"] = round(
                    headline["decisions_per_sec"]
                    / max(off_row["decisions_per_sec"], 1e-9), 3)
        finally:
            sched.shutdown()
        del engine

    # ---- round-18 idle-fraction sweep: the digest fast path under a
    # fleet where only a fraction of tenants changed since their last
    # request. Every request after bootstrap is a STREAMING DELTA frame
    # (the production shape): changed tenants ship their one churned pod
    # row at an advanced clock, idle tenants ship an EMPTY delta at their
    # unchanged clock — the no-op probe answers those from the per-tenant
    # decision cache without entering the micro-batch. Columns per
    # fraction: drain decisions/sec, the measured cache-hit rate, and the
    # recorder host-prep p50 (fleet_prep root — the O(churn) proof: a
    # batch of one-row deltas costs milliseconds, not the O(P+N)-per-
    # request diff). Two UNTIMED warm drains per fraction keep the
    # one-time lane-bucket compiles (each fraction shrinks the real-
    # request count per take to a new power-of-two width) out of the
    # timed window. Smaller C than the headline sweep — the signal is
    # the relative shape, not a second saturation number.
    Si = shard_counts[-1]
    Ci = 500 if degraded else 2_000
    idle_ticks = 3
    idle_warm = 2
    idle_sweep = {}
    engine = FleetEngine(num_groups=Gt, pod_capacity=128,
                         node_capacity=32, max_tenants=Ci, num_shards=Si)
    sched = FleetScheduler(engine, max_batch=128, flush_ms=5.0,
                           queue_limit=4 * Ci, per_tenant_inflight=2,
                           classes=classes, default_class="standard",
                           pipeline=True)
    try:
        idle_prng = np.random.default_rng(181)
        cur = [fresh(t, 0) for t in range(Ci)]
        nows = [int(now) for _ in range(Ci)]

        def _take(soa, idx):
            return type(soa)(**{
                f: np.asarray(getattr(soa, f))[idx]
                for f in soa.__dataclass_fields__})

        no_rows = np.zeros(0, np.int32)
        empty_pods = _take(cur[0].pods, no_rows)
        empty_nodes = _take(cur[0].nodes, no_rows)

        def idle_drain(changed):
            """One paused-submit/resume drain, all delta frames: tenants
            in ``changed`` churn one pod row + advance their clock; the
            rest ship an empty delta at their unchanged clock (the
            digest no-op shape). Returns (wall_s, cache_hits_delta)."""
            changed = set(int(t) for t in changed)
            frames = []
            for t in range(Ci):
                if t in changed:
                    row = t % Pt
                    cur[t].pods.cpu_milli[row] += 10
                    nows[t] += 60
                    frames.append(DeltaFrame(
                        shapes=(Gt, Pt, Nt),
                        pod_idx=np.array([row], np.int32),
                        pod_vals=_take(cur[t].pods, [row]),
                        node_idx=no_rows, node_vals=empty_nodes,
                        groups=None))
                else:
                    frames.append(DeltaFrame(
                        shapes=(Gt, Pt, Nt),
                        pod_idx=no_rows, pod_vals=empty_pods,
                        node_idx=no_rows, node_vals=empty_nodes,
                        groups=None))
            hits0 = engine.cache_hits
            sched.pause()
            futs = [sched.submit(f"it{t}", None, nows[t],
                                 klass=klass_of(t), delta=frames[t])
                    for t in range(Ci)]
            t0 = time.perf_counter()
            sched.resume()
            for f in futs:
                f.result(timeout=1200)
            return (time.perf_counter() - t0,
                    int(engine.cache_hits - hits0))

        # bootstrap: every tenant resident + cached (full frames — the
        # only ones in the whole sweep) before the first arm
        sched.pause()
        boot = [sched.submit(f"it{t}", cur[t], nows[t],
                             klass=klass_of(t)) for t in range(Ci)]
        sched.resume()
        for f in boot:
            f.result(timeout=1200)
        # the fused step's jit key includes the BUSIEST shard's entry
        # count (rounded to a power of two): a uniform random changed set
        # leaves that count straddling two bucket widths draw to draw, so
        # a timed drain can hit a multi-second first compile no warm
        # covered. Changed sets are therefore drawn STRATIFIED across
        # shards (the balanced-placement expectation — registration
        # round-robins tenants over shards): the busiest-shard count is
        # deterministic per fraction and the warms compile exactly the
        # widths the timed drains use. Tenant membership per shard comes
        # from the public shard_of API.
        shard_members: dict = {}
        for t in range(Ci):
            shard_members.setdefault(
                engine.shard_of(f"it{t}"), []).append(t)

        def stratified_changed(n):
            shards = sorted(shard_members)
            base, extra = divmod(n, len(shards))
            out = []
            for j, s in enumerate(shards):
                members = shard_members[s]
                k = min(base + (1 if j < extra else 0), len(members))
                idx = idle_prng.choice(len(members), size=k,
                                       replace=False)
                out.extend(members[i] for i in idx)
            return np.asarray(out)

        for frac in (0.0, 0.5, 0.9, 0.99):
            n_changed = Ci - int(round(frac * Ci))
            # untimed warms: same fraction => same stratified busiest-
            # shard count => the step program the timed drains run
            # compiles HERE. Tenant 0 is DRAINING (t % 50 == 0) and is
            # swapped into every warm set (for its own shard-0 pick, so
            # the stratification holds): at high idle fractions a random
            # changed set often carries no order-consuming tenant, which
            # would leave the batched order-tail program's first compile
            # to fire inside a timed drain.
            s0 = set(shard_members[engine.shard_of("it0")])
            for _ in range(idle_warm):
                ch = stratified_changed(n_changed)
                if 0 not in ch:
                    # swap tenant 0 in for one of its own shard's picks
                    # so the stratified per-shard counts are unchanged
                    mine = [x for x in ch if x in s0]
                    if mine:
                        ch[ch == mine[0]] = 0
                    else:
                        ch[0] = 0
                idle_drain(ch)
            walls, hits = [], 0
            prep_seq = RECORDER.total_recorded
            for _ in range(idle_ticks):
                wall, h = idle_drain(stratified_changed(n_changed))
                walls.append(wall)
                hits += h
            prep_recs = [r for r in RECORDER.snapshot()
                         if r.get("seq", 0) > prep_seq
                         and r["root"] == "fleet_prep"]
            n_idle_total = (Ci - n_changed) * idle_ticks
            assert hits >= n_idle_total, (
                f"cfg17 idle sweep: {hits} cache hits for "
                f"{n_idle_total} idle re-sends at frac={frac}")
            # MEDIAN drain wall, not the sum: a residual order-tail
            # width's one-time compile can still pollute a single drain
            # (the tail program keys on the busiest shard's DRAINING
            # count, which stays a random draw); the median of 3 is
            # robust to one polluted sample
            med_wall = float(np.median(walls))
            row = {
                "tenants": Ci,
                "idle_fraction": frac,
                "decisions_per_sec": round(Ci / med_wall, 1),
                "cache_hit_rate": round(
                    hits / float(Ci * idle_ticks), 4),
            }
            if prep_recs:
                # host prep per micro-batch of one-row deltas — the
                # recorder-sourced O(churn) column
                row["host_prep_ms_p50"] = round(float(np.median(
                    [r["duration_ms"] for r in prep_recs])), 3)
            idle_sweep[f"idle_{int(frac * 100)}pct"] = row
    finally:
        sched.shutdown()
    del engine

    fleet_row = {
        "tenants": C, "pods_per_tenant": Pt, "timed_ticks": timed_ticks,
        "drain_model": ("all C requests enqueue against a paused "
                        "scheduler; one resume drains them — latency "
                        "includes real queue wait at saturation"),
        "sweep": sweep,
        "idle_sweep": idle_sweep,
        "class_mix": {"critical": "10%", "standard": "60%", "batch": "30%"},
    }
    if len(shard_counts) >= 2:
        a = sweep[f"S{shard_counts[0]}"]["decisions_per_sec"]
        b = sweep[f"S{shard_counts[1]}"]["decisions_per_sec"]
        fleet_row["scaling_1_to_2_wall"] = round(b / max(a, 1e-9), 3)
        fs_a = (sweep[f"S{shard_counts[0]}"]["fleet_step_ms"] or {})
        fs_b = (sweep[f"S{shard_counts[1]}"]["fleet_step_ms"] or {})
        if fs_a.get("p50") and fs_b.get("p50"):
            # per-shard device-program shrink: each shard runs C/S tenants,
            # so the fenced fleet_step phase is the device-side scaling
            # signal the host-bound wall clock hides on a small-core rig
            fleet_row["scaling_1_to_2_device_step"] = round(
                fs_a["p50"] / max(fs_b["p50"], 1e-9), 3)
    if headline is not None:
        fleet_row.update({k: v for k, v in headline.items()
                          if k not in ("buckets",)})
    detail["cfg17_fleet"] = fleet_row
    detail["cfg17_fleet_decisions_per_sec"] = fleet_row.get(
        "decisions_per_sec")
    detail["cfg17_fleet_per_tenant_p99_ms"] = fleet_row.get(
        "per_tenant_p99_ms")
    if "idle_90pct" in idle_sweep:
        detail["cfg17_fleet_idle90_decisions_per_sec"] = (
            idle_sweep["idle_90pct"]["decisions_per_sec"])
        detail["cfg17_fleet_idle90_cache_hit_rate"] = (
            idle_sweep["idle_90pct"]["cache_hit_rate"])


def _cfg18_scaleout(now, detail: dict, degraded: bool) -> None:
    """cfg18 (round-20 tentpole): horizontal scale-out — N=1 vs N=2 fleet
    PARTITIONS, each a REAL subprocess (own interpreter, own JAX runtime,
    own GIL) behind the consistent-hash PartitionRouter, on the cfg17
    per-tenant workload shape (4 groups x 100 pods x 20 nodes, the 10%
    critical / 60% standard / 30% batch class mix) at gRPC-subprocess
    scale (C=64; cfg17's C=10k drain model stays the in-process number).
    Two arms per partition count: the HOST-BOUND high-idle arm (90% of
    tenants repeat an unchanged frame — the digest fast path, pure host
    work, the arm the router exists for) and the full-churn arm (every
    tenant ships a delta, every micro-batch dispatches — device-bound).
    Per-class p99 is reported per partition against the cfg17 bars.

    Honesty contract: partition scaling is CORE-GATED. Two processes on a
    rig that exposes one usable core merely timeshare it — aggregate
    decisions/sec cannot exceed 1x, and the measured ratio is committed
    with that caveat instead of asserted (the cfg7/8 convention: the
    scaling SHAPE is the evidence the rig can produce). On >=2 cores the
    high-idle arm must scale >=1.5x. The full-churn arm is reported with
    the device-contention caveat either way: both partitions dispatch onto
    the same physical device pool, so device-bound work does not scale
    with partition count on a shared-core rig."""
    import shutil
    import subprocess
    import tempfile
    import threading
    from concurrent.futures import ThreadPoolExecutor

    from escalator_tpu.analysis.registry import representative_cluster
    from escalator_tpu.fleet.router import PartitionRouter

    Gt, Pt, Nt = 4, 100, 20
    C = 64
    idle_frac = 0.9
    warm_ticks, timed_ticks = 2, 3
    try:
        cores = len(os.sched_getaffinity(0))
    except AttributeError:  # pragma: no cover - non-linux
        cores = os.cpu_count() or 1

    def klass_of(t: int) -> str:
        m = t % 10
        return "critical" if m == 0 else ("batch" if m >= 7 else "standard")

    launcher = (
        "from escalator_tpu.plugin.server import FleetConfig, "
        "make_server\n"
        "srv = make_server('127.0.0.1:0', max_workers=16, "
        "fleet=FleetConfig(num_groups=%d, pod_capacity=%d, "
        "node_capacity=%d, max_tenants=%d, max_batch=16, flush_ms=5.0, "
        "queue_limit=%d, per_tenant_inflight=1, num_shards=1))\n"
        "srv.start()\n"
        "print('SCALEOUT_PORT=%%d' %% srv._escalator_bound_port, "
        "flush=True)\n"
        "srv.wait_for_termination()\n" % (Gt, Pt, Nt, C + 4, 4 * C))
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"

    def run_arm(nparts: int) -> dict:
        tmp = tempfile.mkdtemp(prefix="escalator-cfg18-")
        procs: dict = {}
        errs: dict = {}
        addrs: dict = {}
        for i in range(nparts):
            p = f"part{i}"
            errs[p] = open(os.path.join(tmp, f"{p}.stderr.log"), "w")
            procs[p] = subprocess.Popen(
                [sys.executable, "-c", launcher],
                stdout=subprocess.PIPE, stderr=errs[p], text=True, env=env)
        router = None
        pool = None
        try:
            boot_t0 = time.perf_counter()
            for p, proc in procs.items():
                port = None
                deadline = time.monotonic() + 300
                while time.monotonic() < deadline:
                    line = proc.stdout.readline()
                    if not line:
                        break
                    if line.startswith("SCALEOUT_PORT="):
                        port = int(line.split("=", 1)[1])
                        break
                if port is None:
                    errs[p].flush()
                    with open(os.path.join(
                            tmp, f"{p}.stderr.log")) as f:
                        tail = f.read()[-2000:]
                    raise AssertionError(
                        f"cfg18 partition {p} failed to start:\n{tail}")
                addrs[p] = f"127.0.0.1:{port}"
            router = PartitionRouter(addrs, timeout_sec=300.0)
            sessions: dict = {}
            nows: dict = {}
            for j in range(C):
                tid = f"c18t{j}"
                sess = router.stream_session(
                    tid, pod_capacity=Pt, node_capacity=Nt,
                    store_kind="numpy", klass=klass_of(j))
                sess.set_groups(representative_cluster(
                    Gt, Pt, Nt, seed=1800 + j).groups)
                for k in range(24):
                    sess.store.upsert_pod(f"{tid}-p{k}", k % Gt,
                                          400 + 10 * k + 3 * j,
                                          10 ** 9, k % 5)
                for k in range(8):
                    sess.store.upsert_node(f"{tid}-n{k}", k % Gt, 4000,
                                           16 * 10 ** 9)
                sessions[tid] = sess
                nows[tid] = int(now)
            tids = list(sessions)
            homes = {tid: router.home(tid) for tid in tids}
            pool = ThreadPoolExecutor(max_workers=16)
            lat_lock = threading.Lock()

            def tick(changed, lat_out=None):
                """One drain: every tenant decides concurrently; tenants
                in ``changed`` churn one pod row and advance their clock,
                the rest repeat unchanged (the digest fast path).
                Returns the drain wall."""
                def one(tid):
                    t_sub = time.perf_counter()
                    router.decide_stream(sessions[tid], nows[tid])
                    if lat_out is not None:
                        with lat_lock:
                            lat_out.setdefault(tid, []).append(
                                time.perf_counter() - t_sub)
                for tid in changed:
                    nows[tid] += 60
                    sessions[tid].store.upsert_pod(
                        f"{tid}-p1", 1, 500 + nows[tid] % 997,
                        10 ** 9, 1)
                futs = [pool.submit(one, tid) for tid in tids]
                t0 = time.perf_counter()
                for f in futs:
                    f.result(timeout=1200)
                return time.perf_counter() - t0

            # bootstrap: full frames register every tenant (the only full
            # frames of the sweep) + one all-delta warm
            tick(set())
            boot_s = time.perf_counter() - boot_t0
            tick(set(tids))
            prng = np.random.default_rng(180 + nparts)
            n_changed = C - int(round(idle_frac * C))
            arm_row: dict = {
                "partitions": nparts,
                "addresses": sorted(addrs.values()),
                "tenants": C,
                "tenant_spread": {
                    p: sum(1 for h in homes.values() if h == p)
                    for p in addrs},
                "bootstrap_s": round(boot_s, 1),
            }
            for arm_name, mix in (("high_idle", "idle"),
                                  ("full_churn", "all")):
                def changed_set():
                    if mix == "all":
                        return set(tids)
                    return set(prng.choice(tids, size=n_changed,
                                           replace=False))
                # warm THIS mix (cfg17 lesson: the step program keys on
                # batch widths — the timed window must not eat a compile)
                for _ in range(warm_ticks):
                    tick(changed_set())
                lats: dict = {}
                walls = [tick(changed_set(), lat_out=lats)
                         for _ in range(timed_ticks)]
                per_class: dict = {}
                for p in addrs:
                    for name in ("critical", "standard", "batch"):
                        cls = [l * 1e3
                               for j, tid in enumerate(tids)
                               if homes[tid] == p and klass_of(j) == name
                               for l in lats.get(tid, ())]
                        if not cls:
                            continue
                        bar = _CFG17_CLASS_BARS[name]
                        p99 = float(np.percentile(cls, 99))
                        per_class[f"{p}/{name}"] = {
                            "p50_ms": round(
                                float(np.percentile(cls, 50)), 3),
                            "p99_ms": round(p99, 3),
                            "p99_bar_ms": bar,
                            "within_bar": (True if bar is None
                                           else bool(p99 <= bar)),
                        }
                arm_row[arm_name] = {
                    "decisions_per_sec": round(
                        C * timed_ticks / sum(walls), 1),
                    "tick_wall_ms": round(
                        float(np.median(walls)) * 1e3, 1),
                    "idle_fraction": 0.0 if mix == "all" else idle_frac,
                    "classes": per_class,
                }
            return arm_row
        finally:
            if pool is not None:
                pool.shutdown()
            if router is not None:
                router.close()
            for p, proc in procs.items():
                proc.kill()
                try:
                    proc.wait(timeout=30)
                except subprocess.TimeoutExpired:  # pragma: no cover
                    pass
                if proc.stdout is not None:
                    proc.stdout.close()
                errs[p].close()
            shutil.rmtree(tmp, ignore_errors=True)

    arms = {f"N{n}": run_arm(n) for n in (1, 2)}
    idle_ratio = round(
        arms["N2"]["high_idle"]["decisions_per_sec"]
        / max(arms["N1"]["high_idle"]["decisions_per_sec"], 1e-9), 3)
    churn_ratio = round(
        arms["N2"]["full_churn"]["decisions_per_sec"]
        / max(arms["N1"]["full_churn"]["decisions_per_sec"], 1e-9), 3)
    row = {
        "workload": ("cfg17 per-tenant shape + class mix at C=64 over "
                     "real gRPC subprocess partitions"),
        "usable_cores": cores,
        "sweep": arms,
        "idle_scaling_1_to_2": idle_ratio,
        "churn_scaling_1_to_2": churn_ratio,
        "churn_caveat": (
            "full-churn is device-bound: both partitions dispatch onto "
            "the same physical device pool, so decisions/sec does not "
            "scale with partition count on a shared-core rig"),
    }
    if cores >= 2:
        assert idle_ratio >= 1.5, (
            f"cfg18: high-idle aggregate decisions/sec scaled only "
            f"{idle_ratio}x from 1 to 2 partitions on a {cores}-core rig "
            "(bar: >=1.5x)")
        row["idle_scaling_bar"] = ">=1.5x (met)"
    else:
        row["idle_scaling_bar"] = (
            f">=1.5x NOT ASSERTABLE: this rig exposes {cores} usable "
            "core(s); two partition processes timeshare it, so aggregate "
            "throughput is capped at ~1x regardless of the router. The "
            "measured ratio is committed as the honest number; the bar "
            "needs a >=2-core host")
    detail["cfg18_scaleout"] = row
    detail["cfg18_scaleout_idle_scaling_1_to_2"] = idle_ratio


def run_cfg18() -> dict:
    """Targeted cfg18 runner (``python bench.py --cfg18``): run ONLY the
    partition-scaling sweep and MERGE its rows into the existing
    BENCH_FULL_LATEST.json detail (the full bench stays the artifact of
    record for every other section — this mode exists so the scale-out
    numbers can be refreshed without re-pricing 17 sections)."""
    detail: dict = {}
    now = np.int64(1_700_000_000)
    _cfg18_scaleout(now, detail, degraded=True)
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_FULL_LATEST.json")
    try:
        with open(path) as f:
            record = json.load(f)
    except (OSError, ValueError):  # no prior full artifact: minimal shell
        record = {"full_artifact": "BENCH_FULL_LATEST.json", "detail": {}}
    record.setdefault("detail", {}).update(_round_floats(detail))
    try:
        _atomic_json_write(path, record)
    except OSError:  # pragma: no cover - read-only checkout
        pass
    return _round_floats(detail)


def _background_audit_row(store, cache, inc, now, P, G, cpu_m,
                          iters=None, cadence=None) -> dict:
    """Per-tick latency of the 1%-churn incremental tick with the refresh
    audit firing every ``cadence`` ticks, in BOTH audit modes: synchronous
    (the audit's O(cluster) recompute runs inside the audit tick — the old
    +96 ms / +383 ms spike) and background (the audit tick pays one
    device-copy snapshot + a thread handoff; the recompute runs on a worker
    against the frozen double buffer). ``audit_tick_ms`` vs
    ``normal_tick_ms`` is the p99 story: in background mode the ratio
    should be ~1. Every background audit is drained and its verdict
    recorded — amortized to zero ON-PATH, not skipped."""
    n_churn = P // 100
    tick_no = itertools.count(9000)

    def one_tick() -> float:
        t = next(tick_no)
        idx = (t * n_churn + np.arange(n_churn)) % P
        store.upsert_pods_batch(
            [f"p{i}" for i in idx], idx % G,
            np.full(n_churn, cpu_m), np.full(n_churn, 10**9))
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd))
        t0 = time.perf_counter()
        inc.decide(now, False)
        return (time.perf_counter() - t0) * 1e3

    # warm both audit forms' programs outside the timed loops (the snapshot
    # copy jit would otherwise pollute the first background audit tick)
    warm = one_tick()
    tick_est = min(warm, one_tick())
    t0 = time.perf_counter()
    inc.refresh()
    audit_est = (time.perf_counter() - t0) * 1e3
    inc._start_background_audit()
    inc.drain_audit()
    if cadence is None:
        # the cadence must give the worker ROOM: an audit still in flight at
        # the next cadence point forces a blocking settle (at-most-one-audit
        # invariant), which would price the settle, not the steady state.
        # Production runs cadence 256; the bench picks the smallest cadence
        # whose inter-audit window (cadence x normal tick) covers ~2x the
        # synchronous audit duration, probed from the warm ticks above.
        cadence = max(4, int(2.0 * audit_est / max(tick_est, 1e-3)) + 1)
    # seven audit ticks per mode: the audit-tick median over few samples is
    # noise-dominated on a shared-core rig (normal ticks here swing 2-4x
    # tick to tick; the normal-tick median averages over ~100+ ticks while
    # the audit median gets only the cadence points), which made the
    # published ratio wobble far off the steady state the row exists to
    # price — quiet-rig probes sit at ~1.0x while a 2-sample median has
    # landed anywhere in 0.95-1.5x
    if iters is None:
        iters = 7 * cadence
    else:
        # a caller-passed tick budget is a FLOOR: at 1M the self-probed
        # cadence (~32: the audit takes ~15 normal ticks) exceeded the
        # fixed 12-tick budget, so no tick ever hit the cadence point and
        # the row published audits=0 with NaN medians
        iters = max(iters, 7 * cadence)

    out = {"cadence_ticks": cadence}
    prev_every, prev_bg = inc._refresh_every, inc._background
    try:
        for mode, bg in (("sync", False), ("background", True)):
            inc._background = bg
            inc._refresh_every = cadence
            inc._ticks = 0
            audit_t, normal_t = [], []
            one_tick()   # warm (tick 1: no audit)
            for _ in range(iters):
                ms = one_tick()
                (audit_t if inc._ticks % cadence == 0
                 else normal_t).append(ms)
            ok = inc.drain_audit() if bg else inc.last_audit_ok
            a = float(np.median(audit_t))
            n = float(np.median(normal_t))
            out[mode] = {
                "audit_tick_ms": round(a, 3),
                "normal_tick_ms": round(n, 3),
                "audit_tick_over_normal": round(a / n, 3) if n else None,
                "audits": len(audit_t),
                "audits_ok": bool(ok),
            }
    finally:
        inc._refresh_every, inc._background = prev_every, prev_bg
        inc.drain_audit()
    return out


def _observability_overhead(store, cache, inc, now, P, G, cpu_m,
                            iters=20, n_churn=None) -> dict:
    """Median ms of the full incremental tick (upsert + drain + gather +
    scatter + delta decide at 1% churn) with span recording ENABLED vs
    DISABLED (spans.set_enabled — the no-op control arm). The enabled arm is
    exactly what production pays: every span site executes, the flight
    recorder records every tick. Negative deltas are clamped to 0 (rig
    noise on a shared-core host exceeds the real ~10 us cost)."""
    from escalator_tpu.observability import spans

    if n_churn is None:
        n_churn = P // 100
    tick_no = itertools.count(5000)   # distinct lanes from the sweep's

    def tick():
        t = next(tick_no)
        idx = (t * n_churn + np.arange(n_churn)) % P
        store.upsert_pods_batch(
            [f"p{i}" for i in idx], idx % G,
            np.full(n_churn, cpu_m), np.full(n_churn, 10**9))
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd))
        inc.decide(now, False)

    # INTERLEAVED arms + MINIMA: on this shared-core rig a medians-of-
    # sequential-loops comparison jitters by more than the ~40 us a full
    # record actually costs (micro-benched; back-to-back medians of the
    # SAME arm differ by >1 ms). Alternating enabled/disabled ticks makes
    # host-load drift hit both arms equally, and the min is the bench's
    # established stall-resistant program-cost estimator (see cfg9).
    enabled_t, disabled_t = [], []
    try:
        tick()   # warm any fresh delta-bucket shape
        for _ in range(iters):
            spans.set_enabled(True)
            t0 = time.perf_counter()
            tick()
            enabled_t.append((time.perf_counter() - t0) * 1e3)
            spans.set_enabled(False)
            t0 = time.perf_counter()
            tick()
            disabled_t.append((time.perf_counter() - t0) * 1e3)
    finally:
        spans.set_enabled(True)
    enabled_min = float(np.min(enabled_t))
    disabled_min = float(np.min(disabled_t))
    overhead_ms = max(0.0, enabled_min - disabled_min)
    return {
        "enabled_ms": round(float(np.median(enabled_t)), 3),
        "disabled_ms": round(float(np.median(disabled_t)), 3),
        "enabled_min_ms": round(enabled_min, 3),
        "disabled_min_ms": round(disabled_min, 3),
        "overhead_ms": round(overhead_ms, 3),
        "overhead_pct": round(100.0 * overhead_ms / disabled_min, 2)
        if disabled_min else None,
    }


def _memory_envelope(device, detail: dict) -> None:
    """Single-chip HBM envelope (VERDICT r4 item 3). Preferred source:
    device.memory_stats() AFTER the big clusters are resident (returned {} in
    every round-4 capture — re-probed here and recorded either way, including
    the raw key list so a runtime that starts reporting is noticed). Always
    recorded: the computed per-row footprint from the store column dtypes
    (native/statestore.py _POD_FIELDS/_NODE_FIELDS) and the implied max
    cluster per 16 GB v5e chip."""
    try:
        ms = device.memory_stats()
        detail["device_memory_stats_raw_keys"] = sorted((ms or {}).keys())
        if ms:
            detail["device_memory_stats"] = {
                k: ms[k]
                for k in ("bytes_in_use", "peak_bytes_in_use",
                          "bytes_limit", "largest_alloc_size", "num_allocs")
                if k in ms
            }
    except Exception as e:
        detail["device_memory_stats_error"] = str(e)
    # computed envelope from the device-resident column dtypes:
    #   pod row  = int32 group + int64 cpu + int64 mem + int32 node + bool valid
    #   node row = int32 group + 3x int64 + 3x bool + int64 taint_time + bool
    pod_b = 4 + 8 + 8 + 4 + 1            # 25 B/pod
    node_b = 4 + 8 + 8 + 8 + 1 + 1 + 1 + 8 + 1  # 40 B/node
    hbm = 16 * 10**9                      # v5e: 16 GB HBM per chip
    detail["device_memory_envelope"] = {
        "bytes_per_pod_row": pod_b,
        "bytes_per_node_row": node_b,
        "headline_shape_bytes": 100_000 * pod_b + 50_000 * node_b,
        "cfg13_shape_bytes": 1_000_000 * pod_b + 100_000 * node_b,
        "note": (
            "store columns only; decide intermediates add ~3x the pod "
            "columns transiently (sort keys + argsort indices + segment "
            "sums), so peak ~= 4x column bytes. Under that model, with "
            "nodes at 10% of pods, one 16 GB v5e chip holds ~138M pods + "
            "~13.8M nodes; docs/performance.md applies further safety "
            "margin on top of this number, not instead of it."
        ),
        "max_pods_per_chip_4x_intermediates": int(
            hbm / (4 * pod_b + 0.1 * 4 * node_b)),
    }
    # round 15: the envelope's per-owner half is now EXECUTABLE — the
    # resource registry reports what each owner of persistent device state
    # actually holds (and its declared formula budget) at capture time,
    # next to the hand model above
    try:
        from escalator_tpu.observability import resources as _res

        detail["device_resource_owners"] = _res.RESOURCES.snapshot()
        detail["device_memory_capabilities"] = _res.capabilities()
    except Exception as e:  # noqa: BLE001 - reporting must not kill a capture
        detail["device_resource_owners_error"] = str(e)


def _cfg9_pallas_matrix(detail, headline_cluster, host_headline,
                        churned_cluster, rng, now, device,
                        flush=None) -> None:
    """pallas-vs-xla on >=3 shapes with a computed conclusion (VERDICT r3
    item 2): (a) the contiguous 100k-lane headline layout, (b) the churned
    slot-reused interleaved layout from the native store (the on-device-sort
    path's raison d'etre, ops/pallas_kernel.py pallas_sorted), (c) a 1M-lane
    single-group shape. Full-decide timings, so the ratio reflects what a
    user of impl="pallas" actually gets."""
    from escalator_tpu.ops import pallas_kernel as pk

    rows = {}
    # Off-TPU, impl="pallas" runs the INTERPRETER — measured ~45 s/call at
    # 1M lanes on this rig (round-11 artifact: pallas_ms 48004 on the
    # 1Mlane row), which is 45+ minutes of bench time for a number that
    # prices the interpreter, not the kernel. Keep the row (the ratio's
    # order of magnitude is still evidence the auto-select is right to pin
    # xla off-TPU) but at a few iterations, flagged in the row.
    pallas_iters = ITERS if device.platform == "tpu" else max(2, ITERS // 15)

    def row(label, cluster, host_group, host_valid, host_cpu):
        # time each impl in its own try: a pallas lowering failure on one
        # shape must not discard the xla baseline already measured
        r = {}
        if pallas_iters != ITERS:
            r["pallas_interpret_mode"] = (
                f"non-TPU platform: pallas rows are interpreter timings, "
                f"{pallas_iters} iters")
        try:
            r["xla_ms"], r["xla_min_ms"] = (
                round(v, 3) for v in _time_decide_med_min(cluster, now, impl="xla"))
        except Exception as e:  # pragma: no cover
            r["xla_error"] = str(e)
        try:
            r["pallas_ms"], r["pallas_min_ms"] = (
                round(v, 3)
                for v in _time_decide_med_min(cluster, now, impl="pallas",
                                              iters=pallas_iters))
        except Exception as e:  # pragma: no cover
            r["pallas_error"] = str(e)
        try:
            r["path"] = pk.path_report(
                np.where(host_valid, host_group, 0), host_valid,
                {"cpu": host_cpu},
            )["path"]
        except Exception as e:  # pragma: no cover
            r["path_error"] = str(e)
        # ratio from the MINIMA: a tunnel stall mid-loop inflates one impl's
        # median by orders of magnitude (observed: 567 ms median vs 0.25 ms
        # min on the same shape in one session) and would flip the computed
        # conclusion; the best observed iteration is the stall-resistant
        # estimate of what the program costs
        # residency diagnostic: sessions 2026-07-30T0519/0543 showed rows
        # timed late in a session running 100-500x slower with TIGHT
        # min~median (size-proportional — consistent with per-call argument
        # re-transfer, not compute), while a row's SECOND impl sometimes ran
        # fast on the same arrays (repeated access re-establishing
        # residency). Re-timing xla after the pallas loop separates the two
        # stories: xla_retime << xla means the first loop paid warming, and
        # the retime is the steady-state cost.
        if "xla_ms" in r:
            try:
                r["xla_retime_ms"], r["xla_retime_min_ms"] = (
                    round(v, 3)
                    for v in _time_decide_med_min(cluster, now, impl="xla"))
            except Exception as e:  # pragma: no cover
                r["xla_retime_error"] = str(e)
        # symmetric retime for pallas too, so both impls get the same number
        # of loops (round-4 gave only xla a retime, biasing the ratio; the
        # old single-loop ratio key ``pallas_over_xla`` is retired — this is
        # a different statistic, so it gets a new name, ``pallas_over_xla_min``)
        if "pallas_ms" in r:
            try:
                r["pallas_retime_ms"], r["pallas_retime_min_ms"] = (
                    round(v, 3)
                    for v in _time_decide_med_min(cluster, now, impl="pallas",
                                                  iters=pallas_iters))
            except Exception as e:  # pragma: no cover
                r["pallas_retime_error"] = str(e)
        # ratio of steady-state costs: each impl's best observation across
        # its two loops (min is the stall-resistant estimate; see above)
        xla_eff = min(
            (v for v in (r.get("xla_min_ms"), r.get("xla_retime_min_ms"))
             if v is not None),
            default=None,
        )
        pallas_eff = min(
            (v for v in (r.get("pallas_min_ms"), r.get("pallas_retime_min_ms"))
             if v is not None),
            default=None,
        )
        if xla_eff and pallas_eff:
            r["pallas_over_xla_min"] = round(pallas_eff / xla_eff, 3)
        rows[label] = r
        # each row is 4 timing loops on a possibly-stalling tunnel — flush so
        # a wedge mid-matrix keeps the rows already measured (and feeds the
        # campaign watchdog's progress signal). Flushed under a DISTINCT
        # in-progress key: _summarize_tpu_partials counts cfg sections by
        # key, and the final key here would present a wedged mid-matrix run
        # as a completed cfg9 section (ADVICE r5)
        detail["cfg9_pallas_vs_xla_partial"] = {
            "rows": dict(rows), "conclusion": "(matrix in progress)"}
        if flush is not None:
            flush()

    row("contiguous_2048g_100kpods", headline_cluster,
        host_headline.pods.group, host_headline.pods.valid,
        host_headline.pods.cpu_milli)
    if churned_cluster is not None:
        cp = churned_cluster.pods
        row("churned_interleaved_2048g_100kpods", churned_cluster,
            np.asarray(cp.group), np.asarray(cp.valid),
            np.asarray(cp.cpu_milli))
    giant = _rng_cluster_arrays(rng, 1, 1_000_000, 50_000, mixed=True)
    import jax

    row("1Mlane_1group", jax.device_put(giant, device),
        giant.pods.group, giant.pods.valid, giant.pods.cpu_milli)

    try:
        ms = device.memory_stats() or {}
        detail["cfg9_device_memory"] = {
            k: ms[k]
            for k in ("bytes_in_use", "peak_bytes_in_use",
                      "largest_alloc_size", "num_allocs")
            if k in ms
        }
    except Exception:  # pragma: no cover - not every backend reports stats
        pass

    # control: re-time the cfg4 program (compiled at session start, on the
    # early-uploaded headline cluster) AFTER the heavy rows. Session
    # 0627 showed the inflated rows are steady-state (xla_retime ~= xla,
    # so not warming) while the contiguous row stayed sub-ms at the same
    # point — if this control also stays at its cfg4 value, the penalty is
    # per-program/per-buffer (a tunnel cache artifact), not a session-wide
    # slowdown, and the product path (few programs, compiled at startup)
    # is unaffected.
    try:
        ctl_med, ctl_min = _time_decide_med_min(headline_cluster, now)
        detail["cfg9_control_cfg4_retime_ms"] = round(ctl_med, 3)
        detail["cfg9_control_cfg4_retime_min_ms"] = round(ctl_min, 3)
    except Exception as e:  # pragma: no cover
        detail["cfg9_control_cfg4_retime_error"] = str(e)

    measured = [l for l, r in rows.items() if r.get("pallas_over_xla_min")]
    wins = [l for l in measured if rows[l]["pallas_over_xla_min"] < 0.95]
    losses = [l for l in measured if rows[l]["pallas_over_xla_min"] > 1.05]
    if not measured:
        concl = "no successful pallas-vs-xla measurement (all rows errored)"
    elif wins and not losses:
        concl = f"pallas wins >5% on: {', '.join(wins)}"
    elif losses and not wins:
        concl = ("XLA scatter is good enough on this chip: pallas loses >5% "
                 f"on {', '.join(losses)}")
    elif not wins and not losses:
        concl = (f"no measurable difference (within 5%) on {len(measured)} "
                 "measured shape(s): XLA scatter is good enough on this "
                 "chip; pallas kept for layout-churn robustness only")
    else:
        concl = f"mixed: pallas wins on {wins}, loses on {losses}"
    detail.pop("cfg9_pallas_vs_xla_partial", None)
    detail["cfg9_pallas_vs_xla"] = {"rows": rows, "conclusion": concl}


def _bench_ffd_pack(rng, device) -> dict:
    """Fleet-wide FFD packing sweeps at 2048 groups x 64 padded pods x
    (32 real + 16 virtual) bins, on TWO pod distributions:

    - the historical MIXED row (independent cpu/mem draws): dominant-share
      ties interleave distinct shapes, the histogram prepass cannot
      compress, and the per-pod scan prices the adversarial floor;
    - a REPLICASET row (3 distinct pod shapes — the production-common case
      the prepass exists for, ops/binpack.py): runs collapse ~64 pods ->
      ~4 scan steps and the run-block program prices the compressed path.

    Each row records what the prepass decided (``pack_compression_stats``)
    so the artifact says WHICH scan program its number measured."""
    import jax

    from escalator_tpu.ops.binpack import ffd_pack, pack_compression_stats

    G, Ppg, M, B = 2048, 64, 32, 16
    pod_cpu = rng.choice([100, 250, 500, 1000, 2000], (G, Ppg)).astype(np.int64)
    pod_mem = rng.choice([10**8, 5 * 10**8, 10**9, 4 * 10**9],
                         (G, Ppg)).astype(np.int64)
    pod_valid = rng.random((G, Ppg)) < 0.9
    bin_cpu = rng.choice([2000, 4000, 8000], (G, M)).astype(np.int64)
    bin_mem = rng.choice([8, 16, 32], (G, M)).astype(np.int64) * 10**9
    bin_valid = rng.random((G, M)) < 0.95
    tmpl_cpu = np.full(G, 4000, np.int64)
    tmpl_mem = np.full(G, 16 * 10**9, np.int64)

    out = {}

    from escalator_tpu.observability import spans

    def row(prefix, pc, pm):
        def packed():
            return ffd_pack(pc, pm, pod_valid, bin_cpu, bin_mem, bin_valid,
                            tmpl_cpu, tmpl_mem, new_bin_budget=B)

        med, mn = _timeit(
            lambda: jax.block_until_ready(packed().new_nodes_needed),
            iters=max(10, ITERS // 3),
        )
        out[f"{prefix}_ms"] = round(med, 3)
        out[f"{prefix}_min_ms"] = round(mn, 3)
        out[f"{prefix}_compression"] = pack_compression_stats(
            pc, pm, pod_valid, tmpl_cpu, tmpl_mem)
        # recorder-sourced column: a few fenced iterations through the span
        # layer, summarized from the flight recorder (same channel prod uses)
        for _ in range(5):
            with spans.span(prefix):
                with spans.span("ffd_pack", kind="device"):
                    spans.fence(packed().new_nodes_needed)
        out[f"{prefix}_recorder_phases"] = _recorder_phase_stats(prefix)

    row("cfg10_ffd_pack_2048g_64pods", pod_cpu, pod_mem)
    shapes = np.array([[500, 10**9], [250, 5 * 10**8], [1000, 4 * 10**9]],
                      np.int64)
    pick = rng.integers(0, 3, (G, Ppg))
    row("cfg10_ffd_pack_replicaset", shapes[pick, 0], shapes[pick, 1])
    return out


def _bench_plugin_roundtrip(host_cluster, now) -> dict:
    """cfg12: the gRPC compute-plugin boundary priced at the headline shape —
    columnar encode -> localhost gRPC -> decode -> decide on the server's
    device -> encode -> decode. This is what a non-Python controller shell
    (the reference-style embedding, SURVEY.md §2.7 plugin slot) pays per tick
    over the bare in-process decide that cfg4 times."""
    from escalator_tpu.plugin.client import ComputeClient
    from escalator_tpu.plugin.server import make_server

    server = make_server("127.0.0.1:0", max_workers=2)
    try:
        server.start()
        client = ComputeClient(f"127.0.0.1:{server._escalator_bound_port}",
                               timeout_sec=120.0)
        try:
            med, mn = _timeit(
                lambda: client.decide_arrays(host_cluster, int(now)),
                iters=max(5, ITERS // 3),
            )
            return {"cfg12_plugin_roundtrip_2048g_100kpods_ms": round(med, 3),
                    "cfg12_plugin_roundtrip_min_ms": round(mn, 3)}
        finally:
            client.close()
    finally:
        server.stop(grace=None)


def _summarize_tpu_captures() -> list:
    """One summary row per TPU capture: this round's campaign files
    (TPU_BENCH_*.json from tools/tpu_campaign.sh) plus the driver-recorded
    benches of PRIOR rounds (BENCH_r*.json, flagged ``prior_round`` — older
    code, but genuine TPU sessions), so the artifact carries cross-session
    spread evidence (VERDICT r3 item 5) even when the tunnel stays wedged
    for a whole round."""
    import glob

    rows = []
    here = os.path.dirname(os.path.abspath(__file__))
    # round 15 hygiene: campaign captures live under tpu_traces/ now (the
    # repo-root glob stays for any stray capture from an older campaign
    # script still running against this checkout)
    paths = sorted(glob.glob(os.path.join(here, "TPU_BENCH_*.json"))
                   + glob.glob(os.path.join(here, "tpu_traces",
                                            "TPU_BENCH_*.json")))
    paths += sorted(glob.glob(os.path.join(here, "BENCH_r*.json")))
    for path in paths:
        # CAPTURE.json is the campaign's copy of the last good capture, not an
        # independent session; and a capture still being written (possibly by
        # this very process) is empty — neither is spread evidence
        if os.path.basename(path) == "TPU_BENCH_CAPTURE.json":
            continue
        try:
            with open(path) as f:
                text = f.read().strip()
            if not text:
                continue
            try:
                data = json.loads(text)  # whole file: wrapper or one line
            except json.JSONDecodeError:
                # campaign capture with stderr noise ahead of the bench line
                data = json.loads(text.splitlines()[-1])
            if "metric" not in data:
                # driver wrapper (BENCH_r*.json) stores the bench dict under
                # "parsed"; a fully wedged round has none — not a capture
                data = data.get("parsed")
                if not isinstance(data, dict) or "metric" not in data:
                    if not os.path.basename(path).startswith("BENCH_r"):
                        # a campaign capture that died mid-run still names a
                        # TPU session — surface it, don't erase the evidence
                        rows.append({"file": os.path.basename(path),
                                     "error": "no bench record in capture"})
                    continue
            # split device into name + degraded flag: embedding the raw
            # "... CPU fallback" marker here would poison the campaign's
            # degradation grep for every later capture
            dev = str(data.get("device") or "")
            degraded = "CPU fallback" in dev
            base = os.path.basename(path)
            row = {
                "file": base,
                "value_ms": data.get("value"),
                "headline_scope": data.get("headline_scope", "(pre-r4 kernel-only)"),
                "device_name": dev.split(" (")[0],
                "degraded": degraded,
                "cfg4_kernel_only_ms": data.get("detail", {}).get(
                    "cfg4_kernel_only_ms",
                    data.get("detail", {}).get("cfg4_2048ng_100kpods_ms")),
            }
            if base.startswith("BENCH_r"):
                row["prior_round"] = True  # earlier code, genuine TPU session
            rows.append(row)
        except Exception as e:  # pragma: no cover
            rows.append({"file": os.path.basename(path), "error": str(e)})
    return rows


def _summarize_tpu_partials() -> list:
    """One row per salvaged partial capture (TPU_PARTIAL_*.json, kept by
    tools/tpu_campaign.sh when a bench wedged mid-run): which sections the
    session completed before dying, and its headline if cfg6 landed. Partial
    evidence is still evidence — a wedge-prone tunnel may never hold still
    for a full bench, and the fields a partial carries are real measurements
    from a live session."""
    import glob

    here = os.path.dirname(os.path.abspath(__file__))
    rows = []
    for path in sorted(glob.glob(os.path.join(here, "TPU_PARTIAL_*.json"))
                       + glob.glob(os.path.join(here, "tpu_traces",
                                                "TPU_PARTIAL_*.json"))):
        try:
            with open(path) as f:
                data = json.load(f)
            d = data.get("detail") or {}
            # a section counts as completed only via a MEASURED key — error,
            # skip and in-progress markers (cfg6_native_tick_error,
            # cfg12_skipped, cfg9_pallas_vs_xla_partial, ...) must not
            # present a failed or half-done section as salvaged evidence
            done = {k.split("_")[0] for k in d
                    if k.startswith("cfg")
                    and not k.endswith(("_error", "_skipped", "_partial"))}
            rows.append({
                "file": os.path.basename(path),
                "device_name": str(data.get("device", "")).split(" (")[0],
                "degraded": "CPU fallback" in str(data.get("device", "")),
                "sections": sorted(done, key=lambda s: int(s[3:] or 0)),
                "e2e_tick_1pct_ms": d.get("cfg6_native_tick_1pct_churn_ms"),
            })
        except Exception as e:  # pragma: no cover
            rows.append({"file": os.path.basename(path), "error": str(e)})
    return rows


def _archived_e2e_values(capture_rows: list) -> list:
    """End-to-end headline values from the ARCHIVED live-device campaign
    captures in the repo (degraded, errored, valueless, pre-r4-scope and
    BENCH_r* prior-round-wrapper rows excluded). Timestamped filenames in
    detail.tpu_captures say which session produced each value — captures
    persist across rounds, so "archived" means exactly that, not "this
    round's"."""
    return [
        r["value_ms"] for r in capture_rows
        if not r.get("prior_round") and not r.get("degraded")
        and not r.get("error")
        and r.get("value_ms") is not None
        and str(r.get("headline_scope", "")).startswith("end_to_end")
    ]


def _run_sharded_subprocess(detail: dict) -> None:
    """cfg7/cfg8 need 8 devices; the single-chip/CPU main process can't host
    them, so they run in a subprocess with 8 virtual CPU devices (the same
    environment the multi-chip dry-run validates against)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--sharded"],
            env=env, capture_output=True, text=True, timeout=3000,
        )
        if proc.returncode != 0:
            detail["cfg7_error"] = proc.stderr[-300:]
            return
        detail.update(json.loads(proc.stdout.strip().splitlines()[-1]))
    except Exception as e:  # pragma: no cover
        detail["cfg7_error"] = str(e)


def run_sharded() -> None:
    """Subprocess body: cfg7 (mesh-sharded, 8192 groups / 1M pods) and cfg8
    (pod-axis, one giant group / 1M pods) as device-count SCALING CURVES on
    the 8-virtual-device CPU mesh, plus single-device runs of the same shapes.

    De-confounding (VERDICT r3 items 3/4): the virtual devices share ONE
    host's physical cores — on this rig every "device" timeshares the same
    silicon, and replicated computation serializes S-fold. Absolute ratios
    therefore measure thread contention / program structure, NOT ICI scaling;
    the curve SHAPE (how latency changes as per-device work shrinks 1->8) is
    the only evidence this rig can produce. Both the core count and an
    explicit confound note ship in the JSON so the numbers cannot be read as
    chip scaling by accident."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.ops.kernel import decide_jit
    from escalator_tpu.parallel import mesh as meshlib
    from escalator_tpu.parallel import podaxis

    devices = jax.devices()
    assert len(devices) == 8, devices
    rng = np.random.default_rng(7)
    now = np.int64(1_700_000_000)
    out = {
        "sharded_host_physical_cores": os.cpu_count(),
        "sharded_confound": (
            "virtual CPU devices timeshare one host's cores; ratios measure "
            "thread contention, not chip scaling — read the curve shape only"
        ),
    }
    iters = max(5, ITERS // 5)

    # ---- cfg7: 8192 groups / 1M pods / 500k nodes over the group axis ------
    G, P, N = 8192, 1_000_000, 500_000

    def packed_shards(S):
        shards = [
            _rng_cluster_arrays(np.random.default_rng(7 + s), G // S, P // S,
                                N // S, mixed=True, heterogeneous=True,
                                tainted_frac=0.1, cordoned_frac=0.02)
            for s in range(S)
        ]
        leaves = [c.tree_flatten()[0] for c in shards]
        stacked = [np.stack(parts) for parts in zip(*leaves, strict=True)]
        return ClusterArrays.tree_unflatten(None, stacked)

    curve = {}
    for S in (1, 2, 4, 8):
        mesh = meshlib.make_mesh(devices[:S])
        placed = meshlib.shard_cluster_arrays(packed_shards(S), mesh)
        decider = meshlib.make_sharded_decider(mesh)
        med, _ = _timeit(
            lambda: jax.block_until_ready(decider(placed, now)), iters=iters)
        curve[str(S)] = round(med, 3)
    out["cfg7_curve_ms_by_devices"] = curve
    out["cfg7_sharded_8dev_8192ng_1Mpods_ms"] = curve["8"]

    # same total shape on ONE device, flat (no shard axis), for reference
    single = _rng_cluster_arrays(rng, G, P, N, mixed=True, heterogeneous=True,
                                 tainted_frac=0.1, cordoned_frac=0.02)
    single = jax.device_put(single, devices[0])
    med1, _ = _timeit(
        lambda: jax.block_until_ready(decide_jit(single, now)), iters=iters)
    out["cfg7_single_device_ms"] = round(med1, 3)
    out["cfg7_speedup_8dev"] = (
        round(med1 / curve["8"], 2) if curve["8"] > 0 else None)
    del single, placed, decider

    # ---- cfg8: pod-axis, ONE giant group with 1M pods ----------------------
    # Round 6 split the row into BUSY vs STEADY ticks: a steady tick runs the
    # lazy-orders light program (no node sort anywhere); a busy/drain tick
    # runs the ordered program with the GROUP-BLOCK-SHARDED tail
    # (ops.order_tail wired through podaxis.make_podaxis_decider): each
    # device sorts only its group block's nodes — for this one-giant-group
    # shape, ONE device pays the [N] sort while the other seven skip via
    # lax.cond, instead of all eight replicating it (the 218-of-241 ms tail
    # round 5 measured, BENCH_r05 cfg8_replicated_tail_ms). The legacy
    # replicated-ordered row is kept alongside as the before/after.
    from escalator_tpu.ops import order_tail

    giant = _rng_cluster_arrays(rng, 1, 1_000_000, 50_000, mixed=True)
    busy8 = {}
    steady8 = {}
    mesh8 = placed8_on_mesh8 = decider8_on_mesh8 = blocks8 = None
    for S in (2, 8):
        mesh = meshlib.make_mesh(devices[:S])
        placed8 = podaxis.place(podaxis.pad_pods_for_mesh(giant, mesh), mesh)
        blocks = order_tail.assign_order_blocks(
            giant.nodes.group, giant.nodes.valid, S, num_groups=1)
        decider8 = podaxis.make_podaxis_decider(mesh)
        light8 = podaxis.make_podaxis_decider(mesh, with_orders=False)
        medb, _ = _timeit(
            lambda: jax.block_until_ready(decider8(placed8, now, blocks)),
            iters=iters)
        meds, _ = _timeit(
            lambda: jax.block_until_ready(light8(placed8, now)), iters=iters)
        busy8[str(S)] = round(medb, 3)
        steady8[str(S)] = round(meds, 3)
        if S == 8:
            mesh8, placed8_on_mesh8 = mesh, placed8
            decider8_on_mesh8, blocks8 = decider8, blocks
    out["cfg8_busy_curve_ms_by_devices"] = busy8
    out["cfg8_steady_curve_ms_by_devices"] = steady8
    out["cfg8_podaxis_8dev_1Mpods_ms"] = busy8["8"]

    # the pre-round-6 ordered path (replicated [N] sort on every device),
    # same mesh/cluster: the before/after of the sharded tail in one artifact
    med_legacy, _ = _timeit(
        lambda: jax.block_until_ready(decider8_on_mesh8(placed8_on_mesh8, now)),
        iters=iters)
    out["cfg8_legacy_replicated_8dev_ms"] = round(med_legacy, 3)

    # phase split on the 8-dev mesh: the sharded pod sweep (scales with
    # devices on real chips) vs the decide tail — reported for BOTH ordered
    # formulations (replicated = round 5's crossover-model loss term;
    # sharded = what a busy tick now pays on top of the sweep)
    sweep_ms = podaxis.time_pod_sweep(
        mesh8, placed8_on_mesh8, _timeit=lambda f: _timeit(f, iters=iters))
    out["cfg8_sweep_only_8dev_ms"] = round(sweep_ms, 3)
    out["cfg8_replicated_tail_ms"] = round(med_legacy - sweep_ms, 3)
    out["cfg8_sharded_tail_ms"] = round(busy8["8"] - sweep_ms, 3)

    # recorder-sourced per-phase columns on the 8-dev mesh: a few fenced
    # iterations of each program variant through the span layer, summarized
    # from the flight recorder (the same channel the backends feed in prod)
    from escalator_tpu.observability import spans

    for variant, run in (
        ("busy_sharded_tail",
         lambda: decider8_on_mesh8(placed8_on_mesh8, now, blocks8)),
        ("steady_light", lambda: light8(placed8_on_mesh8, now)),
        ("legacy_replicated", lambda: decider8_on_mesh8(placed8_on_mesh8, now)),
    ):
        root = f"cfg8_{variant}"
        for _ in range(5):
            with spans.span(root):
                with spans.span("decide", kind="device"):
                    spans.fence(run())
    out["cfg8_recorder_phases_ms"] = {
        v: _recorder_phase_stats(f"cfg8_{v}")
        for v in ("busy_sharded_tail", "steady_light", "legacy_replicated")
    }

    giant_dev = jax.device_put(giant, devices[0])
    med8s, _ = _timeit(
        lambda: jax.block_until_ready(decide_jit(giant_dev, now)), iters=iters)
    med8l, _ = _timeit(
        lambda: jax.block_until_ready(
            decide_jit(giant_dev, now, with_orders=False)), iters=iters)
    out["cfg8_single_device_ms"] = round(med8s, 3)
    out["cfg8_single_device_steady_ms"] = round(med8l, 3)
    out["cfg8_speedup_8dev"] = (
        round(med8s / busy8["8"], 2) if busy8["8"] > 0 else None)
    out["cfg8_busy_8dev_vs_single"] = (
        round(busy8["8"] / med8s, 2) if med8s > 0 else None)
    # the 2-device row is the only one this rig can physically parallelize
    # (2 cores); at 8 virtual devices timesharing dominates every term
    out["cfg8_busy_2dev_vs_single"] = (
        round(busy8["2"] / med8s, 2) if med8s > 0 else None)

    # free the podaxis section's 1M-pod buffers before timing the grid rows
    # (every "device" shares one host's RAM; resident-set pressure skews
    # timings — same hygiene as the cfg7 dels above)
    del giant, giant_dev, mesh8, placed8_on_mesh8, decider8_on_mesh8, blocks8

    # ---- cfg8 grid: 2-D (groups x pods) mesh, few-huge-groups shape --------
    # The round-4 finding: podaxis' replicated [N] decide tail was 165 of
    # 182 ms because node arrays ride along whole. The grid shards nodes by
    # group block, so the tail term shrinks with Sg. Same total load as cfg8
    # (1M pods / 50k nodes) but as 8 one-group blocks of 125k pods — the
    # "few huge groups" cluster the 2-D layout exists for. The tail_ms column
    # across layouts (8x1 -> 1x8) is the design's published curve: at Sg=1
    # the tail is podaxis' replicated loss, at Sg=8 it is sharded 8-fold.
    from escalator_tpu.parallel import grid as gridlib

    blocks = [
        _rng_cluster_arrays(np.random.default_rng(70 + s), 1, 125_000, 6_250,
                            mixed=True)
        for s in range(8)
    ]
    leaves8 = [c.tree_flatten()[0] for c in blocks]
    stacked8 = ClusterArrays.tree_unflatten(
        None, [np.stack(parts) for parts in zip(*leaves8, strict=True)])

    vdecide = jax.jit(jax.vmap(lambda c, t: decide_jit(c, t), in_axes=(0, None)))
    stacked_dev = jax.device_put(stacked8, devices[0])
    gmed1, _ = _timeit(
        lambda: jax.block_until_ready(vdecide(stacked_dev, now)), iters=iters)
    out["cfg8_grid_single_device_ms"] = round(gmed1, 3)
    del stacked_dev

    grid_curve = {}
    for sg in (8, 4, 2, 1):
        gmesh = gridlib.make_grid_mesh(devices, num_group_shards=sg)
        gplaced = gridlib.place_grid(stacked8, gmesh)
        grid_curve[f"{sg}x{8 // sg}"] = gridlib.time_grid_phases(
            gmesh, gplaced, _timeit=lambda f: _timeit(f, iters=iters))
        del gplaced
    out["cfg8_grid_curve_by_layout"] = grid_curve
    best = min(grid_curve.values(), key=lambda r: r["total_ms"])
    out["cfg8_grid_best_total_ms"] = best["total_ms"]
    out["cfg8_grid_speedup_vs_single"] = (
        round(gmed1 / best["total_ms"], 2) if best["total_ms"] > 0 else None)
    print(json.dumps(out))


def run_smoke() -> dict:
    """Tier-1-safe smoke mode (``python bench.py --smoke``; also driven by
    tests/test_bench_smoke.py): tiny shapes pushed through the two round-6
    hot paths — cfg8's group-block-sharded ordering tail and cfg10's blocked
    FFD — with parity ASSERTED, not just timed. A hot-path regression then
    surfaces in CI instead of at capture time, when only the numbers (which
    drift anyway on this rig) would hint at it. Returns/prints one JSON dict;
    raises AssertionError on any parity break."""
    import jax

    from escalator_tpu.core.semantics import ffd_pack_pure
    from escalator_tpu.ops import order_tail
    from escalator_tpu.ops.binpack import ffd_pack, pack_compression_stats
    from escalator_tpu.ops.kernel import decide_jit
    from escalator_tpu.parallel import mesh as meshlib, podaxis

    rng = np.random.default_rng(12)
    now = np.int64(1_700_000_000)
    out = {"smoke": True}

    # per-leg wall-clock accounting (round 15): the smoke has grown to ~10
    # legs inside the tier-1 budget — the table below names which leg a
    # runtime regression lives in, prints at the end, and persists into the
    # smoke JSON artifacts so CI runs are comparable
    leg_seconds: dict = {}
    _leg_t0 = [time.perf_counter()]

    def _leg(name: str) -> None:
        t = time.perf_counter()
        leg_seconds[name] = round(t - _leg_t0[0], 3)
        _leg_t0[0] = t

    # ---- cfg8 path: podaxis ordered decider w/ sharded tail vs single ----
    G, P, N = 8, 512, 96
    cluster = _rng_cluster_arrays(rng, G, P, N, mixed=True, tainted_frac=0.25,
                                  cordoned_frac=0.05)
    single = decide_jit(jax.device_put(cluster), now)
    mesh = meshlib.make_mesh()
    S = int(mesh.devices.size)
    out["smoke_devices"] = S
    placed = podaxis.place(podaxis.pad_pods_for_mesh(cluster, mesh), mesh)
    blocks = order_tail.assign_order_blocks(
        cluster.nodes.group, cluster.nodes.valid, S, num_groups=G)
    sharded = podaxis.make_podaxis_decider(mesh)(placed, now, blocks)
    order_fields = ("scale_down_order", "untaint_order")
    for f in single.__dataclass_fields__:
        if f in order_fields:
            continue
        np.testing.assert_array_equal(
            np.asarray(getattr(single, f)), np.asarray(getattr(sharded, f)),
            err_msg=f"cfg8 smoke: {f}")
    u_off = np.asarray(single.untainted_offsets)
    t_off = np.asarray(single.tainted_offsets)
    for g in range(G):
        np.testing.assert_array_equal(
            np.asarray(single.scale_down_order)[u_off[g]:u_off[g + 1]],
            np.asarray(sharded.scale_down_order)[u_off[g]:u_off[g + 1]],
            err_msg=f"cfg8 smoke: scale-down window g={g}")
        np.testing.assert_array_equal(
            np.asarray(single.untaint_order)[t_off[g]:t_off[g + 1]],
            np.asarray(sharded.untaint_order)[t_off[g]:t_off[g + 1]],
            err_msg=f"cfg8 smoke: untaint window g={g}")
    out["smoke_cfg8_parity"] = "ok"
    _leg("cfg8_order_tail")

    # ---- cfg10 path: blocked FFD (both scan programs) vs the golden model --
    for label, n_shapes in (("replicaset", 2), ("mixed", 0)):
        Gp, Pp, M, B = 4, 24, 5, 4
        if n_shapes:
            shapes = np.array([[500, 10**9], [250, 5 * 10**8]], np.int64)
            pick = rng.integers(0, n_shapes, (Gp, Pp))
            pc, pm = shapes[pick, 0], shapes[pick, 1]
        else:
            pc = rng.choice([100, 250, 500, 1000], (Gp, Pp)).astype(np.int64)
            pm = rng.choice([10**8, 5 * 10**8, 10**9], (Gp, Pp)).astype(np.int64)
        pv = rng.random((Gp, Pp)) < 0.9
        bc = rng.choice([1000, 2000, 4000], (Gp, M)).astype(np.int64)
        bm = rng.choice([1, 4], (Gp, M)).astype(np.int64) * 10**9
        bv = rng.random((Gp, M)) < 0.9
        tc = np.full(Gp, 2000, np.int64)
        tm = np.full(Gp, 4 * 10**9, np.int64)
        pack = ffd_pack(pc, pm, pv, bc, bm, bv, tc, tm, new_bin_budget=B)
        out[f"smoke_cfg10_{label}_path"] = pack_compression_stats(
            pc, pm, pv, tc, tm)["path"]
        for g in range(Gp):
            pods = [(int(pc[g, i]), int(pm[g, i]))
                    for i in range(Pp) if pv[g, i]]
            bins = [(int(bc[g, i]), int(bm[g, i]))
                    for i in range(M) if bv[g, i]]
            want_assign, want_new, want_unp = ffd_pack_pure(
                pods, bins, (int(tc[g]), int(tm[g])), B)
            got = [int(a) for i, a in enumerate(np.asarray(pack.assignment[g]))
                   if pv[g, i]]
            # golden bins are the valid-compacted list; map kernel bin slots
            slot_of = {s: i for i, s in
                       enumerate([i for i in range(M) if bv[g, i]])}
            mapped = [
                (-1 if a < 0 else
                 (slot_of[a] if a < M else a - M + len(bins)))
                for a in got
            ]
            assert mapped == want_assign, (label, g, mapped, want_assign)
            assert int(pack.new_nodes_needed[g]) == want_new, (label, g)
            assert int(pack.unplaced[g]) == want_unp, (label, g)
    out["smoke_cfg10_parity"] = "ok"
    # the prepass must have exercised BOTH scan programs
    assert out["smoke_cfg10_replicaset_path"] == "runs"
    assert out["smoke_cfg10_mixed_path"] == "pods"
    _leg("cfg10_ffd")

    # ---- cfg14 path: incremental delta decide vs full recompute ----------
    # A compact multi-tick run of the round-8 incremental stack (native
    # store -> DeviceClusterCache -> IncrementalDecider): steady ticks run
    # delta_decide on the compacted dirty rows, the drain tick exercises
    # the ordered aggregate-fed re-dispatch, and EVERY tick asserts
    # bit-exact parity (all fields, scale delta included) against a full
    # decide_jit on the same resident cluster — so tier-1 locks the
    # incremental/full contract, not just cfg14's timings.
    from escalator_tpu.core.arrays import ClusterArrays
    from escalator_tpu.native.statestore import NativeStateStore
    from escalator_tpu.ops.device_state import DeviceClusterCache, IncrementalDecider
    from escalator_tpu.ops.kernel import lazy_orders_decide

    Gi = 8
    store = NativeStateStore(pod_capacity=1 << 9, node_capacity=1 << 7)
    store.upsert_pods_batch([f"sp{i}" for i in range(160)],
                            np.arange(160) % Gi,
                            np.full(160, 500), np.full(160, 10**9))
    store.upsert_nodes_batch([f"sn{i}" for i in range(40)],
                             np.arange(40) % Gi,
                             np.full(40, 4000), np.full(40, 16 * 10**9))
    pods_v, nodes_v = store.as_pod_node_arrays()
    base = _rng_cluster_arrays(rng, Gi, 1, 1)
    store.drain_dirty()
    cache = DeviceClusterCache(
        ClusterArrays(groups=base.groups, pods=pods_v, nodes=nodes_v))
    inc = IncrementalDecider(cache, refresh_every=3)
    dirty_counts = []
    ordered_ticks = []
    for t in range(6):
        # steady ticks churn 5 pods in-place (5 dirty groups of 8: the
        # compaction is observably selective); ticks 4-5 cheapen 60 pods so
        # every group falls below taint_lower — a drain begins (ordered)
        n, cpu = (5, 500) if t < 4 else (60, 100)
        idx = (t * 12 + np.arange(n)) % 160
        store.upsert_pods_batch([f"sp{i}" for i in idx], idx % Gi,
                                np.full(n, cpu), np.full(n, 10**9))
        if t == 5:
            # flip taints on 3 nodes between the two ordered ticks: their
            # sort keys change, so the second ordered tick exercises the
            # round-10 order-state REPAIR merge (not just the bootstrap
            # sort) — and parity below still asserts against the full sort
            tn = np.array([1, 9, 17])
            store.upsert_nodes_batch(
                [f"sn{i}" for i in tn], tn % Gi,
                np.full(3, 4000), np.full(3, 16 * 10**9),
                tainted=np.ones(3, bool),
                taint_time_sec=np.full(3, int(now) - 50))
        pd, nd = store.drain_dirty()
        inc.apply_gathered(cache.gather_deltas(pd, nd))
        out_i, ordered = inc.decide(now, False)
        ref, ref_ordered = lazy_orders_decide(
            lambda w: jax.block_until_ready(
                decide_jit(cache.cluster, now, with_orders=w)), False)
        assert ordered == ref_ordered, f"cfg14 smoke tick {t}: protocol"
        for f in ref.__dataclass_fields__:
            np.testing.assert_array_equal(
                np.asarray(getattr(out_i, f)), np.asarray(getattr(ref, f)),
                err_msg=f"cfg14 smoke tick {t}: {f}")
        dirty_counts.append(inc.last_dirty_count)
        ordered_ticks.append(bool(ordered))
    # both protocol paths must have run, the dirty set must have been
    # selective, and the cadence audit must have fired clean
    assert any(ordered_ticks) and not all(ordered_ticks), ordered_ticks
    assert any(0 < c < Gi for c in dirty_counts), dirty_counts
    assert inc.refreshes >= 1
    # round 10: the cadence audits above ran in BACKGROUND mode (the
    # default) — drain must reconcile every in-flight verdict clean, i.e.
    # the double-buffer snapshot froze exactly the maintained state
    assert inc.drain_audit(), "background refresh audit reported a mismatch"
    # and the ordered ticks ran the incremental order path: bootstrap on
    # the first, the rank-repair merge once taints flipped keys — with the
    # per-tick field loop above having asserted the permutation BIT-EXACT
    # against the full-sort decide on every ordered tick
    assert inc.order_stats.get("bootstrap", 0) >= 1, inc.order_stats
    assert inc.order_stats.get("repair", 0) >= 1, inc.order_stats
    out["smoke_cfg14_parity"] = "ok"
    out["smoke_cfg14_dirty_counts"] = dirty_counts
    out["smoke_order_paths"] = dict(inc.order_stats)
    _leg("cfg14_incremental")

    # ---- replay smoke (round 11): snapshot -> record -> dump -> debug-replay
    # The failover/replay acceptance loop at smoke scale, driven through the
    # REAL artifact path: checkpoint the decider, record four more churn
    # ticks' inputs, dump the ring (now a self-contained replay bundle), and
    # re-execute it via the actual `escalator-tpu debug-replay` verb —
    # asserting identical per-tick crc32 decision digests. The report ships
    # as REPLAY_SMOKE_LATEST.json, uploaded by CI next to the jaxlint
    # report.
    import tempfile

    from escalator_tpu.observability import RECORDER
    from escalator_tpu.observability import replay as replaymod
    from escalator_tpu.ops import snapshot as snaplib

    replay_dir = tempfile.mkdtemp(prefix="escalator-replay-smoke-")
    try:
        leaves, snap_meta = inc.snapshot_state()
        snap_path = snaplib.write_snapshot(
            snaplib.latest_path(replay_dir), leaves, snap_meta)
        replaymod.INPUT_LOG.clear()
        replaymod.INPUT_LOG.set_enabled(True)
        want_digests = []
        for t in range(6, 10):
            n_churn, cpu = 5, 400 + 10 * t
            idx = (t * 12 + np.arange(n_churn)) % 160
            store.upsert_pods_batch([f"sp{i}" for i in idx], idx % Gi,
                                    np.full(n_churn, cpu),
                                    np.full(n_churn, 10**9))
            pd, nd = store.drain_dirty()
            inc.apply_gathered(cache.gather_deltas(pd, nd))
            out_r, _ordered_r = inc.decide(now, False)
            want_digests.append(replaymod.decision_digest(out_r))
        replaymod.INPUT_LOG.set_enabled(False)
        ring_path = os.path.join(replay_dir, "ring.json")
        RECORDER.dump(ring_path, reason="replay-smoke")
        from escalator_tpu.cli import main as cli_main

        report_path = os.path.join(replay_dir, "report.json")
        rc = cli_main(["debug-replay", "--dump", ring_path,
                       "--snapshot", snap_path, "--output", report_path])
        assert rc == 0, f"debug-replay exited {rc}"
        with open(report_path) as f:
            replay_report = json.load(f)
        assert replay_report["ok"] and replay_report["replayed"] == 4, (
            replay_report)
        got_digests = [r["digest"] for r in replay_report["ticks"]]
        assert got_digests == want_digests, (got_digests, want_digests)
    finally:
        import shutil

        replaymod.INPUT_LOG.set_enabled(False)
        replaymod.INPUT_LOG.clear()
        shutil.rmtree(replay_dir, ignore_errors=True)
    out["smoke_replay_digests"] = want_digests
    out["smoke_replay"] = "ok"
    replay_artifact = os.environ.get(
        "ESCALATOR_TPU_REPLAY_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "REPLAY_SMOKE_LATEST.json"),
    )
    out["replay_smoke_report"] = write_smoke_artifact(
        replay_artifact, replay_report)
    _leg("replay")

    # ---- streaming ingestion smoke (round 12): event-driven vs re-list ---
    # The tentpole's parity contract at smoke scale, through the REAL event
    # pipeline: an EventfulClient world flows through WatchBridge into BOTH
    # store kinds (numpy always; C++ when the toolchain is present), each
    # tick drains as a packed delta batch into an IncrementalDecider, and
    # the decision digest is asserted equal to the RE-LIST path (filtered
    # listers -> pack_cluster -> full light decide) on every tick — across
    # pod updates, delete-then-re-add of the same UID inside one tick
    # window, node deletion with slot reuse, a group move, and a taint
    # (ordered) tick.
    from escalator_tpu.controller.native_backend import NativeJaxBackend
    from escalator_tpu.core import semantics as sem
    from escalator_tpu.core.arrays import pack_cluster, pack_groups
    from escalator_tpu.k8s import types as k8s_types
    from escalator_tpu.k8s.cache import WatchBridge
    from escalator_tpu.k8s.listers import relist_group_inputs
    from escalator_tpu.native.statestore import (
        available as native_available,
        make_state_store,
    )
    from escalator_tpu.observability import spans as _spans
    from escalator_tpu.observability.replay import decision_digest

    # ONE world definition, shared with tests/test_event_ingest_parity.py —
    # the smoke and the test suite must assert the same parity contract
    from escalator_tpu.testsupport.streamworld import (
        stream_configs as make_stream_configs,
        stream_filters,
        stream_node,
        stream_pod,
        stream_world,
    )

    stream_configs = make_stream_configs(2)

    def smoke_world():
        return stream_world(nodes_per_group=5, pods_per_group=22)

    def mutate(client, t, nowi):
        if t == 1:      # pod resource updates (MODIFIED)
            for i in range(4):
                client.update_pod(stream_pod(
                    f"alpha-p{i}", "alpha", cpu=900,
                    node=f"alpha-n{i % 5}"))
        elif t == 2:    # delete-then-re-add the SAME uid in one tick window
            victim = [p for p in client.list_pods()
                      if p.name == "beta-p3"][0]
            client.remove_pod(victim)
            client.add_pod(stream_pod(
                "beta-p3", "beta", cpu=2000, mem=2 * 10**9))
        elif t == 3:    # node deletion + slot reuse by a NEW node
            client.delete_node("alpha-n2")
            client.add_node(stream_node("alpha-n9", "alpha", creation=77))
        elif t == 4:    # group move: a pod's selector flips alpha -> beta
            client.update_pod(stream_pod("alpha-p7", "beta"))
        elif t == 5:    # taint: the ordered (lazy re-dispatch) tick
            n = [nd for nd in client.list_nodes()
                 if nd.name == "beta-n1"][0].copy()
            n.taints.append(k8s_types.Taint(
                key=k8s_types.TO_BE_REMOVED_BY_AUTOSCALER_KEY,
                value=str(nowi - 50)))
            client.update_node(n)

    kinds = ["numpy"] + (["native"] if native_available() else [])
    out["smoke_streaming_store_kinds"] = kinds
    nowi = int(now)
    for kind in kinds:
        client = smoke_world()
        filters = stream_filters()
        store_k = make_state_store(pod_capacity=256, node_capacity=64,
                                   kind=kind)
        bridge = WatchBridge(store_k, filters)
        client.subscribe(bridge.apply, replay=True)
        states = [sem.GroupState() for _ in range(2)]
        pods_v, nodes_v = store_k.as_pod_node_arrays()
        groups_k = pack_groups(
            list(zip(stream_configs, states, strict=True)), pad_groups=8)
        store_k.drain_dirty()
        cache_k = DeviceClusterCache(ClusterArrays(
            groups=groups_k, pods=pods_v, nodes=nodes_v))
        inc_k = IncrementalDecider(cache_k, refresh_every=0)
        inc_k.decide(nowi, False)     # bootstrap
        root = f"cfg16_smoke_{kind}"
        for t in range(6):
            mutate(client, t, nowi)
            with _spans.span(root):
                with _spans.span("event_drain"):
                    gathered = store_k.drain_dirty_packed()
                with _spans.span("triple_build"):
                    tainted_any = bool(
                        (np.asarray(nodes_v.valid)
                         & np.asarray(nodes_v.tainted)).any())
                with _spans.span("scatter", kind="device"):
                    inc_k.apply_gathered(gathered)
                out_s, _ordered_s = inc_k.decide(nowi, tainted_any)
            # the RE-LIST reference path on the same world
            gi_rel = relist_group_inputs(
                client, filters, stream_configs, states)
            rel_cluster = pack_cluster(gi_rel, pad_pods=512, pad_nodes=64,
                                       pad_groups=8)
            full = jax.block_until_ready(decide_jit(
                jax.device_put(rel_cluster), np.int64(nowi),
                with_orders=False))
            assert decision_digest(out_s) == decision_digest(full), (
                f"streaming vs re-list digest diverged: kind={kind} tick={t}")
        out[f"smoke_streaming_parity_{kind}"] = "ok"
        del inc_k, cache_k, store_k

    # the REAL streaming backend, one rebuild + three steady ticks: the new
    # phase taxonomy (event_drain / triple_build, plus the overlap hook's
    # event_predrain on the delta tick) must be what production records
    client3 = smoke_world()
    backend3 = NativeJaxBackend(
        client3, stream_filters(), pod_capacity=256, node_capacity=64,
        incremental=True, refresh_every=0)
    gi_cfg = [([], [], stream_configs[g], sem.GroupState())
              for g in range(2)]
    backend3.decide(gi_cfg, nowi)          # rebuild tick
    for i in range(3):                     # steady ticks: packed fast path
        client3.add_pod(stream_pod(f"alpha-late{i}", "alpha", cpu=250,
                                   mem=10**8))
        backend3.decide(gi_cfg, nowi + 60 * (i + 1))
    recs3 = [r for r in RECORDER.snapshot() if r["root"] == "native-jax"]
    names3 = {p["name"] for r in recs3 for p in r["phases"]}
    assert {"event_drain", "triple_build"} <= names3, sorted(names3)
    assert "event_predrain" in names3, sorted(names3)
    assert recs3[-1].get("store") in ("native", "numpy"), recs3[-1]
    out["smoke_streaming_backend_store"] = recs3[-1].get("store")
    out["smoke_streaming_phases"] = "ok"

    # host-phase breakdown artifact: per-phase medians of the streaming
    # smoke ticks + the real backend's STEADY ticks, from the flight
    # recorder — uploaded by CI next to FLIGHT_SMOKE_LATEST.json so the
    # host tail is attributable per PR run. The rebuild tick (full upload +
    # compile inside its scatter span) is excluded: medianing it in made
    # the summary read a ~500 ms "steady" scatter (it is identifiable as
    # the record without a delta_decide phase).
    steady3 = [r for r in recs3
               if any(p["name"] == "delta_decide" for p in r["phases"])]
    backend_tick_ms = _phase_stats_from_records(steady3)
    assert backend_tick_ms["_ticks"] >= 3, backend_tick_ms
    host_phases = {
        "smoke": True,
        "native_backend_tick_ms": backend_tick_ms,
        "streaming_ticks_ms": {
            kind: _recorder_phase_stats(f"cfg16_smoke_{kind}")
            for kind in kinds
        },
    }
    host_phase_path = os.environ.get(
        "ESCALATOR_TPU_HOST_PHASES_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "HOST_PHASES_SMOKE_LATEST.json"),
    )
    out["host_phases_report"] = write_smoke_artifact(
        host_phase_path, host_phases)
    _leg("streaming")

    # ---- flight recorder: populated, named phases, bounded overhead ------
    # The 6 incremental ticks above ran through the instrumented
    # IncrementalDecider, so the recorder must hold their records with the
    # protocol's phase names (delta_decide on steady ticks, decide_ordered
    # on the drain re-dispatch, refresh_audit from the cadence audit).
    from escalator_tpu.observability import RECORDER

    assert RECORDER.depth > 0, "flight recorder is empty after smoke ticks"
    records = RECORDER.snapshot()
    phase_names = {
        p["name"] for rec in records for p in rec["phases"]
    }
    root_names = {rec["root"] for rec in records}
    assert "delta_decide" in phase_names, sorted(phase_names)
    # round 10: the drain re-dispatch runs the incremental ordered program
    # (order-state repair inside), and the cadence audit splits into the
    # on-path snapshot copy + the worker-thread refresh_audit_bg timeline
    assert "decide_ordered_incremental" in phase_names, sorted(phase_names)
    assert "order_repair" in phase_names, sorted(phase_names)
    assert "audit_snapshot" in phase_names, sorted(phase_names)
    assert "refresh_audit_bg" in root_names, sorted(root_names)
    # every delta_decide phase is device-FENCED (the device-time contract)
    for rec in RECORDER.snapshot():
        for p in rec["phases"]:
            if p["name"] == "delta_decide":
                assert p["fenced"], rec
    out["smoke_flight_recorder_depth"] = RECORDER.depth

    # instrumentation overhead at smoke scale: the cfg14 helper's
    # interleaved enabled/disabled arms around the same steady tick. The
    # full-scale bound (<1% on cfg14, tens-of-ms ticks) lives in the bench
    # artifact; at sub-ms smoke shapes on a noisy CI core a relative bound
    # flakes, so the gate here is the absolute form: the added cost of ~8
    # span sites must stay under 0.75 ms (the real cost is ~40 us; the
    # margin absorbs timer noise, and a genuine regression — a blocking
    # dump or an O(cluster) hook on the span path — blows through it
    # immediately).
    ovh = _observability_overhead(store, cache, inc, now, 160, Gi, 500,
                                  iters=15, n_churn=5)
    assert ovh["overhead_ms"] < 0.75, (
        f"span overhead {ovh['overhead_ms']:.3f} ms (enabled min "
        f"{ovh['enabled_min_ms']:.3f} / disabled min "
        f"{ovh['disabled_min_ms']:.3f}) — instrumentation grew a real cost")
    out["smoke_observability_overhead_ms"] = ovh["overhead_ms"]
    _leg("recorder_overhead")

    # ---- tail-latency smoke (round 13): histogram accuracy, tail-capture
    # fire path, trace-export round-trip — the ISSUE-8 acceptance loop at
    # smoke scale, written to TAIL_SMOKE_LATEST.json for CI upload.
    from escalator_tpu.observability import histograms as hgmod
    from escalator_tpu.observability import tail as tailmod

    tail_report: dict = {"smoke": True}

    # (a) quantile accuracy: the streaming log-bucket engine vs
    # np.percentile ground truth on adversarial distributions. The
    # contract: every quantile within ONE bucket width (<= 25% relative)
    # of the exact order statistic — bimodal (quantiles straddle the modes),
    # heavy tail (pareto: p999 far from p50), and the single-sample
    # degenerate case where every quantile IS the sample.
    rng_t = np.random.default_rng(13)
    acc: dict = {}
    for dist_name, samples in (
        ("bimodal", np.concatenate([rng_t.normal(2e-3, 3e-4, 4000),
                                    rng_t.normal(8e-2, 1e-2, 250)])),
        ("heavy_tail", (rng_t.pareto(1.5, 4000) + 1) * 1e-4),
        ("single_sample", np.array([1.23e-2])),
    ):
        samples = np.clip(samples, 1e-7, 9.0)
        h = hgmod.LogHistogram()
        for s in samples:
            h.record(float(s))
        dist_rows = {}
        for q in (50.0, 90.0, 99.0, 99.9):
            gt = float(np.percentile(samples, q))
            got = h.quantile(q / 100.0)
            lo_e, hi_e = hgmod.bucket_bounds(gt)
            width = hi_e - lo_e
            assert abs(got - gt) <= width + 1e-12, (
                f"histogram p{q:g} off by more than a bucket on "
                f"{dist_name}: got {got:.6g} vs ground truth {gt:.6g} "
                f"(bucket width {width:.3g})")
            dist_rows[f"p{q:g}"] = {
                "ground_truth_ms": round(gt * 1e3, 6),
                "histogram_ms": round(got * 1e3, 6),
                "bucket_width_ms": round(width * 1e3, 6),
            }
        acc[dist_name] = dist_rows
    tail_report["quantile_accuracy"] = acc
    out["smoke_tail_quantile_accuracy"] = "ok"

    # production feed check: the smoke's real backend ticks above landed in
    # the histograms through the SAME root-complete hook the recorder uses
    # (event_drain is always a LEAF phase; delta_decide is a composite when
    # the overlap hook nests event_predrain under it, and composites stay
    # out of the per-phase series — same selection as the Prometheus feed)
    drain_hist = hgmod.PHASES.peek("native-jax", "event_drain")
    assert drain_hist is not None and drain_hist.count > 0, (
        "streaming backend ticks missing from the phase histograms")
    assert hgmod.tick_quantiles_ms()["count"] > 0
    tail_report["native_backend_tick_quantiles_ms"] = hgmod.tick_quantiles_ms(
        "native-jax")

    # (b) the tail-capture fire path through the REAL hook chain: seed a
    # root series with fast ticks, breach with a forced slow tick, and
    # assert the reason="tail" dump landed with the breach annotation —
    # then that an immediate second breach is rate-limited away.
    tail_dir = tempfile.mkdtemp(prefix="escalator-tail-smoke-")
    prev_env = {k: os.environ.get(k) for k in (
        "ESCALATOR_TPU_TAIL_CAPTURE", "ESCALATOR_TPU_TAIL_MIN_TICKS",
        "ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC", "ESCALATOR_TPU_DUMP_DIR")}
    # min_ticks == the number of seed ticks: the watchdog arms exactly at
    # the forced slow tick, so a jittery CI core can't breach on a noisy
    # seed tick and steal the rate-limit slot from the one this asserts on
    os.environ.update({
        "ESCALATOR_TPU_TAIL_CAPTURE": "3.0",
        "ESCALATOR_TPU_TAIL_MIN_TICKS": "10",
        "ESCALATOR_TPU_TAIL_DUMP_INTERVAL_SEC": "600",
        "ESCALATOR_TPU_DUMP_DIR": tail_dir,
    })
    try:
        tailmod.WATCHDOG.reset()
        for _ in range(10):
            with _spans.span("tail_smoke_tick"):
                _spans.annotate(backend="tail-smoke")
                with _spans.span("steady_work"):
                    time.sleep(0.002)
        with _spans.span("tail_smoke_tick"):
            _spans.annotate(backend="tail-smoke")
            with _spans.span("slow_work"):
                time.sleep(0.05)   # ~25x steady: an unambiguous breach
        tailmod.WATCHDOG.drain()
        tail_dumps = [f for f in os.listdir(tail_dir) if "-tail-" in f]
        assert len(tail_dumps) == 1, (
            f"expected exactly one tail dump, found {tail_dumps}")
        with open(os.path.join(tail_dir, tail_dumps[0])) as f:
            tail_doc = json.load(f)
        assert tail_doc["reason"] == "tail" and tail_doc["flight_recorder"]
        breach = tail_doc["tail"]
        assert breach["root"] == "tail_smoke_tick", breach
        assert breach["duration_ms"] > breach["threshold_ms"], breach
        # the bundle carries the breaching tick's span tree
        assert any(r.get("seq") == breach["seq"] and any(
            p["name"] == "slow_work" for p in r["phases"])
            for r in tail_doc["ticks"]), "breaching tick not in the bundle"
        # rate limit: another breach inside the interval must NOT dump again
        with _spans.span("tail_smoke_tick"):
            with _spans.span("slow_work"):
                time.sleep(0.05)
        tailmod.WATCHDOG.drain()
        assert len([f for f in os.listdir(tail_dir) if "-tail-" in f]) == 1
        tail_report["tail_capture"] = breach
        out["smoke_tail_capture"] = "ok"
    finally:
        for k, v in prev_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        tailmod.WATCHDOG.reset()

    # (c) trace export round-trip through the REAL `debug-trace` verb: a
    # plugin-routed decide (in-process gRPC server when the toolchain has
    # grpc; the same graft mechanism synthetically otherwise) so the
    # exported Perfetto JSON shows client AND server spans in one trace.
    trace_mode = "grpc"
    try:
        from escalator_tpu.plugin.client import ComputeClient
        from escalator_tpu.plugin.server import make_server
    except ImportError as e:
        trace_mode = f"synthetic-graft (grpc unavailable: {e.name})"
    tiny = _rng_cluster_arrays(rng, 2, 64, 16)
    if trace_mode == "grpc":
        server = make_server("127.0.0.1:0", max_workers=2)
        server.start()
        tclient = ComputeClient(
            f"127.0.0.1:{server._escalator_bound_port}", timeout_sec=120.0)
        try:
            with _spans.span("tail_trace_tick"):
                _spans.annotate(backend="grpc")
                with _spans.span("rpc", kind="rpc"):
                    _t_out, server_phases = tclient.decide_arrays_traced(
                        tiny, int(now),
                        span_ctx={"path": _spans.current_path()})
                _spans.graft(server_phases or [],
                             under="tail_trace_tick/rpc")
        finally:
            tclient.close()
            server.stop(grace=None)
    else:
        with _spans.span("tail_trace_tick"):
            _spans.annotate(backend="grpc")
            with _spans.span("rpc", kind="rpc"):
                time.sleep(0.001)
            _spans.graft(
                [{"name": "decide", "path": "plugin_decide/decide",
                  "ms": 0.8, "kind": "device", "fenced": True,
                  "offset_ms": 0.1}],
                under="tail_trace_tick/rpc")
    trace_dump_path = os.path.join(tail_dir, "trace-dump.json")
    RECORDER.dump(trace_dump_path, reason="trace-smoke")
    trace_out_path = os.path.join(tail_dir, "smoke.trace.json")
    rc = cli_main(["debug-trace", "--dump", trace_dump_path,
                   "--output", trace_out_path])
    assert rc == 0, f"debug-trace exited {rc}"
    with open(trace_out_path) as f:
        trace_doc = json.load(f)
    slices = [e for e in trace_doc["traceEvents"] if e.get("ph") == "X"]
    for e in trace_doc["traceEvents"]:
        assert {"name", "ph", "pid", "tid"} <= set(e), e
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)), e
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0, e
    tick_evs = [e for e in slices
                if str(e["args"].get("path", "")).startswith(
                    "tail_trace_tick")]
    assert any(e["name"] == "rpc" and not e["args"].get("remote")
               for e in tick_evs), "client rpc span missing from trace"
    assert any(e["args"].get("remote") and e["name"] == "decide"
               for e in tick_evs), "plugin-server span missing from trace"
    tail_report["trace_export"] = {
        "mode": trace_mode,
        "trace_events": len(slices),
        "client_and_server_merged": True,
    }
    out["smoke_trace_export"] = "ok"

    # artifacts: the tail report + the exported trace, both uploaded by CI
    # with run-summary digests (next to the flight/jaxlint artifacts)
    tail_artifact = os.environ.get(
        "ESCALATOR_TPU_TAIL_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "TAIL_SMOKE_LATEST.json"),
    )
    trace_artifact = os.environ.get(
        "ESCALATOR_TPU_TRACE_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "TRACE_SMOKE_LATEST.trace.json"),
    )
    out["tail_smoke_report"] = write_smoke_artifact(tail_artifact, tail_report)
    try:
        shutil.copyfile(trace_out_path, trace_artifact)
        out["trace_smoke_artifact"] = trace_artifact
    except OSError:   # read-only checkout: the in-memory asserts still ran
        out["trace_smoke_artifact"] = "(write failed)"
    shutil.rmtree(tail_dir, ignore_errors=True)
    _leg("tail_trace")

    # ---- fleet smoke (round 14): C=8 tenants through the REAL gRPC fleet
    # server — coalescing observed, per-tenant 13-column digests equal the
    # single-cluster decide, and the backpressure path fires under a
    # flooded queue (RESOURCE_EXHAUSTED + retry-after trailer). Written to
    # FLEET_SMOKE_LATEST.json for CI upload.
    import threading as _threading

    from escalator_tpu.analysis.registry import representative_cluster
    from escalator_tpu.observability.replay import decision_digest
    from escalator_tpu.ops import kernel as _fk

    fleet_report: dict = {"smoke": True}
    try:
        import grpc as _grpc

        from escalator_tpu.plugin.client import ComputeClient as _FC
        from escalator_tpu.plugin.server import FleetConfig, make_server
        fleet_mode = "grpc"
    except ImportError as e:   # pragma: no cover - CI installs grpcio
        fleet_mode = f"skipped (grpc unavailable: {e.name})"
    if fleet_mode == "grpc":
        Gf, Pf, Nf = 6, 24, 12
        # round 16: the smoke server runs the MESH-SHARDED engine (4 shards
        # under the forced multi-device CPU, fewer when the rig has fewer)
        # with the pipelined scheduler — the CI leg asserts sharded-vs-
        # unsharded digest parity through the real gRPC path below
        fleet_shards = min(4, len(jax.devices()))
        fsrv = make_server("127.0.0.1:0", max_workers=16, fleet=FleetConfig(
            num_groups=Gf, pod_capacity=Pf, node_capacity=Nf, max_tenants=8,
            max_batch=8, flush_ms=10.0, queue_limit=64,
            per_tenant_inflight=1, num_shards=fleet_shards))
        fsrv.start()
        fclient = _FC(f"127.0.0.1:{fsrv._escalator_bound_port}",
                      timeout_sec=300.0)
        try:
            # warm the fleet-step jit so the concurrent burst below measures
            # batching, not the first compile
            fclient.decide_arrays_fleet(
                representative_cluster(Gf, Pf, Nf, seed=899), int(now),
                "warmup")
            tenants = {f"ft{i}": representative_cluster(Gf, Pf, Nf,
                                                        seed=900 + i)
                       for i in range(8)}
            fres: dict = {}
            flock = _threading.Lock()

            def _one(tid, c):
                o, _p, meta = fclient.decide_arrays_fleet(c, int(now), tid)
                with flock:
                    fres[tid] = (o, meta)

            # deterministic coalescing: all eight tenants enqueue against a
            # paused worker, then one resume serves them as ONE micro-batch
            fsched0 = fsrv._escalator_service.fleet
            fsched0.pause()
            fthreads = [_threading.Thread(target=_one, args=kv)
                        for kv in tenants.items()]
            for t in fthreads:
                t.start()
            deadline = time.monotonic() + 30
            while (fsched0.queue_depth < len(tenants)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            fsched0.resume()
            for t in fthreads:
                t.join()
            batch_sizes = sorted(meta["batch_size"]
                                 for _o, meta in fres.values())
            # per-tenant digest parity: each fleet response's decision
            # digest equals the tenant's standalone single-cluster decide
            # AND (round 16) an UNSHARDED single-device FleetEngine's
            # decision on the same requests — the sharded-vs-unsharded
            # parity lock, through the real gRPC server
            from escalator_tpu.fleet import (
                DecideRequest as _FDR,
                FleetEngine as _FE,
            )

            eng_unsharded = _FE(num_groups=Gf, pod_capacity=Pf,
                                node_capacity=Nf, max_tenants=8,
                                num_shards=1)
            unsharded = {
                r.tenant_id: r for r in eng_unsharded.step(
                    [_FDR(tid, c, int(now))
                     for tid, c in tenants.items()])}
            shard_ids = set()
            for tid, c in tenants.items():
                o, meta = fres[tid]
                ref = _fk.decide_jit(jax.device_put(c), np.int64(int(now)))
                assert decision_digest(o) == decision_digest(ref), (
                    f"fleet smoke digest diverged for {tid}")
                assert (decision_digest(o)
                        == decision_digest(unsharded[tid].arrays)), (
                    f"fleet smoke sharded-vs-unsharded digest diverged "
                    f"for {tid}")
                for fld in _fk.GROUP_DECISION_FIELDS:
                    np.testing.assert_array_equal(
                        np.asarray(getattr(o, fld)),
                        np.asarray(getattr(ref, fld)),
                        err_msg=f"fleet smoke {tid}: {fld}")
                shard_ids.add(meta.get("shard"))
            if fleet_shards > 1:
                assert len(shard_ids) > 1, (
                    f"tenants did not spread across shards: {shard_ids}")
            # the scheduler actually coalesced concurrent tenants
            assert batch_sizes[-1] >= 2, batch_sizes
            fleet_report["tenants"] = len(tenants)
            fleet_report["batch_sizes"] = batch_sizes
            fleet_report["shards"] = fleet_shards
            fleet_report["tenant_shards"] = sorted(
                int(s) for s in shard_ids if s is not None)
            fleet_report["sharded_vs_unsharded_parity"] = "ok"
            out["smoke_fleet_parity"] = "ok"
            out["smoke_fleet_shards"] = fleet_shards
            out["smoke_fleet_max_batch"] = batch_sizes[-1]

            # pipelined-overlap visibility: every fleet_batch record now
            # carries overlap_host_ms (prep wall); overlap_saved_ms shows
            # where prep ran under an in-flight dispatch (burst-dependent
            # at smoke scale — reported, not asserted positive)
            from escalator_tpu.observability import RECORDER as _FREC

            fb_recs = [r for r in _FREC.snapshot()
                       if r.get("root") == "fleet_batch"]
            assert fb_recs and any(
                r.get("overlap_host_ms") is not None for r in fb_recs), (
                "fleet_batch records carry no overlap_host_ms")
            fleet_report["overlap"] = {
                "pipelined": True,
                "overlap_host_ms": [r.get("overlap_host_ms")
                                    for r in fb_recs[-4:]],
                "overlap_saved_ms": [r.get("overlap_saved_ms")
                                     for r in fb_recs[-4:]],
            }
            out["smoke_fleet_overlap_fields"] = "ok"

            # backpressure: flood a PAUSED worker past a queue bound of 4 —
            # the overflow rejects with RESOURCE_EXHAUSTED + retry-after
            # trailer, the rest serve after resume
            fsched = fsrv._escalator_service.fleet
            fsched.queue_limit = 4
            fsched.pause()
            flood_out: list = []

            def _flood(i):
                try:
                    fclient.decide_arrays_fleet(
                        representative_cluster(Gf, Pf, Nf, seed=950 + i),
                        int(now), f"flood{i}", max_attempts=1)
                    with flock:
                        flood_out.append("ok")
                except _grpc.RpcError as e:
                    md = dict(e.trailing_metadata() or ())
                    with flock:
                        flood_out.append((
                            e.code().name,
                            md.get("escalator-retry-after-ms")))

            flood_threads = [_threading.Thread(target=_flood, args=(i,))
                             for i in range(6)]
            rejected0 = fsched.rejected_total
            for t in flood_threads:
                t.start()
            deadline = time.monotonic() + 10
            while (fsched.queue_depth + (fsched.rejected_total - rejected0)
                   < 6 and time.monotonic() < deadline):
                time.sleep(0.02)
            fsched.resume()
            for t in flood_threads:
                t.join()
            rejected = [o for o in flood_out if o != "ok"]
            assert flood_out.count("ok") == 4 and len(rejected) == 2, (
                flood_out)
            for code, retry_after in rejected:
                assert code == "RESOURCE_EXHAUSTED" and retry_after, (
                    flood_out)
            fleet_report["backpressure"] = {
                "served": flood_out.count("ok"),
                "rejected": len(rejected),
                "retry_after_ms": [float(r[1]) for r in rejected],
            }
            out["smoke_fleet_backpressure"] = "ok"

            # round 18: streaming ingestion + the digest fast path through
            # the SAME real server. A FleetStreamSession ships a full
            # frame, churns its store twin and ships a DELTA frame — the
            # answer must digest-equal both a standalone decide on the
            # store content and the diff path (the same content as a full
            # frame under a second tenant). An unchanged repeat must then
            # answer from the cache: hit counted, batch_size 0, `cached`
            # journey stage present.
            from dataclasses import fields as _dcfields

            from escalator_tpu.core.arrays import ClusterArrays as _SCA
            from escalator_tpu.plugin.client import (
                FleetStreamSession as _FSS,
            )

            fengine = fsrv._escalator_service.fleet.engine
            ssess = _FSS(fclient, "smoke-stream", pod_capacity=Pf,
                         node_capacity=Nf, store_kind="numpy")
            sgroups = representative_cluster(Gf, Pf, Nf, seed=970).groups
            ssess.set_groups(sgroups)
            for i in range(8):
                ssess.store.upsert_pod(f"sp{i}", i % Gf, 400 + 20 * i,
                                       10 ** 9, i % 5)
            for i in range(5):
                ssess.store.upsert_node(f"sn{i}", i % Gf, 4000,
                                        16 * 10 ** 9, tainted=(i == 4))

            def _stream_content():
                def copy(soa):
                    return type(soa)(**{
                        f.name: np.array(getattr(soa, f.name))
                        for f in _dcfields(soa)})
                pods, nodes = ssess.store.as_pod_node_arrays()
                return _SCA(groups=copy(sgroups), pods=copy(pods),
                            nodes=copy(nodes))

            o_full, _p, m_full = ssess.decide(int(now))
            ssess.store.upsert_pod("sp2", 2, 3000, 4 * 10 ** 9, 1)
            ssess.store.delete_pod("sp6")
            ssess.store.upsert_node("sn5", 5, 8000, 32 * 10 ** 9)
            o_delta, _p, m_delta = ssess.decide(int(now) + 60)
            assert ssess.full_frames == 1 and ssess.delta_frames == 1
            content = _stream_content()
            ref = _fk.decide_jit(jax.device_put(content),
                                 np.int64(int(now) + 60))
            o_diff, _p, m_diff = fclient.decide_arrays_fleet(
                content, int(now) + 60, "smoke-diff")
            assert (decision_digest(o_delta) == decision_digest(ref)
                    == decision_digest(o_diff)), (
                "fleet smoke: streamed-delta vs diff-path digests diverged")
            # unchanged repeat: the digest fast path answers, no dispatch
            hits0 = int(fengine.cache_hits)
            o_hit, _p, m_hit = ssess.decide(int(now) + 60)
            assert m_hit["cached"] and m_hit["batch_size"] == 0, m_hit
            assert int(fengine.cache_hits) == hits0 + 1
            assert "cached" in (m_hit.get("journey") or {}).get(
                "stages_ms", {}), m_hit.get("journey")
            assert decision_digest(o_hit) == decision_digest(o_delta), (
                "fleet smoke: cached answer diverged from its dispatch")
            fleet_report["streaming"] = {
                "full_frames": ssess.full_frames,
                "delta_frames": ssess.delta_frames,
                "delta_vs_diff_parity": "ok",
            }
            fleet_report["cache_hits"] = int(fengine.cache_hits)
            fleet_report["tail_batched"] = int(fengine.tail_dispatches)
            out["smoke_fleet_streaming_parity"] = "ok"
            out["smoke_fleet_cache_hits"] = int(fengine.cache_hits)

            fh = fclient.health()
            fleet_report["health_fleet"] = fh["fleet"]
            assert fh["fleet"]["rejected_total"] >= 2
        finally:
            fclient.close()
            fsrv.stop(grace=None)
    fleet_report["mode"] = fleet_mode
    out["smoke_fleet_mode"] = fleet_mode
    fleet_artifact = os.environ.get(
        "ESCALATOR_TPU_FLEET_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "FLEET_SMOKE_LATEST.json"),
    )
    out["fleet_smoke_report"] = write_smoke_artifact(
        fleet_artifact, fleet_report)
    _leg("fleet")

    # ---- request-journey smoke (round 17): a MULTI-CLASS run through the
    # real gRPC fleet server proving (1) every request's five journey
    # stages sum to its endpoint e2e within 5% (dispatch device-fenced),
    # (2) the journal round-trips through the real Journal RPC, (3) the
    # journey+journal hook cost stays <1% of a smoke-scale fleet batch,
    # and (4) debug-trace renders per-request journey tracks with the
    # client-side submit→response slice wrapping the grafted server
    # journey. Written to JOURNEY_SMOKE_LATEST.json for CI upload.
    journey_report: dict = {"smoke": True, "mode": fleet_mode}
    if fleet_mode == "grpc":
        from escalator_tpu import observability as _obs
        from escalator_tpu.observability import histograms as _jh
        from escalator_tpu.observability import journal as _jj
        from escalator_tpu.observability import traceexport as _jt

        # the canonical stage set (one definition — a stage added there
        # must fail here, not silently under-assert)
        _JSTAGES = _jh.JOURNEY_STAGES
        _JSTAGES_ALL = _JSTAGES + ("service",)

        jsrv = make_server("127.0.0.1:0", max_workers=16, fleet=FleetConfig(
            num_groups=Gf, pod_capacity=Pf, node_capacity=Nf, max_tenants=8,
            max_batch=8, flush_ms=10.0, queue_limit=64,
            per_tenant_inflight=1, num_shards=fleet_shards))
        jsrv.start()
        jclient = _FC(f"127.0.0.1:{jsrv._escalator_bound_port}",
                      timeout_sec=300.0)
        try:
            journal_seq0 = _jj.JOURNAL.total_recorded
            # warm (same bucket shapes as the fleet leg: no new compiles)
            jclient.decide_arrays_fleet(
                representative_cluster(Gf, Pf, Nf, seed=980), int(now),
                "jwarm")
            jsched = jsrv._escalator_service.fleet
            jtenants = {f"jt{i}": (representative_cluster(Gf, Pf, Nf,
                                                          seed=981 + i),
                                   ("critical", "standard", "batch")[i % 3])
                        for i in range(6)}
            jres: dict = {}
            jlock = _threading.Lock()

            def _jone(tid, c, klass):
                # client-side root span wrapping submit→response, grafting
                # the server journey under its rpc slice — the GrpcBackend
                # convention, driven directly so the smoke controls the
                # span names it asserts on below
                with _obs.spans.span(f"journey_client_{tid}"):
                    _obs.annotate(backend="journey-smoke")
                    with _obs.spans.span("rpc", kind="rpc"):
                        o, phases, meta = jclient.decide_arrays_fleet(
                            c, int(now), tid,
                            span_ctx={"path": _obs.current_path()},
                            klass=klass)
                    if phases:
                        _obs.graft(phases,
                                   under=_obs.current_path() + "/rpc")
                with jlock:
                    jres[tid] = (o, meta)

            jsched.pause()
            jthreads = [_threading.Thread(target=_jone, args=(t, c, k))
                        for t, (c, k) in jtenants.items()]
            for t in jthreads:
                t.start()
            deadline = time.monotonic() + 30
            while (jsched.queue_depth < len(jtenants)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            jsched.resume()
            for t in jthreads:
                t.join()
            # (1) stage-sum ≈ e2e for EVERY request, from the sidecar the
            # server shipped back AND from the fleet_batch records
            sums = []
            for tid, (o, meta) in jres.items():
                j = (meta or {}).get("journey")
                assert j, f"journey smoke: no journey sidecar for {tid}"
                ssum = sum(j["stages_ms"][st] for st in _JSTAGES)
                e2e = j["e2e_ms"]
                assert abs(ssum - e2e) <= max(0.05 * e2e, 0.05), (
                    f"journey smoke: stages sum {ssum} vs e2e {e2e} "
                    f"for {tid}")
                sums.append({"tenant": tid, "klass": j.get("klass"),
                             "e2e_ms": e2e, "stages_ms": j["stages_ms"]})
            jb_recs = [r for r in _FREC.snapshot()
                       if r.get("root") == "fleet_batch"
                       and r.get("journeys")]
            ring_journeys = [j for r in jb_recs for j in r["journeys"]]
            served = {j["tenant"] for j in ring_journeys}
            assert set(jtenants) <= served, (set(jtenants), served)
            for j in ring_journeys:
                ssum = sum(j["stages_ms"].values())
                assert abs(ssum - j["e2e_ms"]) <= max(
                    0.05 * j["e2e_ms"], 0.05), j
            # the dispatch stage is the FENCED fleet_step window
            assert any(
                p.get("name") == "fleet_step" and p.get("fenced")
                for r in jb_recs for p in r.get("phases", ())), (
                "fleet_step span not fenced")
            # per-(class, stage) histograms populated for every class hit
            for klass in ("critical", "standard", "batch"):
                for stage in ("admission", "dispatch", "service"):
                    h = _jh.STAGES.peek(klass, stage)
                    assert h is not None and h.count >= 1, (klass, stage)
            journey_report["requests"] = sums
            journey_report["stage_sum_tolerance"] = "5%"
            out["smoke_journey_decomposition"] = "ok"

            # (2) journal round-trip through the REAL Journal RPC: the six
            # registers + one forced admission reject must come back over
            # the wire with monotonic seqs
            jsched.queue_limit = 1
            jsched.pause()
            fill_out: list = []

            def _jfill():
                # the queue-filling request blocks until resume — it must
                # ride a thread (a synchronous call against the paused
                # scheduler would deadlock this leg)
                try:
                    jclient.decide_arrays_fleet(
                        representative_cluster(Gf, Pf, Nf, seed=990),
                        int(now), "jreject-a", max_attempts=1)
                    fill_out.append("ok")
                except _grpc.RpcError as e:   # pragma: no cover
                    fill_out.append(e.code().name)

            filler = _threading.Thread(target=_jfill)
            filler.start()
            deadline = time.monotonic() + 10
            while jsched.queue_depth < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            try:
                try:
                    jclient.decide_arrays_fleet(
                        representative_cluster(Gf, Pf, Nf, seed=991),
                        int(now), "jreject-b", max_attempts=1)
                    raise AssertionError(
                        "journey smoke: queue-full reject did not fire")
                except _grpc.RpcError as e:
                    assert e.code().name == "RESOURCE_EXHAUSTED", e
            finally:
                jsched.queue_limit = 64
                jsched.resume()
                filler.join(timeout=30)
            assert fill_out == ["ok"], fill_out
            jdoc = jclient.journal(since_seq=journal_seq0)
            kinds = [e["kind"] for e in jdoc["events"]]
            assert "fleet-tenant-register" in kinds, kinds
            assert "admission-reject" in kinds, kinds
            seqs = [e["seq"] for e in jdoc["events"]]
            assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
            registered = {e.get("tenant") for e in jdoc["events"]
                          if e["kind"] == "fleet-tenant-register"}
            assert set(jtenants) <= registered, (set(jtenants), registered)
            journey_report["journal"] = {
                "events": len(jdoc["events"]),
                "kinds": sorted(set(kinds)),
                "rpc": "ok",
            }
            out["smoke_journal_rpc"] = "ok"

            # (3) overhead gate: one journey record (6 stage-histogram
            # observes + the sink append + a journal event) micro-benched,
            # multiplied by the batch width, must stay under 1% of the
            # measured smoke-scale fleet batch — the PR-4 discipline
            bench_journal = _jj.OpsJournal(capacity=256)
            sink: list = []
            iters = 2000
            jt0 = time.perf_counter()
            for _ in range(iters):
                for stage in _JSTAGES_ALL:
                    _jh.STAGES.observe(("overheadbench", stage), 1e-3)
                sink.append({"tenant": "x"})
                bench_journal.event("bench-journey", tenant="x",
                                    klass="standard")
                if len(sink) > 64:
                    sink.clear()
            hook_us = (time.perf_counter() - jt0) / iters * 1e6
            for stage in _JSTAGES_ALL:
                _jh.STAGES.discard("overheadbench", stage)
            # denominator: the median WARM batch (the ring also holds the
            # compile-scale warm-up batches, which would flatter the gate
            # — the round-15 recorder lesson), with the PR-4/PR-13
            # absolute floor: a smoke-scale batch is microscopic next to a
            # production one, so percent-of-tiny is noise below 0.25 ms
            warm_ms = sorted(r["duration_ms"] for r in jb_recs
                             if not r.get("compile_events"))
            batch_ms = (warm_ms[len(warm_ms) // 2] if warm_ms
                        else min(r["duration_ms"] for r in jb_recs))
            batch_n = max(len(r.get("journeys") or ()) for r in jb_recs)
            hook_ms = hook_us * batch_n / 1e3
            gate_ms = max(0.01 * batch_ms, 0.25)
            assert hook_ms < gate_ms, (
                f"journey+journal hook cost {hook_us:.1f} us x {batch_n} "
                f"requests = {hook_ms:.3f} ms vs gate {gate_ms:.3f} ms "
                f"(1% of a {batch_ms:.1f} ms warm fleet batch, floor "
                "0.25 ms)")
            journey_report["overhead"] = {
                "hook_us_per_request": round(hook_us, 2),
                "warm_batch_ms": batch_ms,
                "hook_per_batch_ms": round(hook_ms, 4),
                "gate_ms": round(gate_ms, 4),
            }
            out["smoke_journey_overhead_ms"] = round(hook_ms, 4)

            # (4) debug-trace renders per-request journey tracks AND the
            # client slice wrapping the grafted server journey — through
            # the real CLI verb on a real ring dump
            import tempfile as _jtempfile

            from escalator_tpu.cli import main as _cli_main

            jtmp = _jtempfile.mkdtemp(prefix="escalator-journey-smoke-")
            jdump = os.path.join(jtmp, "journey-ring.json")
            jtrace = os.path.join(jtmp, "journey.trace.json")
            _FREC.dump(jdump, reason="journey-smoke")
            rc = _cli_main(["debug-trace", "--dump", jdump,
                            "--output", jtrace])
            assert rc == 0, f"debug-trace exited {rc}"
            with open(jtrace) as f:
                trace_doc = json.load(f)
            ev = trace_doc["traceEvents"]
            jslices = [e for e in ev if e.get("ph") == "X"
                       and e.get("tid", 0) >= _jt.TID_JOURNEY_BASE]
            req_slices = [e for e in jslices
                          if e["name"].startswith("req jt")]
            assert req_slices, "no per-request journey slices in trace"
            stage_names = {e["name"] for e in jslices}
            assert {"admission", "dispatch", "unpack"} <= stage_names, (
                stage_names)
            # client+server merged: the journey_client record's grafted
            # journey phases sit under its rpc slice path
            grafted = [e for e in ev if e.get("ph") == "X"
                       and "/rpc/journey/" in str(
                           e.get("args", {}).get("path", ""))]
            assert grafted, "client trace carries no grafted journey"
            journey_report["trace"] = {
                "request_slices": len(req_slices),
                "stage_slices": len(jslices) - len(req_slices),
                "grafted_client_slices": len(grafted),
            }
            out["smoke_journey_trace"] = "ok"
        finally:
            jclient.close()
            jsrv.stop(grace=None)
    journey_artifact = os.environ.get(
        "ESCALATOR_TPU_JOURNEY_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "JOURNEY_SMOKE_LATEST.json"),
    )
    out["journey_smoke_report"] = write_smoke_artifact(
        journey_artifact, journey_report)
    out["smoke_journey_mode"] = fleet_mode
    _leg("journey")

    # dump the ring BEFORE the resources leg below: that leg's profiler
    # pump serves a few hundred plugin decides (each a root record), which
    # would flush the streaming/incremental smoke ticks out of the
    # 256-deep ring — and the committed FLIGHT_SMOKE artifact must carry
    # exactly those ticks' phase taxonomy
    dump_path = os.environ.get(
        "ESCALATOR_TPU_FLIGHT_DUMP",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "FLIGHT_SMOKE_LATEST.json"),
    )
    try:
        dumped = RECORDER.dump(dump_path, reason="smoke")
        # canonicalize the committed artifact (sorted keys, fixed float
        # precision) without touching the live incident-dump format
        with open(dumped) as f:
            out["flight_recorder_dump"] = write_smoke_artifact(
                dumped, json.load(f))
    except OSError:   # read-only checkout: the in-memory asserts still ran
        out["flight_recorder_dump"] = "(write failed)"

    # ---- decision provenance smoke (round 19): explain-vs-columns bit
    # parity on a LIVE fleet server, a forced up/down oscillation through
    # the real decide path firing the flap watchdog (journal event +
    # reason="flap" dump with the flapping group's explanations attached),
    # a steady tenant firing NOTHING, and a debug-explain CLI round-trip
    # over the real RPC. Written to PROVENANCE_SMOKE_LATEST.json for CI
    # upload. Runs after the committed flight dump above on purpose: this
    # leg's ~20 extra plugin ticks must not flush the streaming/incremental
    # records out of the 256-deep ring the FLIGHT_SMOKE artifact carries.
    prov_report: dict = {"smoke": True, "mode": fleet_mode}
    if fleet_mode == "grpc":
        import dataclasses as _pdc

        from escalator_tpu.observability import journal as _pjournal
        from escalator_tpu.observability import provenance as _prov

        _prov.HISTORY.reset()
        _prov.FLAPS.reset()
        prov_dir = tempfile.mkdtemp(prefix="escalator-prov-smoke-")
        prov_old_dump_dir = os.environ.get("ESCALATOR_TPU_DUMP_DIR")
        os.environ["ESCALATOR_TPU_DUMP_DIR"] = prov_dir
        prov_journal_seq = (_pjournal.JOURNAL.snapshot()[-1]["seq"]
                           if _pjournal.JOURNAL.snapshot() else 0)
        Gv, Pv, Nv = 4, 16, 8
        psrv = make_server("127.0.0.1:0", max_workers=8, fleet=FleetConfig(
            num_groups=Gv, pod_capacity=Pv, node_capacity=Nv, max_tenants=4,
            max_batch=4, flush_ms=5.0, queue_limit=64,
            per_tenant_inflight=1, num_shards=1))
        psrv.start()
        prov_addr = f"127.0.0.1:{psrv._escalator_bound_port}"
        pclient = _FC(prov_addr, timeout_sec=300.0)
        try:
            base_c = representative_cluster(Gv, Pv, Nv, seed=940)

            def _with_load(cpu_milli: int, mem_bytes: int):
                """The same tenant topology under a different pod load:
                heavy pushes every populated group over scale_up_thr (70%),
                light drops max_percent under taint_lower (30%)."""
                pods = _pdc.replace(
                    base_c.pods,
                    cpu_milli=np.full_like(
                        np.asarray(base_c.pods.cpu_milli), cpu_milli),
                    mem_bytes=np.full_like(
                        np.asarray(base_c.pods.mem_bytes), mem_bytes))
                return _pdc.replace(base_c, pods=pods)

            heavy = _with_load(3800, 15 * 10**9)
            light = _with_load(10, 10**6)

            # a steady control tenant: the same light frame every tick —
            # constant decisions must fire NOTHING (the watchdog's silence
            # half of the acceptance criterion)
            for i in range(6):
                pclient.decide_arrays_fleet(light, int(now) + i, "steady")

            # the forced oscillation: alternate heavy/light so nodes_delta
            # flips sign every tick on the populated groups
            flap_deltas = []
            last_o = None
            for i in range(12):
                last_o, _p, _meta = pclient.decide_arrays_fleet(
                    heavy if i % 2 == 0 else light, int(now) + 100 + i,
                    "flappy")
                flap_deltas.append(np.asarray(last_o.nodes_delta).copy())
            deltas = np.stack(flap_deltas)                      # [T, G]
            signs = np.sign(deltas)
            alternating = [
                g for g in range(Gv)
                if ((signs[1:, g] != 0) & (signs[:-1, g] != 0)
                    & (signs[1:, g] != signs[:-1, g])).sum() >= 3]
            assert alternating, (
                f"forced oscillation produced no sign-alternating group: "
                f"{deltas.tolist()}")
            prov_report["alternating_groups"] = alternating

            # watchdog fired for the flapping tenant, stayed silent for the
            # steady one; the dump worker finishes before we read the dir
            _prov.FLAPS.drain()
            assert _prov.FLAPS.flaps >= 1, "flap watchdog never fired"
            flap_keys = {r["key"] for r in list(_prov.FLAPS.recent)}
            assert flap_keys == {"flappy"}, (
                f"flap watchdog misattributed: {flap_keys}")
            flap_events = [
                e for e in _pjournal.JOURNAL.snapshot(
                    since_seq=prov_journal_seq, kinds=["group-flap"])
                if e.get("key") == "flappy"]
            assert flap_events, "no group-flap journal event"
            assert any(set(e["groups"]) & set(alternating)
                       for e in flap_events), (flap_events, alternating)
            flap_dumps = sorted(
                p for p in os.listdir(prov_dir) if "-flap-" in p)
            assert flap_dumps, f"no reason=flap dump in {prov_dir}"
            with open(os.path.join(prov_dir, flap_dumps[0])) as f:
                flap_doc = json.load(f)
            assert flap_doc["reason"] == "flap", flap_doc["reason"]
            flap_info = flap_doc["flap"]
            dumped_groups = {d["group"]
                             for d in flap_info.get("explanations", [])}
            assert dumped_groups & set(alternating), (
                f"flap dump explanations name groups {dumped_groups}, "
                f"expected one of {alternating}")
            prov_report["flaps"] = {
                "fired": int(_prov.FLAPS.flaps),
                "dumps": int(_prov.FLAPS.dumps),
                "journal_events": len(flap_events),
                "dump_reason": flap_doc["reason"],
                "dump_groups": sorted(dumped_groups),
            }
            out["smoke_provenance_flap"] = "ok"

            # explain-vs-columns bit parity over the real Explain RPC: the
            # served explanations must match the LAST decide's columns
            # bit-for-bit and carry no cross-check mismatches
            resp = pclient.explain("flappy")
            docs = resp["explanations"]
            assert len(docs) == Gv, (len(docs), Gv)
            last = flap_deltas[-1]
            mm_before = _prov.mismatch_total()
            last_status = np.asarray(last_o.status)
            last_cpu = np.asarray(last_o.cpu_percent)
            last_mem = np.asarray(last_o.mem_percent)
            for d in docs:
                g = d["group"]
                assert "mismatches" not in d, d["mismatches"]
                assert d["status"] == int(last_status[g]), (
                    g, d["status"], int(last_status[g]))
                assert d["nodes_delta"] == int(last[g]), (
                    g, d["nodes_delta"], int(last[g]))
                assert d["threshold_branch"] in _prov.THRESHOLD_BRANCHES
                # float terms are served bit-exact, not approximately
                assert (np.float64(d["terms"]["cpu_percent"]).tobytes()
                        == last_cpu[g].tobytes()), (g, "cpu_percent")
                assert (np.float64(d["terms"]["mem_percent"]).tobytes()
                        == last_mem[g].tobytes()), (g, "mem_percent")
            assert _prov.mismatch_total() == mm_before == 0, (
                "explain cross-check mismatches in the smoke")
            assert len(resp["history"]) >= 8, len(resp["history"])
            prov_report["explain"] = {
                "groups": len(docs),
                "mismatches": int(_prov.mismatch_total()),
                "threshold_branches": sorted(
                    {d["threshold_branch"] for d in docs}),
                "history_depth": len(resp["history"]),
            }
            out["smoke_provenance_parity"] = "ok"

            # health surfaces the provenance section
            ph = pclient.health()
            assert ph["provenance"]["flaps_total"] >= 1, ph["provenance"]
            prov_report["health"] = ph["provenance"]

            # debug-explain CLI round-trip over the real RPC: discovery
            # then per-tenant (rc 0 = no mismatches anywhere)
            from escalator_tpu.cli import main as _prov_cli
            rc_disc = _prov_cli(["debug-explain",
                                 "--plugin-address", prov_addr])
            rc_tenant = _prov_cli(["debug-explain",
                                   "--plugin-address", prov_addr,
                                   "--tenant", "flappy"])
            assert rc_disc == 0 and rc_tenant == 0, (rc_disc, rc_tenant)
            prov_report["cli"] = {"discovery_rc": rc_disc,
                                  "tenant_rc": rc_tenant}
            out["smoke_provenance_cli"] = "ok"
        finally:
            pclient.close()
            psrv.stop(grace=None)
            if prov_old_dump_dir is None:
                os.environ.pop("ESCALATOR_TPU_DUMP_DIR", None)
            else:
                os.environ["ESCALATOR_TPU_DUMP_DIR"] = prov_old_dump_dir
            import shutil as _pshutil
            _pshutil.rmtree(prov_dir, ignore_errors=True)
    prov_artifact = os.environ.get(
        "ESCALATOR_TPU_PROVENANCE_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "PROVENANCE_SMOKE_LATEST.json"),
    )
    out["provenance_smoke_report"] = write_smoke_artifact(
        prov_artifact, prov_report)
    out["smoke_provenance_mode"] = fleet_mode
    _leg("provenance")

    # ---- scale-out smoke (round 20): TWO fleet partitions as REAL
    # subprocesses behind the PartitionRouter — route streamed decides
    # across both, warm-migrate one tenant (journal sequence + digest
    # continuity + measured gap), roll the tenant checkpoints, SIGKILL one
    # partition and prove the breaker → fail_over → replay ladder re-homes
    # its tenants onto the survivor with digest continuity and a measured
    # failover wall time. Written to SCALEOUT_SMOKE_LATEST.json for CI
    # upload; the numbers feed docs/scale-out.md's committed SLO table.
    import shutil as _soshutil
    import subprocess as _sosubprocess
    import tempfile as _sotempfile

    scaleout_report: dict = {"smoke": True, "mode": fleet_mode}
    if fleet_mode == "grpc":
        from dataclasses import fields as _sodcfields

        from escalator_tpu import observability as _soobs
        from escalator_tpu.core.arrays import ClusterArrays as _SOCA
        from escalator_tpu.fleet.router import PartitionRouter as _SOPR

        Gs, Ps, Ns = 6, 24, 12
        nowi = int(now)
        so_dir = _sotempfile.mkdtemp(prefix="escalator-scaleout-smoke-")
        # each partition is a real process: its own interpreter, its own
        # JAX runtime, its own GIL — the thing the router exists to escape
        so_launcher = (
            "from escalator_tpu.plugin.server import FleetConfig, "
            "make_server\n"
            "srv = make_server('127.0.0.1:0', max_workers=8, "
            "fleet=FleetConfig(num_groups=%d, pod_capacity=%d, "
            "node_capacity=%d, max_tenants=8, max_batch=8, flush_ms=5.0, "
            "queue_limit=64, per_tenant_inflight=1, num_shards=1))\n"
            "srv.start()\n"
            "print('SCALEOUT_PORT=%%d' %% srv._escalator_bound_port, "
            "flush=True)\n"
            "srv.wait_for_termination()\n" % (Gs, Ps, Ns))
        so_env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
        so_env["JAX_PLATFORMS"] = "cpu"
        so_procs: dict = {}
        so_errs: dict = {}
        for pname in ("sp0", "sp1"):
            so_errs[pname] = open(
                os.path.join(so_dir, f"{pname}.stderr.log"), "w")
            so_procs[pname] = _sosubprocess.Popen(
                [sys.executable, "-c", so_launcher],
                stdout=_sosubprocess.PIPE, stderr=so_errs[pname],
                text=True, env=so_env)

        def _so_port(pname):
            proc = so_procs[pname]
            deadline = time.monotonic() + 180
            while time.monotonic() < deadline:
                line = proc.stdout.readline()
                if not line:
                    break
                if line.startswith("SCALEOUT_PORT="):
                    return int(line.split("=", 1)[1])
            so_errs[pname].flush()
            with open(os.path.join(so_dir,
                                   f"{pname}.stderr.log")) as f:
                tail = f.read()[-2000:]
            raise AssertionError(
                f"scale-out partition {pname} failed to start:\n{tail}")

        srouter = None
        try:
            so_t0 = time.perf_counter()
            addrs = {p: f"127.0.0.1:{_so_port(p)}" for p in so_procs}
            scaleout_report["partition_start_s"] = round(
                time.perf_counter() - so_t0, 3)
            # breaker_threshold=1: the smoke wants ONE dead RPC to trip
            # fail-over — production keeps the default 3
            srouter = _SOPR(addrs, breaker_threshold=1,
                            checkpoint_dir=os.path.join(so_dir, "ckpt"),
                            timeout_sec=120.0)
            # deterministic spread: walk tenant names until each partition
            # owns two (consistent hashing is pure, so this terminates
            # fast and the picked set is identical on every run)
            by_home: dict = {"sp0": [], "sp1": []}
            i = 0
            while min(len(v) for v in by_home.values()) < 2:
                t = f"so{i}"
                i += 1
                h = srouter.home(t)
                if len(by_home[h]) < 2:
                    by_home[h].append(t)
            stenants = by_home["sp0"] + by_home["sp1"]
            scaleout_report["partitions"] = {
                p: {"address": addrs[p], "tenants": by_home[p]}
                for p in addrs}

            ssessions: dict = {}
            sgroups_so: dict = {}
            for j, tid in enumerate(stenants):
                sess = srouter.stream_session(
                    tid, pod_capacity=Ps, node_capacity=Ns,
                    store_kind="numpy")
                sgroups_so[tid] = representative_cluster(
                    Gs, Ps, Ns, seed=1200 + j).groups
                sess.set_groups(sgroups_so[tid])
                for k in range(8):
                    sess.store.upsert_pod(f"{tid}-p{k}", k % Gs,
                                          400 + 20 * k + 7 * j,
                                          10 ** 9, k % 5)
                for k in range(5):
                    sess.store.upsert_node(f"{tid}-n{k}", k % Gs, 4000,
                                           16 * 10 ** 9,
                                           tainted=(k == 4))
                ssessions[tid] = sess

            def _so_content(tid):
                def copy(soa):
                    return type(soa)(**{
                        f.name: np.array(getattr(soa, f.name))
                        for f in _sodcfields(soa)})
                pods, nodes = ssessions[tid].store.as_pod_node_arrays()
                return _SOCA(groups=copy(sgroups_so[tid]),
                             pods=copy(pods), nodes=copy(nodes))

            def _so_assert_parity(tid, o, at):
                ref = _fk.decide_jit(jax.device_put(_so_content(tid)),
                                     np.int64(at))
                assert decision_digest(o) == decision_digest(ref), (
                    f"scale-out smoke: digest diverged for {tid} @ {at}")

            # (1) route: full frame then a churned delta through BOTH
            # partitions, every answer digest-equal to a local reference
            # decide on the session's store content
            for tid in stenants:
                o, _p, _m = srouter.decide_stream(ssessions[tid], nowi)
                _so_assert_parity(tid, o, nowi)
            for tid in stenants:
                ssessions[tid].store.upsert_pod(
                    f"{tid}-p1", 1, 3000, 4 * 10 ** 9, 1)
                ssessions[tid].store.delete_pod(f"{tid}-p6")
                o, _p, _m = srouter.decide_stream(
                    ssessions[tid], nowi + 60)
                _so_assert_parity(tid, o, nowi + 60)
                assert ssessions[tid].full_frames == 1, (
                    tid, ssessions[tid].full_frames)
            assert {srouter.home(t) for t in stenants} == {"sp0", "sp1"}
            scaleout_report["routed_decides"] = 2 * len(stenants)
            out["smoke_scaleout_route_parity"] = "ok"

            # (2) warm migration sp0 -> sp1: journal sequence, the session
            # stays on the DELTA path (no resync full frame), and the next
            # decide digest-matches the local reference
            mig_tid = by_home["sp0"][0]
            seq0 = _soobs.journal.JOURNAL.total_recorded
            mig = srouter.migrate_tenant(mig_tid, "sp1")
            mig_kinds = [
                e["kind"]
                for e in _soobs.journal.JOURNAL.snapshot(since_seq=seq0)
                if e.get("tenant") == mig_tid]
            assert mig_kinds == [
                "migration-start", "migration-row-snapshot",
                "migration-evict", "migration-adopt",
                "migration-complete"], mig_kinds
            assert srouter.home(mig_tid) == "sp1"
            ssessions[mig_tid].store.upsert_pod(
                f"{mig_tid}-p2", 2, 5000, 8 * 10 ** 9, 0)
            o, _p, _m = srouter.decide_stream(
                ssessions[mig_tid], nowi + 120)
            _so_assert_parity(mig_tid, o, nowi + 120)
            assert ssessions[mig_tid].full_frames == 1, (
                "warm migration forced a resync full frame")
            scaleout_report["migration"] = {
                "tenant": mig_tid, "source": "sp0", "dest": "sp1",
                "gap_ms": mig["gap_ms"], "journal_sequence": mig_kinds,
                "post_migration_frames": "delta",
            }
            out["smoke_scaleout_migration_gap_ms"] = mig["gap_ms"]

            # (3) roll the failover checkpoints off the LIVE homes
            ckpt = srouter.checkpoint_tenants()
            assert set(ckpt) == set(stenants) and all(
                v == "ok" for v in ckpt.values()), ckpt
            scaleout_report["checkpoint"] = ckpt

            # (4) SIGKILL the partition now holding three tenants; the
            # next routed decide eats the dead RPC, trips the breaker,
            # fails every sp1 tenant over to sp0 (warm, from the rolling
            # checkpoints) and replays — ONE slow decide, no error
            so_procs["sp1"].kill()
            so_procs["sp1"].wait(timeout=30)
            seq1 = _soobs.journal.JOURNAL.total_recorded
            fail_tid = by_home["sp1"][0]
            ft0 = time.perf_counter()
            o, _p, _m = srouter.decide_stream(
                ssessions[fail_tid], nowi + 180, max_attempts=1)
            failover_decide_ms = (time.perf_counter() - ft0) * 1e3
            _so_assert_parity(fail_tid, o, nowi + 180)
            fo_events = _soobs.journal.JOURNAL.snapshot(since_seq=seq1)
            fo_kinds = [e["kind"] for e in fo_events]
            assert "partition-breaker-open" in fo_kinds, fo_kinds
            assert "partition-failover-start" in fo_kinds, fo_kinds
            rehomes = [e for e in fo_events
                       if e["kind"] == "failover-rehome"]
            assert rehomes and all(
                e["outcome"] == "warm" and e["partition"] == "sp0"
                for e in rehomes), rehomes
            complete = [e for e in fo_events
                        if e["kind"] == "partition-failover-complete"]
            assert len(complete) == 1, fo_kinds
            # every survivor-side tenant keeps answering, digest-equal to
            # its local reference (continuity through the kill)
            for tid in stenants:
                assert srouter.home(tid) == "sp0"
                o, _p, _m = srouter.decide_stream(
                    ssessions[tid], nowi + 240)
                _so_assert_parity(tid, o, nowi + 240)
            sh = srouter.health()
            assert sh["down"] == ["sp1"], sh["down"]
            assert sh["aggregate"]["partitions"] == 1, sh["aggregate"]
            scaleout_report["failover"] = {
                "killed": "sp1",
                "tenants_rehomed": len(rehomes),
                "rehome_outcomes": sorted(
                    e["outcome"] for e in rehomes),
                "wall_ms": complete[0]["wall_ms"],
                "first_decide_ms": round(failover_decide_ms, 3),
            }
            out["smoke_scaleout_failover_wall_ms"] = complete[0]["wall_ms"]
            out["smoke_scaleout_failover_decide_ms"] = round(
                failover_decide_ms, 3)
            out["smoke_scaleout_parity"] = "ok"
        finally:
            if srouter is not None:
                srouter.close()
            for pname, proc in so_procs.items():
                proc.kill()
                try:
                    proc.wait(timeout=30)
                except _sosubprocess.TimeoutExpired:  # pragma: no cover
                    pass
                if proc.stdout is not None:
                    proc.stdout.close()
                so_errs[pname].close()
            _soshutil.rmtree(so_dir, ignore_errors=True)
    scaleout_artifact = os.environ.get(
        "ESCALATOR_TPU_SCALEOUT_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "SCALEOUT_SMOKE_LATEST.json"),
    )
    out["scaleout_smoke_report"] = write_smoke_artifact(
        scaleout_artifact, scaleout_report)
    out["smoke_scaleout_mode"] = fleet_mode
    _leg("scaleout")

    # ---- device resource observatory smoke (round 15): per-owner budgets,
    # forced-leak watchdog fire, compile-ring attribution, and a
    # debug-profile round-trip through the REAL plugin RPC — written to
    # MEMORY_SMOKE_LATEST.json for CI upload.
    import threading as _rthreading

    from escalator_tpu.observability import jaxmon as jaxmonmod
    from escalator_tpu.observability import resources as resmod
    from escalator_tpu.observability import spans as _rspans

    memory_report: dict = {"smoke": True}

    # (a) per-owner device-buffer budgets: the cfg14 decider + cache above
    # are live, so every persistent-state owner must be registered with
    # measured bytes EXACTLY equal to its executable envelope formula (the
    # docs' hand-computed HBM numbers, now asserted instead of maintained)
    owners = resmod.RESOURCES.snapshot()
    for need in ("cluster_arrays", "group_aggregates", "decision_columns",
                 "order_state"):
        assert need in owners, (need, sorted(owners))
        row = owners[need]
        assert row["nbytes"] > 0, (need, row)
        assert row["budget_bytes"] is not None, (need, row)
        assert row["nbytes"] == row["budget_bytes"], (
            f"resource owner {need}: measured {row['nbytes']} B != declared "
            f"budget {row['budget_bytes']} B — the executable envelope and "
            f"the implementation diverged")
    # fleet arenas register too (the fleet smoke's engine is still live);
    # its budget is the docs/fleet.md capacity-envelope formula
    if fleet_mode == "grpc":
        row = owners.get("fleet_arenas")
        assert row and row["nbytes"] == row["budget_bytes"] > 0, row
    # the formulas are the docs' envelopes, independently of the budget
    # closures: ONE cfg14-cache instance costs exactly this many bytes
    # (other smoke legs' caches may still be alive, so the owner total is
    # a multiple of per-instance expectations — recorded, not asserted)
    memory_report["expected_cfg14_cluster_bytes"] = (
        resmod.expected_cluster_bytes(cache.pod_capacity,
                                      cache.node_capacity, Gi))
    memory_report["owners"] = owners
    memory_report["capabilities"] = resmod.capabilities()
    memory_report["device_memory"] = resmod.device_memory()
    memory_report["live_arrays"] = resmod.live_arrays_bytes()
    # degrade contract: every capability surface either works or names why
    for surface in ("device_memory", "live_arrays"):
        v = memory_report[surface]
        assert isinstance(v, dict) and v, (surface, v)
    out["smoke_resource_budgets"] = "ok"

    # (b) forced leak -> memory watchdog dump: a test-injected owner that
    # grows every tick must fire the growth watchdog's reason="memory"
    # flight dump (rate-limited like the tail watchdog)
    import tempfile as _rtempfile

    leak_dir = _rtempfile.mkdtemp(prefix="escalator-memory-smoke-")
    saved_env = {k: os.environ.get(k) for k in (
        "ESCALATOR_TPU_MEMORY_WATCH", "ESCALATOR_TPU_MEMORY_MIN_GROWTH",
        "ESCALATOR_TPU_MEMORY_DUMP_INTERVAL_SEC",
        "ESCALATOR_TPU_MEMORY_SAMPLE_EVERY", "ESCALATOR_TPU_DUMP_DIR")}
    os.environ["ESCALATOR_TPU_MEMORY_WATCH"] = "8"
    os.environ["ESCALATOR_TPU_MEMORY_MIN_GROWTH"] = "1000"
    os.environ["ESCALATOR_TPU_MEMORY_DUMP_INTERVAL_SEC"] = "0"
    os.environ["ESCALATOR_TPU_MEMORY_SAMPLE_EVERY"] = "1"
    os.environ["ESCALATOR_TPU_DUMP_DIR"] = leak_dir

    class _LeakyOwner:
        def __init__(self):
            self.arrays = []

    leaky = _LeakyOwner()
    leak_reg = resmod.RESOURCES.register(
        "smoke_injected_leak", leaky, lambda o: o.arrays)
    resmod.MEMORY_WATCHDOG.reset()
    try:
        for _ in range(10):
            leaky.arrays.append(np.zeros(512, np.int64))
            with _rspans.span("memory_smoke_tick"):
                _rspans.annotate(backend="memory-smoke")
        resmod.MEMORY_WATCHDOG.drain()
        import glob as _rglob

        leak_dumps = _rglob.glob(os.path.join(
            leak_dir, "escalator-tpu-flight-memory-*.json"))
        assert leak_dumps, "forced leak did not fire the memory watchdog"
        with open(leak_dumps[0]) as f:
            leak_doc = json.load(f)
        assert leak_doc["reason"] == "memory"
        wd = leak_doc["memory_watchdog"]
        assert wd["growth_bytes"] >= 1000 and wd["rising_steps"] >= 4, wd
        assert wd["owners"].get("smoke_injected_leak", 0) > 0, wd
        # every dump (this one included) carries the memory section
        assert leak_doc["memory"]["owners"], leak_doc["memory"]
        memory_report["forced_leak"] = {
            "growth_bytes": wd["growth_bytes"],
            "window_ticks": wd["window_ticks"],
            "dump": os.path.basename(leak_dumps[0]),
        }
    finally:
        leak_reg.close()
        resmod.MEMORY_WATCHDOG.reset()
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        import shutil as _rshutil

        _rshutil.rmtree(leak_dir, ignore_errors=True)
    out["smoke_memory_watchdog"] = "ok"

    # (c) compile observatory: the smoke's own compiles rode the ring with
    # span-path attribution — the cfg14 delta program must be named
    ring = jaxmonmod.compile_ring()
    assert ring, "compile ring empty after a smoke run full of compiles"
    attributed = jaxmonmod.attribute_compiles(ring)
    # the ring is bounded, so assert on the program families the most
    # recent legs certainly compiled rather than one specific early entry
    known = {"kernel.decide", "kernel.delta_decide",
             "kernel.ordered_delta_decide", "device_state.fleet_step",
             "device_state.scatter_update_aggs"}
    assert any(r.get("entry") in known for r in attributed), (
        [r["key"] for r in attributed])
    memory_report["compile_ring_depth"] = len(ring)
    memory_report["compile_attribution"] = [
        {k: r[k] for k in ("key", "count", "total_sec")} for r in attributed]
    out["smoke_compile_attribution"] = "ok"

    # (d) debug-profile round-trip through the REAL plugin RPC: a Profile
    # capture of 2 served decides ships TensorBoard/XPlane files back over
    # the wire and the CLI verb writes them locally
    if fleet_mode == "grpc":
        psrv = make_server("127.0.0.1:0", max_workers=8)
        psrv.start()
        paddr = f"127.0.0.1:{psrv._escalator_bound_port}"
        pclient = _FC(paddr, timeout_sec=60.0)
        prof_dir = _rtempfile.mkdtemp(prefix="escalator-profile-smoke-")
        try:
            # the fleet leg already compiled the single-cluster decide at
            # (6, 24, 12) in this process — reuse the shape so this leg
            # prices the profiler round-trip, not a fresh jit compile
            pc = representative_cluster(6, 24, 12, seed=1234)
            pclient.decide_arrays(pc, int(now))   # warm the server path
            from escalator_tpu.cli import main as _cli_main

            cli_rc: list = []

            def _run_profile_cli():
                cli_rc.append(_cli_main([
                    "debug-profile", "--plugin-address", paddr,
                    "--ticks", "2", "--output", prof_dir,
                    "--timeout", "60"]))

            pt = _rthreading.Thread(target=_run_profile_cli)
            pt.start()
            deadline = time.monotonic() + 90
            while pt.is_alive() and time.monotonic() < deadline:
                # keep decides flowing until the capture window closes (the
                # profiler's first start_trace can take a moment, so a
                # fixed count could all land before the trace arms)
                pclient.decide_arrays(pc, int(now))
                time.sleep(0.05)
            pt.join(10)
            assert cli_rc and cli_rc[0] == 0, f"debug-profile rc={cli_rc}"
            prof_files = resmod.trace_files(prof_dir)
            assert any(f.endswith(".xplane.pb") for f in prof_files), (
                prof_files)
            memory_report["profile_rpc"] = {
                "files": prof_files,
                "bytes": sum(os.path.getsize(os.path.join(prof_dir, f))
                             for f in prof_files),
            }
            # the plugin health probe now carries the memory section too
            ph = pclient.health()
            assert "memory" in ph and "owners" in ph["memory"], ph.keys()
            out["smoke_profile_rpc"] = "ok"
        finally:
            pclient.close()
            psrv.stop(grace=None)
            import shutil as _rshutil

            _rshutil.rmtree(prof_dir, ignore_errors=True)
    else:
        out["smoke_profile_rpc"] = fleet_mode   # skipped (grpc unavailable)
    _leg("resources")

    # per-leg duration table (round 15 satellite): printed for humans,
    # persisted in the smoke JSON artifacts for CI comparison
    memory_report["leg_seconds"] = leg_seconds
    out["smoke_leg_seconds"] = leg_seconds
    print("smoke leg durations (s):", file=sys.stderr)
    for name, sec in leg_seconds.items():
        print(f"  {name:>20}: {sec:8.3f}", file=sys.stderr)
    memory_artifact = os.environ.get(
        "ESCALATOR_TPU_MEMORY_SMOKE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "MEMORY_SMOKE_LATEST.json"),
    )
    out["memory_smoke_report"] = write_smoke_artifact(
        memory_artifact, memory_report)
    return out


def _loadavg():
    try:
        return [round(v, 2) for v in os.getloadavg()]
    except (OSError, AttributeError):
        return None


# per-run: concurrent benches (a driver run overlapping the campaign's — this
# rig's documented contention case) must not share one partial file, or the
# campaign's stall watchdog reads the OTHER run's progress and its salvage
# copies the other session's sections. tools/tpu_campaign.sh passes a
# TPU_PARTIAL_<ts>.json path (which TPU_BENCH_*.json capture globs never
# match); standalone runs use the LATEST default.
_PARTIAL_PATH = os.environ.get(
    "ESCALATOR_TPU_BENCH_PARTIAL",
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "BENCH_PARTIAL_LATEST.json"),
)


def _device_label(device, degraded: bool) -> str:
    return str(device) + (
        " (accelerator unreachable; CPU fallback)" if degraded else "")


def _round_floats(detail: dict) -> dict:
    return {k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in detail.items()}


def _canon_smoke(obj, ndigits: int = 4):
    """Canonical smoke-artifact form (round 19 satellite): every float leaf
    (durations, rates, percentiles) rounded to a fixed precision, recursively.
    Together with sorted keys this makes regenerating an artifact with
    unchanged behavior an empty diff instead of 49 lines of timing noise
    (the PR-17 tip commit)."""
    if isinstance(obj, dict):
        return {k: _canon_smoke(v, ndigits) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_canon_smoke(v, ndigits) for v in obj]
    if isinstance(obj, float):
        return round(obj, ndigits)
    return obj


def write_smoke_artifact(path: str, report) -> str:
    """The ONE ``*_SMOKE_LATEST.json`` writer: sorted keys + fixed float
    precision (see :func:`_canon_smoke`). Returns the path written, or
    ``"(write failed)"`` on a read-only checkout — the in-memory asserts
    already ran, so a failed artifact write is reported, not fatal."""
    try:
        with open(path, "w") as f:
            json.dump(_canon_smoke(report), f, indent=1, sort_keys=True)
            f.write("\n")
        return path
    except OSError:
        return "(write failed)"


def _atomic_json_write(path: str, rec: dict) -> None:
    """tmp-write + rename: a campaign SIGKILL mid-write must never leave a
    truncated file for the driver (or the salvage) to ingest."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)


def _flush_partial(detail: dict, device, degraded: bool) -> None:
    """Atomically write the sections measured SO FAR to the partial file. The
    tunnel can wedge mid-run (round 4 lost its closing bench additions exactly
    that way: added 07:07Z, tunnel dead 07:23Z, zero captures carried them) —
    a killed bench must not lose the sections it completed.
    tools/tpu_campaign.sh keeps this file as the salvaged capture when the
    bench dies, and uses its mtime as the stall-watchdog progress signal, so
    an early wedge costs the stall budget, not the whole bench timeout.
    Removed on successful completion (the full artifact supersedes it)."""
    try:
        _atomic_json_write(_PARTIAL_PATH, {
            "partial": True,
            "device": _device_label(device, degraded),
            "detail": _round_floats(detail),
        })
    except OSError:  # pragma: no cover - read-only checkout
        pass


def main() -> None:
    # probe-and-degrade with retries: a wedged accelerator tunnel must not hang
    # the bench, but it also recovers — so probe a few times before settling
    # (attempts logged to TPU_ATTEMPTS.log for the audit trail either way)
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    attempts = int(os.environ.get("ESCALATOR_TPU_PROBE_ATTEMPTS", "3"))
    degraded = not ensure_responsive_accelerator(
        timeout_sec=90.0, attempts=attempts, retry_wait_sec=20.0,
        attempt_log=os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                 "TPU_ATTEMPTS.log"),
    )
    import jax

    from escalator_tpu.ops import kernel as _kernel  # noqa: F401 registers pytrees

    now = np.int64(1_700_000_000)
    rng = np.random.default_rng(0)
    device = jax.devices()[0]
    put = lambda c: jax.device_put(c, device)

    detail = {}
    # contention stamp: this rig has ONE physical core, so any concurrent
    # process (a test run, a second bench, the campaign's capture) inflates
    # every timing. A load average well above ~1 at the start marks the whole
    # artifact contention-suspect — the round-5 CPU artifact's packed-transfer
    # rows (54.9 ms vs the prior 25.2 ms with every sibling metric stable)
    # were exactly such a silent outlier.
    if (load := _loadavg()) is not None:
        detail["host_load_avg_start"] = load
    # "bench started, nothing measured yet" baseline — a wedge inside cfg1's
    # first compile is then distinguishable from a bench that never launched
    _flush_partial(detail, device, degraded)
    # 1. single nodegroup, 500 pods, uniform
    detail["cfg1_1ng_500pods_ms"] = _time_decide(
        put(_rng_cluster_arrays(rng, 1, 500, 100)), now
    )
    _flush_partial(detail, device, degraded)
    # 2. single nodegroup, 50k pods, mixed requests
    detail["cfg2_1ng_50kpods_ms"] = _time_decide(
        put(_rng_cluster_arrays(rng, 1, 50_000, 2_000, mixed=True)), now
    )
    # 3. 64 nodegroups, heterogeneous instance types
    detail["cfg3_64ng_hetero_ms"] = _time_decide(
        put(
            _rng_cluster_arrays(rng, 64, 20_000, 5_000, mixed=True, heterogeneous=True)
        ),
        now,
    )
    _flush_partial(detail, device, degraded)
    # 4. BASELINE shape: 2048 nodegroups, 100k pods (kernel-only + e2e)
    host_headline = _rng_cluster_arrays(
        rng, 2048, 100_000, 50_000, mixed=True, heterogeneous=True,
        tainted_frac=0.1, cordoned_frac=0.02,
    )
    headline_cluster = put(host_headline)
    from escalator_tpu.ops.kernel import decide_jit

    jax.block_until_ready(decide_jit(headline_cluster, now))
    med, mn = _timeit(
        lambda: jax.block_until_ready(decide_jit(headline_cluster, now)))
    detail["cfg4_kernel_only_ms"] = round(med, 3)
    detail["cfg4_kernel_only_min_ms"] = round(mn, 3)
    detail["cfg4_phases"] = _phase_breakdown(
        host_headline, headline_cluster, now, device)
    _flush_partial(detail, device, degraded)

    # full-upload end-to-end tick: transfer the whole cluster + decide, per
    # iteration — the fallback headline when the native store is unavailable
    def full_tick():
        dev = jax.device_put(host_headline, device)
        jax.block_until_ready(decide_jit(dev, now))

    e2e_med, e2e_min = _timeit(full_tick, iters=max(10, ITERS // 3))
    detail["cfg4_e2e_full_upload_ms"] = round(e2e_med, 3)
    detail["cfg4_e2e_full_upload_min_ms"] = round(e2e_min, 3)
    _flush_partial(detail, device, degraded)

    # 5. scale-down ordering: 10k pods, heavy taint/cordon masking
    detail["cfg5_scaledown_10kpods_ms"] = _time_decide(
        put(
            _rng_cluster_arrays(
                rng, 64, 10_000, 10_000, tainted_frac=0.4, cordoned_frac=0.1
            )
        ),
        now,
    )

    # 6. native incremental path (phase breakdown + churn sweep); its churned
    # device cluster feeds cfg9's interleaved-layout row
    churned_cluster = None
    try:
        churned_cluster = _cfg6_native(rng, now, device, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg6_native_tick_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 13. long-context stretch: native incremental tick at 1M pods/100k nodes
    # on one chip (runs before cfg9 so its decide program loads as early as
    # possible; see the late-program session penalty in docs/performance.md)
    try:
        _cfg13_native_1M(rng, now, device, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg13_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 14. incremental vs full decide across the churn sweep (round-8
    # tentpole): dirty-group-compacted delta_decide vs the full recompute,
    # at 100k and 1M pods, parity asserted per tick
    try:
        _cfg14_incremental_vs_full(rng, now, device, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg14_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 15. ordered-incremental drain-churn sweep (round-10 tentpole):
    # persistent order-state rank-repair vs the full sort it replaces,
    # parity asserted bit-exact per tick; the ISSUE-5 bar is ordered
    # incremental <= 2x the light tick
    try:
        _cfg15_ordered_incremental(rng, now, device, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg15_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 16. streaming e2e tick (round-12 tentpole): watch-delta ingestion +
    # packed dirty drain + delta decide at 100k and 1M, digest parity vs
    # the re-list path per tick, per-phase columns from the recorder, and
    # the recorded-workload replay row (the noise-immune before/after)
    try:
        _cfg16_streaming(rng, now, device, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg16_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 17. fleet decision service (round-14 tentpole): C=1k tenants through
    # the continuous-batching scheduler — decisions/sec + per-tenant p99,
    # 13-column bit-parity for every tenant every tick, and the
    # one-dispatch-per-micro-batch proof from recorder phase counts
    try:
        _cfg17_fleet(rng, now, device, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg17_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 18. scale-out partition sweep (round-20 tentpole): N=1 vs N=2 fleet
    # partition subprocesses behind the consistent-hash router — aggregate
    # decisions/sec at the host-bound high-idle arm and the device-bound
    # full-churn arm, per-class p99 per partition, core-gated scaling bar
    try:
        _cfg18_scaleout(now, detail, degraded)
    except Exception as e:  # pragma: no cover
        detail["cfg18_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # device memory: stats probe + computed envelope, after the biggest
    # clusters (cfg13's 1M-pod store) are resident so peak covers them
    _memory_envelope(device, detail)
    _flush_partial(detail, device, degraded)

    # 9. pallas-vs-xla aggregation matrix (VERDICT r3 item 2): compiled Pallas
    # is TPU-only (interpret mode would measure the interpreter), so the
    # matrix is skipped on the CPU fallback
    if not degraded:
        _cfg9_pallas_matrix(
            detail, headline_cluster, host_headline, churned_cluster, rng,
            now, device,
            flush=lambda: _flush_partial(detail, device, degraded))
    _flush_partial(detail, device, degraded)

    # 10. FFD bin-packing at bench scale (the marquee beyond-reference
    # feature, ops/binpack.py): 2048 groups x 64 pods x 32 real bins + 16
    # virtual — one blocked packing sweep for the whole fleet, priced on
    # both the adversarial mixed load and the compressible replicaset load
    try:
        detail.update(_bench_ffd_pack(rng, device))
        # continuity alias: rounds 1-5 published this exact key
        detail["cfg10_ffd_pack_min_ms"] = detail[
            "cfg10_ffd_pack_2048g_64pods_min_ms"]
    except Exception as e:  # pragma: no cover
        detail["cfg10_ffd_pack_error"] = str(e)

    # 11. what-if candidate-delta sweep (ops/simulate.py) on the BASELINE
    # shape: post-delta utilisation for 2048 groups x 32 candidate deltas
    try:
        from escalator_tpu.ops.simulate import sweep_deltas_jit

        swp_med, swp_min = _timeit(
            lambda: jax.block_until_ready(
                sweep_deltas_jit(headline_cluster, num_candidates=32)))
        detail["cfg11_whatif_sweep_2048g_32cand_ms"] = round(swp_med, 3)
        detail["cfg11_whatif_sweep_min_ms"] = round(swp_min, 3)
    except Exception as e:  # pragma: no cover
        detail["cfg11_whatif_sweep_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 12. the compute-plugin boundary at the headline shape (skipped when
    # grpc is unavailable; the local fallback path needs no pricing)
    try:
        detail.update(_bench_plugin_roundtrip(host_headline, now))
    except ModuleNotFoundError as e:  # pragma: no cover - grpc-less host
        detail["cfg12_skipped"] = f"grpc unavailable ({e.name})"
    except Exception as e:  # pragma: no cover
        detail["cfg12_plugin_error"] = str(e)
    _flush_partial(detail, device, degraded)

    # 7/8. sharded paths (always in a subprocess on the 8-virtual-device CPU
    # mesh: the scaling SHAPE is the evidence; single-chip hardware can't host
    # an 8-way mesh either way). Campaign captures racing a short tunnel
    # window skip this CPU-only section (ESCALATOR_TPU_BENCH_SKIP_SHARDED) —
    # the TPU-relevant configs above are the capture's point.
    if os.environ.get("ESCALATOR_TPU_BENCH_SKIP_SHARDED", "").lower() not in (
            "", "0", "false"):
        skip_note = "sharded section skipped by design (campaign capture)"
        detail["cfg7_skipped"] = detail["cfg8_skipped"] = skip_note
    else:
        _run_sharded_subprocess(detail)

    # cross-capture spread: summarize every TPU campaign capture in the repo
    detail["tpu_captures"] = _summarize_tpu_captures()
    partials = _summarize_tpu_partials()
    if partials:
        detail["tpu_partials"] = partials
    # best archived on-TPU end-to-end tick: kept top-of-detail so a driver
    # run that lands in a wedged-tunnel window still carries the TPU
    # evidence prominently, clearly labeled as archived (sessions are
    # identifiable by the timestamped filenames in tpu_captures)
    e2e = _archived_e2e_values(detail["tpu_captures"])
    if e2e:
        detail["tpu_best_archived_e2e_ms"] = min(e2e)
        detail["tpu_archived_e2e_spread_ms"] = [min(e2e), max(e2e)]

    # ---- headline: END-TO-END tick at the BASELINE shape -------------------
    # Round 12: the headline is the STREAMING tick (cfg16) — watch-delta
    # ingestion + packed drain + delta decide, the production steady-state
    # path. cfg6 (full-decide native tick) and cfg4 e2e remain the
    # fallbacks, in that order, when a section errored out.
    target_ms = 50.0
    if "cfg16_streaming_tick_100k_1pct_ms" in detail:
        headline = detail["cfg16_streaming_tick_100k_1pct_ms"]
        scope = ("end_to_end_streaming_tick_1pct_churn"
                 "(upsert+event_drain+triple_build+scatter+delta_decide)")
    elif "cfg6_native_tick_1pct_churn_ms" in detail:
        headline = detail["cfg6_native_tick_1pct_churn_ms"]
        scope = ("end_to_end_incremental_tick_1pct_churn"
                 "(upsert+drain+scatter+decide)")
    else:
        headline = detail["cfg4_e2e_full_upload_ms"]
        scope = "end_to_end_full_upload_tick(transfer+decide)"
    if (load := _loadavg()) is not None:
        detail["host_load_avg_end"] = load
    record = {
        "metric": "e2e_tick_latency_2048ng_100kpods",
        "value": round(headline, 3),
        "unit": "ms",
        "vs_baseline": round(target_ms / headline, 2),
        "headline_scope": scope,
        "device": _device_label(device, degraded),
        "full_artifact": "BENCH_FULL_LATEST.json",
        "detail": _round_floats(detail),
    }
    # full artifact to a sibling file FIRST (VERDICT r4 item 6: the round-4
    # driver grabbed only the stdout tail and lost every section before cfg8
    # from BENCH_r04.json; this file carries every cfg section regardless of
    # how the driver captures stdout)
    try:
        _atomic_json_write(
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_FULL_LATEST.json"), record)
    except OSError:  # pragma: no cover - read-only checkout still prints
        record["full_artifact"] = "(write failed; stdout only)"
    # the full artifact supersedes the partial; leaving it would let a later
    # failed run get a STALE partial salvaged next to its own capture
    try:
        os.remove(_PARTIAL_PATH)
    except OSError:
        pass
    print(json.dumps(record))


if __name__ == "__main__":
    # incident-dump hygiene: flight-recorder dumps (audit mismatch, wedge)
    # default to CWD — a local bench run must not litter the repo root with
    # escalator-tpu-flight-*.json debris, so point the dir at a tempdir
    # unless the caller chose one (CI does, to capture dumps as artifacts)
    if "ESCALATOR_TPU_DUMP_DIR" not in os.environ:
        import tempfile

        os.environ["ESCALATOR_TPU_DUMP_DIR"] = tempfile.mkdtemp(
            prefix="escalator-tpu-bench-dumps-")
    if "--sharded" in sys.argv:
        run_sharded()
    elif "--recorded" in sys.argv:
        # recorded-workload bench over an arbitrary replay bundle:
        #   python bench.py --recorded <flight-dump.json> <state.snap> [passes]
        i = sys.argv.index("--recorded")
        args = sys.argv[i + 1:]
        if len(args) < 2:
            raise SystemExit(
                "usage: bench.py --recorded <flight-dump.json> <state.snap>"
                " [passes]")
        passes = int(args[2]) if len(args) > 2 else 5
        print(json.dumps(run_recorded(args[0], args[1], passes=passes)))
    elif "--cfg18" in sys.argv:
        # targeted scale-out refresh: run ONLY the cfg18 partition sweep
        # (CPU subprocesses either way) and merge into BENCH_FULL_LATEST
        os.environ["JAX_PLATFORMS"] = "cpu"
        print(json.dumps(run_cfg18()))
    elif "--smoke" in sys.argv:
        # tier-1-safe: pin to CPU with 8 virtual devices BEFORE jax loads
        # (bench.py keeps jax imports inside functions for exactly this)
        os.environ["JAX_PLATFORMS"] = "cpu"
        _fl = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in _fl:
            os.environ["XLA_FLAGS"] = (
                _fl + " --xla_force_host_platform_device_count=8").strip()
        import jax

        jax.config.update("jax_platforms", "cpu")
        print(json.dumps(run_smoke()))
    else:
        main()
