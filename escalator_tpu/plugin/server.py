"""gRPC compute-plugin service: the device solver behind a local socket.

The SURVEY.md §2.7 "compute plugin" slot: a non-Python controller (e.g. a Go shell
like the reference) calls ``/escalator.Compute/Decide`` with a columnar cluster frame
(see codec.py) and gets the full decision frame back. Method handlers are registered
generically with bytes-level serializers — no protoc codegen, no per-pod message
overhead.

Methods:
- ``Decide``: cluster frame -> decision frame (batched kernel on the server's device)
- ``Health``: empty -> msgpack {device, backend, version}
"""

from __future__ import annotations

import logging
from concurrent import futures

import grpc
import msgpack
import numpy as np

from escalator_tpu import __version__
from escalator_tpu.metrics import metrics
from escalator_tpu.plugin import codec

log = logging.getLogger("escalator_tpu.plugin")

SERVICE_NAME = "escalator.Compute"


class _ComputeService:
    """Runs the batched kernel on whatever device JAX resolved (TPU when present,
    XLA-CPU otherwise — same traced program, the parity-preserving fallback)."""

    def __init__(self):
        from escalator_tpu.ops import kernel  # defer jax init to server start

        self._kernel = kernel
        import jax

        self._device = str(jax.devices()[0])

    def decide(self, request: bytes, context) -> bytes:
        import time

        cluster, now_sec = codec.decode_cluster(request)
        t0 = time.perf_counter()
        out = self._kernel.decide_jit(cluster, np.int64(now_sec))
        import jax

        jax.block_until_ready(out)
        metrics.solver_decide_latency.labels("grpc-server").observe(
            time.perf_counter() - t0
        )
        return codec.encode_decision(out)

    def health(self, request: bytes, context) -> bytes:
        return msgpack.packb(
            {"device": self._device, "version": __version__, "ok": True}
        )


def _identity(x: bytes) -> bytes:
    return x


def make_server(
    address: str = "127.0.0.1:50551", max_workers: int = 4
) -> grpc.Server:
    """Build (not start) the plugin server bound to ``address``.

    Probes the accelerator first: _ComputeService.__init__ touches
    jax.devices(), which hangs indefinitely on a wedged transport. The probe
    no-ops when this process already has live jax backends or is pinned to
    cpu (jaxconfig fast paths), so embedders and tests pay nothing."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    service = _ComputeService()
    handlers = {
        "Decide": grpc.unary_unary_rpc_method_handler(
            service.decide,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.health,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            # cluster frames are ~5 MB at 100k pods; the 4 MiB default would fail
            # exactly at the scale this plugin exists to serve
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    bound = server.add_insecure_port(address)
    if bound == 0:
        raise RuntimeError(f"failed to bind compute plugin to {address}")
    server._escalator_bound_port = bound  # convenience for tests with port 0
    log.info("compute plugin bound to %s (port %d)", address, bound)
    return server


def serve(address: str = "127.0.0.1:50551") -> None:  # pragma: no cover - CLI
    server = make_server(address)  # probes the accelerator (see make_server)
    server.start()
    log.info("compute plugin serving on %s", address)
    server.wait_for_termination()


if __name__ == "__main__":  # pragma: no cover
    import sys

    logging.basicConfig(level=logging.INFO)
    serve(sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1:50551")
