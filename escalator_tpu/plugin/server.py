"""gRPC compute-plugin service: the device solver behind a local socket.

The SURVEY.md §2.7 "compute plugin" slot: a non-Python controller (e.g. a Go shell
like the reference) calls ``/escalator.Compute/Decide`` with a columnar cluster frame
(see codec.py) and gets the full decision frame back. Method handlers are registered
generically with bytes-level serializers — no protoc codegen, no per-pod message
overhead.

Methods:
- ``Decide``: cluster frame -> decision frame (batched kernel on the server's
  device). The frame may carry the caller's span context; the response then
  carries the server-side span timeline so the caller's flight record nests
  the remote phases under its own tick.
- ``Health``: empty -> msgpack {device, backend, version, last_decide_age_sec,
  flight_recorder_depth, ticks_served} — the age/depth pair lets a remote
  health check tell a stale-but-alive controller (socket answers, no decide
  traffic) from a live one.
- ``Dump``: empty -> JSON bytes of the server's flight-recorder ring (the
  ``escalator-tpu debug-dump`` CLI's wire target).
"""

from __future__ import annotations

import logging
import threading
import time
from concurrent import futures

import grpc
import msgpack
import numpy as np

from escalator_tpu import __version__
from escalator_tpu import observability as obs
from escalator_tpu.metrics import metrics
from escalator_tpu.plugin import codec

log = logging.getLogger("escalator_tpu.plugin")

SERVICE_NAME = "escalator.Compute"


class _ComputeService:
    """Runs the batched kernel on whatever device JAX resolved (TPU when present,
    XLA-CPU otherwise — same traced program, the parity-preserving fallback)."""

    def __init__(self):
        from escalator_tpu.ops import kernel  # defer jax init to server start

        self._kernel = kernel
        import jax

        self._device = str(jax.devices()[0])
        obs.jaxmon.install()
        # handlers run on the gRPC worker pool: the served-tick stats are
        # read-modify-written under this lock so concurrent Decides (two
        # controllers, or controller + bench) never lose an increment
        self._stats_lock = threading.Lock()
        self._last_decide_unix: "float | None" = None
        self._ticks_served = 0

    def decide(self, request: bytes, context) -> bytes:
        t0 = time.perf_counter()
        cluster, now_sec, span_ctx = codec.decode_cluster_ctx(request)
        t_decode = time.perf_counter() - t0
        with obs.span("plugin_decide"):
            obs.annotate(backend="grpc-server", impl="xla")
            if span_ctx:
                # name the remote tick that asked, so server-side dumps
                # correlate with the caller's flight record
                obs.annotate(caller=span_ctx.get("path"),
                             trace_id=span_ctx.get("trace_id"))
            obs.add_phase("decode", t_decode)
            with obs.span("decide", kind="device"):
                out = obs.fence(
                    self._kernel.decide_jit(cluster, np.int64(now_sec)))
            metrics.solver_decide_latency.labels("grpc-server").observe(
                time.perf_counter() - t0 - t_decode
            )
            # ship the phases measured so far (decode + decide) back to the
            # caller; the encode phase below cannot serialize itself, so it
            # lands only in the server-local flight record. None when span
            # recording is disabled in this process (timeline absent).
            tl = obs.current_timeline()
            shipped = [p.as_dict() for p in tl.phases] if tl else None
            with obs.span("encode"):
                resp = codec.encode_decision(out, span_phases=shipped)
            with self._stats_lock:
                self._last_decide_unix = time.time()
                self._ticks_served += 1
            return resp

    def health(self, request: bytes, context) -> bytes:
        with self._stats_lock:
            last = self._last_decide_unix
            ticks = self._ticks_served
        age = -1.0 if last is None else time.time() - last
        # tail visibility without a Prometheus scrape (round 13): the root
        # tick quantiles from the streaming histograms — a stale-but-alive
        # server's TAIL is inspectable from the same health probe that
        # exposes its age (None until the first recorded tick)
        q = obs.histograms.tick_quantiles_ms()
        return msgpack.packb({
            "device": self._device,
            "version": __version__,
            "ok": True,
            # stale-but-alive detection: a controller whose plugin answers
            # health but whose decide traffic stopped shows a growing age
            "last_decide_age_sec": round(age, 3),
            "ticks_served": ticks,
            "flight_recorder_depth": obs.RECORDER.depth,
            "tick_p99_ms": q["p99"],
            "tick_p999_ms": q["p999"],
        })

    def dump(self, request: bytes, context) -> bytes:
        import json

        return json.dumps(obs.RECORDER.as_dump("plugin-dump")).encode()


def _identity(x: bytes) -> bytes:
    return x


def make_server(
    address: str = "127.0.0.1:50551", max_workers: int = 4
) -> grpc.Server:
    """Build (not start) the plugin server bound to ``address``.

    Probes the accelerator first: _ComputeService.__init__ touches
    jax.devices(), which hangs indefinitely on a wedged transport. The probe
    no-ops when this process already has live jax backends or is pinned to
    cpu (jaxconfig fast paths), so embedders and tests pay nothing."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    service = _ComputeService()
    handlers = {
        "Decide": grpc.unary_unary_rpc_method_handler(
            service.decide,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.health,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Dump": grpc.unary_unary_rpc_method_handler(
            service.dump,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            # cluster frames are ~5 MB at 100k pods; the 4 MiB default would fail
            # exactly at the scale this plugin exists to serve
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    bound = server.add_insecure_port(address)
    if bound == 0:
        raise RuntimeError(f"failed to bind compute plugin to {address}")
    server._escalator_bound_port = bound  # convenience for tests with port 0
    log.info("compute plugin bound to %s (port %d)", address, bound)
    return server


def serve(address: str = "127.0.0.1:50551") -> None:  # pragma: no cover - CLI
    server = make_server(address)  # probes the accelerator (see make_server)
    server.start()
    log.info("compute plugin serving on %s", address)
    server.wait_for_termination()


if __name__ == "__main__":  # pragma: no cover
    import sys

    logging.basicConfig(level=logging.INFO)
    serve(sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1:50551")
