"""gRPC compute-plugin service: the device solver behind a local socket.

The SURVEY.md §2.7 "compute plugin" slot: a non-Python controller (e.g. a Go shell
like the reference) calls ``/escalator.Compute/Decide`` with a columnar cluster frame
(see codec.py) and gets the full decision frame back. Method handlers are registered
generically with bytes-level serializers — no protoc codegen, no per-pod message
overhead.

Methods:
- ``Decide``: cluster frame -> decision frame (batched kernel on the server's
  device). The frame may carry the caller's span context; the response then
  carries the server-side span timeline so the caller's flight record nests
  the remote phases under its own tick. In FLEET mode
  (``make_server(fleet=…)``), a frame carrying the ``__tenant__`` sidecar
  routes through the continuous-batching scheduler instead: the request
  coalesces with other tenants' into one device dispatch
  (escalator_tpu/fleet/), backpressure surfaces as RESOURCE_EXHAUSTED with
  an ``escalator-retry-after-ms`` trailer, and a malformed/unknown tenant id
  is INVALID_ARGUMENT (validated BEFORE anything queues — it cannot poison a
  batch). Frames without the sidecar — mixed-version peers — serve the
  single-cluster path byte-identically to a fleet-disabled server.
- ``Health``: empty -> msgpack {device, backend, version, last_decide_age_sec,
  flight_recorder_depth, ticks_served} — the age/depth pair lets a remote
  health check tell a stale-but-alive controller (socket answers, no decide
  traffic) from a live one. Fleet mode adds a ``fleet`` section: tenant
  count, queue depth, admitted/rejected totals, oldest-waiting-request age
  (the batcher's own stale-but-alive signal).
- ``Dump``: empty -> JSON bytes of the server's flight-recorder ring (the
  ``escalator-tpu debug-dump`` CLI's wire target).
- ``Journal``: msgpack ``{since?: int}`` (or empty) -> msgpack
  ``{capacity, total_recorded, events: [...]}`` — the ops event journal
  (observability/journal.py: tenant lifecycle, admission rejects, SLO
  burns, chaos firings, watchdog breaches) with monotonic sequence
  numbers; ``since`` filters to events newer than a seq the caller already
  has. The ``escalator-tpu debug-journal`` CLI's wire target.
- ``Profile``: msgpack ``{ticks, timeout_sec}`` -> msgpack ``{ok, files:
  {relpath: bytes}, ...}`` — wraps ``jax.profiler.trace()`` around the next
  ``ticks`` decides this server serves and ships the TensorBoard/XPlane
  artifact back (the ``escalator-tpu debug-profile`` CLI's wire target).
  Degrades to ``{ok: False, unsupported: reason}`` where the platform lacks
  the profiler.
- ``Explain``: msgpack ``{tenant?: str, groups?: [int]}`` (or empty) ->
  msgpack decision-provenance doc (observability/provenance.py). Empty
  request = discovery: the known history keys + flap/mismatch health.
  With a tenant: per-group explanation documents re-derived LIVE from the
  resident fleet arenas (named terms, gate booleans, the one
  controller.go:332-351 threshold arm that fired, config echoes,
  bit-cross-check against the committed columns), the tenant's recent
  decision history ring, and its flap record. The ``escalator-tpu
  debug-explain`` CLI's wire target.
- ``TenantSnapshot`` / ``TenantAdopt`` (fleet mode only): warm tenant
  migration (round 20). Both speak ``__migrate__`` frames (codec.py):
  TenantSnapshot ``{op: "snapshot", tenant}`` freezes the tenant's arena
  row at a batch boundary into portable snapshot bytes; TenantAdopt
  ``{op: "adopt"}`` + blob scatters it into this partition's arenas as a
  resident tenant. The partition router (fleet/router.py) drives the
  migration sequence — snapshot on the source, evict, adopt on the
  target — through these.
"""

from __future__ import annotations

import logging
import os
import time
from concurrent import futures
from concurrent.futures import TimeoutError as FuturesTimeoutError
from dataclasses import dataclass

import grpc
import msgpack
import numpy as np

from escalator_tpu import __version__
from escalator_tpu import observability as obs
from escalator_tpu.analysis import lockwitness
from escalator_tpu.metrics import metrics
from escalator_tpu.plugin import codec

log = logging.getLogger("escalator_tpu.plugin")

SERVICE_NAME = "escalator.Compute"

#: Trailer carrying the scheduler's backoff hint on RESOURCE_EXHAUSTED —
#: the client RetryPolicy reads it as a backoff floor.
RETRY_AFTER_METADATA_KEY = "escalator-retry-after-ms"


@dataclass
class FleetConfig:
    """Knobs for the fleet decision service (engine arenas + scheduler).
    Defaults size a small fleet; bench cfg17 documents the C=10k envelope."""

    num_groups: int = 8
    pod_capacity: int = 256
    node_capacity: int = 64
    max_tenants: int = 16
    max_batch: int = 32
    flush_ms: float = 2.0
    queue_limit: int = 256
    per_tenant_inflight: int = 2
    #: per-request wait bound on the batch future (queue wait + service);
    #: far above any sane flush interval — a breach means a wedged worker
    decide_timeout_sec: float = 60.0
    #: mesh shards the tenant axis partitions over (0 = every device this
    #: process sees); tenants are embarrassingly parallel, so per-shard
    #: device time shrinks near-linearly with the mesh (round 16)
    num_shards: int = 1
    #: pipelined scheduler (round 16): batch k+1's host diff assembles
    #: while batch k's device program is in flight
    pipeline: bool = True
    #: admission classes (None = scheduler defaults: critical/standard/
    #: batch at weights 4/2/1, batch capped to half the queue); requests
    #: pick one via the tenant sidecar's "class" key
    classes: "tuple | None" = None
    default_class: "str | None" = None


def _journey_span_phases(journey: dict) -> list:
    """A fleet journey as ``spans.Phase.as_dict``-style entries: a parent
    ``journey`` phase spanning the e2e plus one child per stage, offsets
    cumulative from the enqueue (the stages are contiguous by
    construction). Offsets are journey-root-relative — the caller's trace
    exporter re-anchors them under its local rpc slice, exactly the
    grafted-remote-phase convention (spans.graft docstring)."""
    stages = journey.get("stages_ms") or {}
    phases = [{
        "name": "journey", "path": "journey",
        "ms": float(journey.get("e2e_ms", 0.0)),
        "kind": "host", "fenced": True, "offset_ms": 0.0,
    }]
    offset = 0.0
    from escalator_tpu.observability.histograms import JOURNEY_STAGES

    for stage in JOURNEY_STAGES:
        if stage not in stages:
            # a journey records only the stages it ran ("cached" appears
            # solely on cache-hit answers) — don't ship phantom phases
            continue
        ms = float(stages.get(stage, 0.0))
        phases.append({
            "name": stage, "path": f"journey/{stage}", "ms": round(ms, 4),
            "kind": "device" if stage == "dispatch" else "host",
            "fenced": True, "offset_ms": round(offset, 4),
        })
        offset += ms
    return phases


class _ComputeService:
    """Runs the batched kernel on whatever device JAX resolved (TPU when present,
    XLA-CPU otherwise — same traced program, the parity-preserving fallback)."""

    def __init__(self, fleet: "FleetConfig | None" = None):
        from escalator_tpu.ops import kernel  # defer jax init to server start

        self._kernel = kernel
        import jax

        self._device = str(jax.devices()[0])
        obs.jaxmon.install()
        # handlers run on the gRPC worker pool: the served-tick stats are
        # read-modify-written under this lock so concurrent Decides (two
        # controllers, or controller + bench) never lose an increment
        self._stats_lock = lockwitness.make_lock("server.stats")
        self._last_decide_unix: "float | None" = None
        self._ticks_served = 0
        self._fleet_cfg = fleet
        self._fleet = None
        if fleet is not None:
            from escalator_tpu.fleet import (
                DEFAULT_CLASSES,
                FleetEngine,
                FleetScheduler,
            )

            engine = FleetEngine(
                num_groups=fleet.num_groups,
                pod_capacity=fleet.pod_capacity,
                node_capacity=fleet.node_capacity,
                max_tenants=fleet.max_tenants,
                num_shards=fleet.num_shards)
            self._fleet = FleetScheduler(
                engine, max_batch=fleet.max_batch, flush_ms=fleet.flush_ms,
                queue_limit=fleet.queue_limit,
                per_tenant_inflight=fleet.per_tenant_inflight,
                classes=(fleet.classes if fleet.classes is not None
                         else DEFAULT_CLASSES),
                default_class=fleet.default_class,
                pipeline=fleet.pipeline)

    @property
    def fleet(self):
        """The live FleetScheduler (None outside fleet mode) — tests and
        embedders reach the engine through it."""
        return self._fleet

    def _empty_decision(self):
        """A zero-group DecisionArrays — the evict ack's payload (the frame
        format has no empty response; sidecars need a carrier)."""
        from dataclasses import fields as dfields

        k = self._kernel
        z32 = np.zeros(0, np.int32)
        cols = {}
        for f in dfields(k.DecisionArrays):
            if f.name in ("untainted_offsets", "tainted_offsets"):
                cols[f.name] = np.zeros(1, np.int32)
            elif f.name in ("cpu_percent", "mem_percent"):
                cols[f.name] = np.zeros(0, np.float64)
            elif f.name in ("cpu_request_milli", "mem_request_bytes",
                            "cpu_capacity_milli", "mem_capacity_bytes"):
                cols[f.name] = np.zeros(0, np.int64)
            elif f.name == "reap_mask":
                cols[f.name] = np.zeros(0, bool)
            else:
                cols[f.name] = z32
        return k.DecisionArrays(**cols)

    def decide(self, request: bytes, context) -> bytes:
        t0 = time.perf_counter()
        cluster, now_sec, span_ctx, tenant, delta = (
            codec.decode_request_full(request))
        t_decode = time.perf_counter() - t0
        if delta is not None:
            # streaming tenants only (round 18): a delta frame indexes into
            # server-side per-tenant state, which exists nowhere but the
            # fleet engine — on a fleet-disabled server (or without a
            # tenant to look the state up under) it has no meaning, so
            # reject loudly rather than decide on an empty cluster
            if self._fleet is None or tenant is None:
                metrics.fleet_admission_rejects.labels("invalid-tenant").inc()
                context.abort(
                    grpc.StatusCode.INVALID_ARGUMENT,
                    "delta frames require a fleet-mode server and a tenant "
                    "sidecar (send full frames to this endpoint)")
            return self._fleet_decide(None, now_sec, tenant, context,
                                      delta=delta)
        if tenant is not None and self._fleet is not None:
            return self._fleet_decide(cluster, now_sec, tenant, context)
        # no tenant sidecar (mixed-version peer), or fleet mode off: the
        # single-cluster path, byte-identical to the pre-fleet server
        with obs.span("plugin_decide"):
            obs.annotate(backend="grpc-server", impl="xla")
            if span_ctx:
                # name the remote tick that asked, so server-side dumps
                # correlate with the caller's flight record
                obs.annotate(caller=span_ctx.get("path"),
                             trace_id=span_ctx.get("trace_id"))
            obs.add_phase("decode", t_decode)
            with obs.span("decide", kind="device"):
                out = obs.fence(
                    self._kernel.decide_jit(cluster, np.int64(now_sec)))
            metrics.solver_decide_latency.labels("grpc-server").observe(
                time.perf_counter() - t0 - t_decode
            )
            # ship the phases measured so far (decode + decide) back to the
            # caller; the encode phase below cannot serialize itself, so it
            # lands only in the server-local flight record. None when span
            # recording is disabled in this process (timeline absent).
            tl = obs.current_timeline()
            shipped = [p.as_dict() for p in tl.phases] if tl else None
            with obs.span("encode"):
                resp = codec.encode_decision(out, span_phases=shipped)
            with self._stats_lock:
                self._last_decide_unix = time.time()
                self._ticks_served += 1
            return resp

    def _fleet_decide(self, cluster, now_sec: int, tenant: dict,
                      context, delta: "dict | None" = None) -> bytes:
        """One tenant's decide through the continuous batcher. Validation
        runs HERE, before anything queues: a malformed tenant id aborts
        this RPC alone (INVALID_ARGUMENT) and the batch it would have
        ridden in never sees it. ``delta`` (a ``codec.decode_request_full``
        delta dict) replaces ``cluster`` for streaming tenants — the
        engine applies the packed drain to its resident twin instead of
        diffing a full repack."""
        from escalator_tpu.fleet import AdmissionError, DeltaFrame, TenantError

        if not isinstance(tenant, dict):
            metrics.fleet_admission_rejects.labels("invalid-tenant").inc()
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "tenant sidecar must be a msgpack map")
        frame = None
        if delta is not None:
            frame = DeltaFrame(
                shapes=delta["shapes"], pod_idx=delta["pod_idx"],
                pod_vals=delta["pod_vals"], node_idx=delta["node_idx"],
                node_vals=delta["node_vals"], groups=delta["groups"])
        try:
            if tenant.get("evict"):
                fut = self._fleet.evict(tenant.get("id"))
            else:
                fut = self._fleet.submit(tenant.get("id"), cluster,
                                         int(now_sec),
                                         klass=tenant.get("class"),
                                         delta=frame)
        except TenantError as e:
            metrics.fleet_admission_rejects.labels("invalid-tenant").inc()
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except AdmissionError as e:
            # backpressure: the retry-after estimate rides a trailer the
            # client RetryPolicy uses as its backoff floor
            context.set_trailing_metadata((
                (RETRY_AFTER_METADATA_KEY, f"{e.retry_after_ms:.0f}"),
            ))
            context.abort(grpc.StatusCode.RESOURCE_EXHAUSTED, str(e))
        timeout = (self._fleet_cfg.decide_timeout_sec
                   if self._fleet_cfg else 60.0)
        try:
            result = fut.result(timeout=timeout)
        except TenantError as e:
            # raced validation (e.g. concurrent evict): still this RPC's
            # problem alone
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except FuturesTimeoutError:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED,
                          f"fleet batch did not serve within {timeout}s")
        with self._stats_lock:
            self._last_decide_unix = time.time()
            self._ticks_served += 1
        from escalator_tpu.fleet import EvictAck

        if isinstance(result, EvictAck):
            return codec.encode_decision(
                self._empty_decision(), fleet={"evicted": result.tenant_id})
        fleet_meta = {
            "ordered": bool(result.ordered),
            "tenant": result.tenant_id,
            "batch_size": int(result.batch_size),
            "shard": int(result.shard),
            # digest fast path (round 18): True when this answer came from
            # the per-tenant decision cache without entering the micro-batch
            "cached": bool(getattr(result, "cached", False)),
        }
        # journey propagation (round 17): the server-side journey rides the
        # response both as structured data (the fleet sidecar, for
        # programmatic clients) and as span phases the caller's obs.graft
        # nests under its rpc span — so the client-side submit→response
        # slice visibly WRAPS the server's admission/assembly/dispatch/
        # unpack decomposition in one debug-trace render.
        journey = getattr(result, "journey", None)
        shipped = None
        if journey:
            fleet_meta["journey"] = {
                k: journey[k] for k in ("klass", "deferrals", "stages_ms",
                                        "e2e_ms") if k in journey}
            shipped = _journey_span_phases(journey)
        return codec.encode_decision(result.arrays, fleet=fleet_meta,
                                     span_phases=shipped)

    def health(self, request: bytes, context) -> bytes:
        with self._stats_lock:
            last = self._last_decide_unix
            ticks = self._ticks_served
        age = -1.0 if last is None else time.time() - last
        # tail visibility without a Prometheus scrape (round 13): the root
        # tick quantiles from the streaming histograms — a stale-but-alive
        # server's TAIL is inspectable from the same health probe that
        # exposes its age (None until the first recorded tick)
        q = obs.histograms.tick_quantiles_ms()
        doc = {
            "device": self._device,
            "version": __version__,
            "ok": True,
            # stale-but-alive detection: a controller whose plugin answers
            # health but whose decide traffic stopped shows a growing age
            "last_decide_age_sec": round(age, 3),
            "ticks_served": ticks,
            "flight_recorder_depth": obs.RECORDER.depth,
            "tick_p99_ms": q["p99"],
            "tick_p999_ms": q["p999"],
            # device resource observatory (round 15): what this server's
            # device is holding — per-owner registered bytes + allocator
            # cross-check (explicit "unsupported" on runtimes that report
            # nothing), same section every flight dump carries
            "memory": obs.resources.memory_section(),
        }
        # decision provenance (round 19): flap/mismatch health from the
        # same probe that exposes staleness — a flapping fleet is visible
        # without a Prometheus scrape or a flight dump
        from escalator_tpu.observability import provenance

        doc["provenance"] = provenance.health_section()
        if self._fleet is not None:
            # the batcher's stale-but-alive surface (mirrors tick_p99_ms):
            # a wedged worker shows oldest_waiting growing while the queue
            # answers admissions and this health probe stays green.
            # stats() snapshots the counters UNDER the scheduler lock
            # (round-16 satellite: the old field-by-field reads could tear
            # mid-batch) and carries the per-class SLO surface.
            doc["fleet"] = {
                "tenants": self._fleet.engine.tenant_count,
                "batches": self._fleet.engine.batches,
                "buckets": self._fleet.engine.buckets,
                "shards": self._fleet.engine.shards,
                **self._fleet.stats(),
            }
        return msgpack.packb(doc)

    def dump(self, request: bytes, context) -> bytes:
        import json

        return json.dumps(obs.RECORDER.as_dump("plugin-dump")).encode()

    def journal(self, request: bytes, context) -> bytes:
        """The ops event journal over the wire (``debug-journal``'s live
        source). Request: empty, or msgpack ``{since: int}`` to fetch only
        events newer than a sequence number the caller already holds."""
        since = 0
        if request:
            try:
                req = msgpack.unpackb(request)
            except Exception:  # noqa: BLE001 - malformed request: named error
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "Journal request must be a msgpack map")
            if not isinstance(req, dict):
                # msgpack-valid but not a map (a bare since value, a
                # list): same named error — silently serving the FULL
                # journal would drop the caller's since filter
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "Journal request must be a msgpack map")
            since = int(req.get("since", 0) or 0)
        return msgpack.packb(obs.journal.JOURNAL.as_doc(since_seq=since))

    def explain(self, request: bytes, context) -> bytes:
        """Decision provenance over the wire (``debug-explain``'s live
        source). Request: empty, or msgpack ``{tenant?: str, groups?:
        [int]}``. Without a tenant the response is DISCOVERY — the known
        history keys plus the provenance health row. With one, the
        per-group explanation documents from the registered live explainer
        (the fleet engine's wildcard registration / an embedded
        controller's), the tenant's recent decision history, and its flap
        record. NOT_FOUND when neither an explainer nor any history covers
        the key — fleet tenants appear after their first decide."""
        from escalator_tpu.observability import provenance

        req: dict = {}
        if request:
            try:
                req = msgpack.unpackb(request)
            except Exception:  # noqa: BLE001 - malformed request: named error
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "Explain request must be a msgpack map")
            if not isinstance(req, dict):
                context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                              "Explain request must be a msgpack map")
        tenant = req.get("tenant")
        if tenant is None:
            return msgpack.packb({
                "keys": provenance.HISTORY.keys(),
                "health": provenance.health_section(),
            })
        key = str(tenant)
        groups = req.get("groups")
        if groups is not None:
            groups = [int(g) for g in groups]
        docs = provenance.explain_for(key, groups)
        history = provenance.HISTORY.history(key)
        if docs is None and not history:
            context.abort(
                grpc.StatusCode.NOT_FOUND,
                f"no live explainer or decision history covers {key!r} "
                "(fleet tenants appear after their first decide)")
        return msgpack.packb({
            "key": key,
            "explanations": docs,
            "history": history,
            "flaps": [r for r in list(provenance.FLAPS.recent)
                      if r.get("key") == key][-16:],
        })

    def tenant_snapshot(self, request: bytes, context) -> bytes:
        """Freeze one tenant's arena row for migration (round 20).
        Request: a ``__migrate__`` frame ``{op: "snapshot", tenant}``.
        Response: ``{op: "row", tenant}`` carrying the tenant-row snapshot
        blob (the ``ops.snapshot`` container bytes — same format a
        checkpoint file holds, so the router can also park it on disk).
        The scheduler quiesces the tenant first (zero queued + inflight)
        and the engine freezes at a batch boundary, so the row is one
        committed tick; the caller owns keeping NEW requests for this
        tenant out while the migration is in flight (the router holds the
        tenant's stream)."""
        from escalator_tpu.fleet import TenantError
        from escalator_tpu.ops import snapshot as snaplib

        if self._fleet is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "TenantSnapshot requires a fleet-mode server")
        try:
            doc, _blob = codec.decode_migration(request)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if doc.get("op") != "snapshot" or not doc.get("tenant"):
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "TenantSnapshot request must be {op: 'snapshot', "
                "tenant: <id>}")
        tenant = str(doc["tenant"])
        timeout = float(doc.get("timeout_sec", 30.0) or 30.0)
        try:
            leaves, meta = self._fleet.snapshot_tenant(
                tenant, timeout_sec=timeout)
        except TenantError as e:
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except (TimeoutError, RuntimeError) as e:
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        return codec.encode_migration(
            "row", tenant, snaplib.snapshot_to_bytes(leaves, meta))

    def tenant_adopt(self, request: bytes, context) -> bytes:
        """Adopt a migrated tenant row (round 20). Request: a
        ``__migrate__`` frame ``{op: "adopt", tenant}`` whose blob is the
        TenantSnapshot response's snapshot bytes. Response: ``{op: "ack",
        tenant, shard, row}``. Rejections keep the restore taxonomy:
        corrupt rows are INVALID_ARGUMENT, rows this arena cannot hold
        (bucket caps, already-resident id) are FAILED_PRECONDITION — the
        router falls back to the cold path (full first frame), never to a
        wrong adopt."""
        from escalator_tpu.fleet import TenantError
        from escalator_tpu.ops import snapshot as snaplib

        if self._fleet is None:
            context.abort(
                grpc.StatusCode.FAILED_PRECONDITION,
                "TenantAdopt requires a fleet-mode server")
        try:
            doc, blob = codec.decode_migration(request)
        except ValueError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        if doc.get("op") != "adopt" or not blob:
            context.abort(
                grpc.StatusCode.INVALID_ARGUMENT,
                "TenantAdopt request must be {op: 'adopt'} with a "
                "snapshot blob")
        try:
            leaves, meta = snaplib.snapshot_from_bytes(
                blob, label="<tenant-adopt>")
            shard, row = self._fleet.adopt_tenant(leaves, meta)
        except snaplib.SnapshotCorruptError as e:
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except TenantError as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION, str(e))
        return codec.encode_migration(
            "ack", meta.get("tenant"), shard=int(shard), row=int(row))

    #: total profile artifact bytes one Profile RPC will ship back — a
    #: pathological capture must not balloon one response without bound
    _PROFILE_MAX_BYTES = 64 << 20

    def profile(self, request: bytes, context) -> bytes:
        """On-demand profiler capture: arm ``jax.profiler`` around the next
        ``ticks`` root ticks this process completes (decides served by this
        plugin count; so do any local controller ticks in an embedded
        server) and return the XPlane trace files. Blocking: the RPC
        returns when the Kth tick lands or ``timeout_sec`` expires — a
        timeout still ships whatever the trace captured (``timed_out``
        flag), because a partial on-chip profile beats none."""
        import shutil
        import tempfile

        from escalator_tpu.observability import resources

        try:
            req = msgpack.unpackb(request) if request else {}
        except Exception:  # noqa: BLE001 - malformed request: named error
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "Profile request must be a msgpack map")
        if not isinstance(req, dict):
            # msgpack-valid but not a map: same named error, not a
            # server-side AttributeError surfacing as UNKNOWN
            context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                          "Profile request must be a msgpack map")
        ticks = int(req.get("ticks", 4) or 4)
        timeout = float(req.get("timeout_sec", 60.0) or 60.0)
        out_dir = tempfile.mkdtemp(prefix="escalator-tpu-profile-")
        try:
            res = resources.PROFILER.capture(ticks, out_dir, timeout=timeout)
            if not res.get("ok"):
                return msgpack.packb(res)
            files: dict = {}
            total = 0
            for rel in resources.trace_files(out_dir):
                path = os.path.join(out_dir, rel)
                size = os.path.getsize(path)
                if total + size > self._PROFILE_MAX_BYTES:
                    res["truncated"] = True
                    break
                with open(path, "rb") as f:
                    files[rel] = f.read()
                total += size
            res.pop("dir", None)   # server-local tempdir: meaningless remote
            res["files"] = files
            res["total_bytes"] = total
            return msgpack.packb(res)
        finally:
            shutil.rmtree(out_dir, ignore_errors=True)


def _identity(x: bytes) -> bytes:
    return x


def make_server(
    address: str = "127.0.0.1:50551", max_workers: int = 4,
    fleet: "FleetConfig | None" = None,
) -> grpc.Server:
    """Build (not start) the plugin server bound to ``address``.

    ``fleet`` (a :class:`FleetConfig`) enables the multi-tenant
    continuous-batching mode: tenant-tagged frames coalesce into fleet
    micro-batches; untagged frames keep the single-cluster path. The built
    server exposes the service as ``server._escalator_service`` so tests
    and embedders can reach the scheduler/engine.

    Probes the accelerator first: _ComputeService.__init__ touches
    jax.devices(), which hangs indefinitely on a wedged transport. The probe
    no-ops when this process already has live jax backends or is pinned to
    cpu (jaxconfig fast paths), so embedders and tests pay nothing."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    service = _ComputeService(fleet=fleet)
    handlers = {
        "Decide": grpc.unary_unary_rpc_method_handler(
            service.decide,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Health": grpc.unary_unary_rpc_method_handler(
            service.health,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Dump": grpc.unary_unary_rpc_method_handler(
            service.dump,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Journal": grpc.unary_unary_rpc_method_handler(
            service.journal,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Profile": grpc.unary_unary_rpc_method_handler(
            service.profile,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "Explain": grpc.unary_unary_rpc_method_handler(
            service.explain,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "TenantSnapshot": grpc.unary_unary_rpc_method_handler(
            service.tenant_snapshot,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
        "TenantAdopt": grpc.unary_unary_rpc_method_handler(
            service.tenant_adopt,
            request_deserializer=_identity,
            response_serializer=_identity,
        ),
    }
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=max_workers),
        options=[
            # cluster frames are ~5 MB at 100k pods; the 4 MiB default would fail
            # exactly at the scale this plugin exists to serve
            ("grpc.max_receive_message_length", -1),
            ("grpc.max_send_message_length", -1),
        ],
    )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
    )
    bound = server.add_insecure_port(address)
    if bound == 0:
        raise RuntimeError(f"failed to bind compute plugin to {address}")
    server._escalator_bound_port = bound  # convenience for tests with port 0
    server._escalator_service = service   # fleet scheduler/engine access
    if service.fleet is not None:
        # tear the batcher down WITH the server: stop() otherwise leaves the
        # fleet worker thread (and the device arenas it owns) alive for the
        # rest of the process, and queued requests could still dispatch
        # after the listener is gone
        grpc_stop = server.stop

        def stop(grace=None):
            service.fleet.shutdown()
            return grpc_stop(grace)

        server.stop = stop
    log.info("compute plugin bound to %s (port %d)%s", address, bound,
             " [fleet mode]" if fleet is not None else "")
    return server


def serve(address: str = "127.0.0.1:50551") -> None:  # pragma: no cover - CLI
    server = make_server(address)  # probes the accelerator (see make_server)
    server.start()
    log.info("compute plugin serving on %s", address)
    server.wait_for_termination()


if __name__ == "__main__":  # pragma: no cover
    import sys

    logging.basicConfig(level=logging.INFO)
    serve(sys.argv[1] if len(sys.argv) > 1 else "127.0.0.1:50551")
