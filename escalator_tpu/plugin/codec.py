"""Wire codec for the compute plugin: flat array buffers, not per-pod messages.

SURVEY.md §7 flags host<->device marshalling of 100k pods as a hard part; the same
applies to the plugin's process boundary. So the wire format is columnar: a msgpack
header (field names, dtypes, shapes, offsets) followed by the raw little-endian array
buffers, zero-copy decodable with ``np.frombuffer``. A 100k-pod cluster is ~5 MB and
encodes/decodes in single-digit milliseconds — per-pod protobuf messages would be
~100x slower, which is why this framework does NOT model the request as repeated Pod
messages (the reference has no plugin boundary at all; its analog is in-process Go
structs)."""

from __future__ import annotations

import struct
from dataclasses import fields
from typing import Any, Dict, List, Optional, Tuple

import msgpack
import numpy as np

from escalator_tpu.core.arrays import ClusterArrays, GroupArrays, NodeArrays, PodArrays

_MAGIC = b"ESCT"
_VERSION = 1

#: Span-context / span-timeline sidecars ride in the SAME columnar frame as
#: msgpack-bytes pseudo-arrays under these names. Both directions are
#: OPTIONAL and version-tolerant by construction: a decoder that predates
#: them never looks the names up (section decoding pulls only its dataclass
#: fields), and a new decoder treats their absence as "peer sent none" —
#: so tracing interoperates across mixed-version peers without a _VERSION
#: bump, exactly like the _OPTIONAL_DEFAULTS columns.
_SPAN_CTX_KEY = "__spanctx__"
_SPANS_KEY = "__spans__"

#: Fleet-mode sidecars (round 14), same mixed-version contract as the span
#: sidecars: ``__tenant__`` rides the REQUEST frame (msgpack
#: ``{"id": str}``, optionally ``{"evict": True}``) and names the tenant a
#: fleet-mode server batches this cluster under; ``__fleet__`` rides the
#: RESPONSE (msgpack ``{"ordered": bool, "tenant": str, "batch_size":
#: int}``). A peer that predates them never looks the names up, and a new
#: decoder treats absence as "single-cluster peer" — so a tenant-tagged
#: frame decodes byte-identically to an untagged one on a pre-fleet (or
#: fleet-disabled) server, and vice versa.
_TENANT_KEY = "__tenant__"
_FLEET_KEY = "__fleet__"

#: Tenant delta-frame sidecar (round 18). A streaming client that keeps a
#: state-store twin of its cluster sends, after the first full frame, only
#: the packed dirty drain: ``__delta__`` (msgpack ``{"shapes": [G, P, N]}``
#: — the logical section widths the slots index into) plus ``dp.idx`` /
#: ``dp.<field>`` (pod scatter batch) and ``dn.idx`` / ``dn.<field>``
#: (node scatter batch), with an OPTIONAL full ``g.`` section when group
#: options changed. Mixed-version behavior is deliberate and documented:
#: a delta frame has no ``p.``/``n.`` sections, so an OLD server raises
#: its existing named missing-array ValueError ("frame is missing required
#: array 'p.group' ...") — a loud incompatible-revision signal, never a
#: silent wrong answer — and untagged full frames stay byte-identical
#: (test-locked), so non-streaming tenants are unaffected.
_DELTA_KEY = "__delta__"

#: Tenant-migration sidecar (round 20). Migration RPCs (``TenantSnapshot``
#: / ``TenantAdopt``) speak the SAME columnar frame as every other plugin
#: message: ``__migrate__`` is a msgpack dict (``{"op": str, "tenant":
#: str, …}`` — extra keys like shard/row placements ride along) and the
#: tenant-row snapshot blob (the ``ops.snapshot`` byte format, crc-checked
#: by its own reader) rides as the ``snap`` uint8 pseudo-array. Mixed
#: versions stay loud: a pre-round-20 server has no migration handlers at
#: all (UNIMPLEMENTED from the gRPC layer), and a torn sidecar raises the
#: named error below — never a silent misroute into the decide path.
_MIGRATE_KEY = "__migrate__"

#: Fields added to the wire format after v1 frames shipped, with the default a
#: decoder must assume when a peer's frame predates them. Keyed by frame array
#: name; the value is (dtype, fill) — the array is materialised against the
#: section's lane count. Keeping explicit defaults (rather than bumping
#: _VERSION) lets mixed-version peers interoperate with *defined* semantics:
#: an old frame decodes as "no group uses emptiest-first", which is exactly
#: what an old encoder meant.
_OPTIONAL_DEFAULTS = {
    "g.emptiest": (np.bool_, False),
}


def _encode_arrays(named: List[Tuple[str, np.ndarray]]) -> bytes:
    header = []
    arrays = []
    offset = 0
    for name, arr in named:
        arr = np.ascontiguousarray(arr)
        header.append(
            {
                "name": name,
                "dtype": arr.dtype.str,
                "shape": list(arr.shape),
                "offset": offset,
                "nbytes": arr.nbytes,
            }
        )
        arrays.append(arr)
        offset += arr.nbytes
    head = msgpack.packb({"v": _VERSION, "arrays": header})
    # single-copy assembly: offsets are known up front, so each array buffer
    # lands directly in the frame (tobytes() + join would copy twice)
    base = 8 + len(head)
    frame = bytearray(base + offset)
    frame[:4] = _MAGIC
    struct.pack_into("<I", frame, 4, len(head))
    frame[8:base] = head
    for spec, arr in zip(header, arrays, strict=True):
        start = base + spec["offset"]
        frame[start : start + spec["nbytes"]] = memoryview(arr).cast("B")
    return bytes(frame)


def _decode_arrays(data: bytes) -> Dict[str, np.ndarray]:
    if data[:4] != _MAGIC:
        raise ValueError("bad magic; not an escalator-tpu array frame")
    (head_len,) = struct.unpack_from("<I", data, 4)
    head = msgpack.unpackb(data[8 : 8 + head_len])
    if head["v"] != _VERSION:
        raise ValueError(f"unsupported frame version {head['v']}")
    base = 8 + head_len
    out = {}
    for spec in head["arrays"]:
        dtype = np.dtype(spec["dtype"])
        count = spec["nbytes"] // dtype.itemsize
        # genuinely zero-copy: views straight into the received frame
        out[spec["name"]] = np.frombuffer(
            data, dtype=dtype, count=count, offset=base + spec["offset"]
        ).reshape(spec["shape"])
    return out


def _msgpack_array(obj: Any) -> np.ndarray:
    """A msgpack document as a uint8 pseudo-array frame entry."""
    return np.frombuffer(msgpack.packb(obj), np.uint8)


def encode_cluster(cluster: ClusterArrays, now_sec: int,
                   span_ctx: Optional[Dict[str, Any]] = None,
                   tenant: Optional[Dict[str, Any]] = None) -> bytes:
    """``span_ctx`` (optional) propagates the caller's span context across
    the process boundary — a small msgpack dict (caller span path, trace
    id) the server annotates its own tick record with, so a plugin-side
    flight record names which remote tick asked for it. ``tenant``
    (optional) is the fleet-mode tenant sidecar (``{"id": str}``); a
    server without fleet mode ignores it and serves the single-cluster
    decide."""
    named = [("__now__", np.array([now_sec], np.int64))]
    if span_ctx:
        named.append((_SPAN_CTX_KEY, _msgpack_array(span_ctx)))
    if tenant:
        named.append((_TENANT_KEY, _msgpack_array(tenant)))
    for prefix, section in (
        ("g.", cluster.groups),
        ("p.", cluster.pods),
        ("n.", cluster.nodes),
    ):
        for f in fields(section):
            named.append((prefix + f.name, getattr(section, f.name)))
    return _encode_arrays(named)


def encode_delta(now_sec: int, shapes: Tuple[int, int, int],
                 pod_idx: np.ndarray, pod_vals: PodArrays,
                 node_idx: np.ndarray, node_vals: NodeArrays,
                 groups: Optional[GroupArrays] = None,
                 span_ctx: Optional[Dict[str, Any]] = None,
                 tenant: Optional[Dict[str, Any]] = None) -> bytes:
    """Encode a tenant delta frame (see ``_DELTA_KEY``): the packed dirty
    drain of a client-side state-store twin instead of the full cluster.
    ``shapes`` is ``(G, P, N)`` — the logical widths the server validates
    the scatter slots against (growth past the server's buckets requires a
    full frame). ``groups`` rides along only when group options changed;
    omitting it means "groups unchanged since my last frame"."""
    named = [("__now__", np.array([now_sec], np.int64))]
    if span_ctx:
        named.append((_SPAN_CTX_KEY, _msgpack_array(span_ctx)))
    if tenant:
        named.append((_TENANT_KEY, _msgpack_array(tenant)))
    named.append((_DELTA_KEY, _msgpack_array(
        {"shapes": [int(s) for s in shapes]})))
    named.append(("dp.idx", np.asarray(pod_idx, np.int32)))
    for f in fields(pod_vals):
        named.append(("dp." + f.name, getattr(pod_vals, f.name)))
    named.append(("dn.idx", np.asarray(node_idx, np.int32)))
    for f in fields(node_vals):
        named.append(("dn." + f.name, getattr(node_vals, f.name)))
    if groups is not None:
        for f in fields(groups):
            named.append(("g." + f.name, getattr(groups, f.name)))
    return _encode_arrays(named)


def _section(arrays: Dict[str, np.ndarray], prefix: str, cls):
    """Build one SoA section, filling documented defaults for fields an older
    peer's frame predates (see _OPTIONAL_DEFAULTS). A missing field with no
    documented default is a hard, *named* error rather than a KeyError."""
    lanes = next(
        (len(arrays[prefix + f.name]) for f in fields(cls) if prefix + f.name in arrays),
        0,
    )
    out = {}
    for f in fields(cls):
        key = prefix + f.name
        arr = arrays.get(key)
        if arr is None:
            if key not in _OPTIONAL_DEFAULTS:
                raise ValueError(
                    f"frame is missing required array {key!r} "
                    "(peer speaks an incompatible codec revision)"
                )
            dtype, fill = _OPTIONAL_DEFAULTS[key]
            arr = np.full(lanes, fill, dtype)
        out[f.name] = arr
    return cls(**out)


def _unpack_sidecar(arrays: Dict[str, np.ndarray], key: str) -> Optional[Any]:
    raw = arrays.get(key)
    if raw is None:
        return None
    try:
        return msgpack.unpackb(raw.tobytes())
    except Exception:  # noqa: BLE001 - a torn sidecar must not fail a decide
        return None


def decode_cluster(data: bytes) -> Tuple[ClusterArrays, int]:
    cluster, now_sec, _ctx = decode_cluster_ctx(data)
    return cluster, now_sec


def decode_cluster_ctx(
    data: bytes,
) -> Tuple[ClusterArrays, int, Optional[Dict[str, Any]]]:
    """:func:`decode_cluster` plus the caller's span context (None when the
    peer sent none / predates tracing)."""
    cluster, now_sec, span_ctx, _tenant = decode_cluster_full(data)
    return cluster, now_sec, span_ctx


def decode_cluster_full(
    data: bytes,
) -> Tuple[ClusterArrays, int, Optional[Dict[str, Any]],
           Optional[Dict[str, Any]]]:
    """:func:`decode_cluster_ctx` plus the fleet tenant sidecar (None when
    the peer sent none / predates fleet mode). A present-but-torn tenant
    sidecar decodes as the raw (unvalidated) msgpack value or None — the
    SERVER owns validation, because a malformed tenant must become a named
    INVALID_ARGUMENT, not a silent single-cluster fallback."""
    arrays = _decode_arrays(data)
    now_sec = int(arrays.pop("__now__")[0])
    span_ctx = _unpack_sidecar(arrays, _SPAN_CTX_KEY)
    raw_tenant = arrays.get(_TENANT_KEY)
    if raw_tenant is None:
        tenant = None
    else:
        try:
            tenant = msgpack.unpackb(raw_tenant.tobytes())
        except Exception:  # noqa: BLE001 - torn sidecar: present but invalid
            tenant = {"id": None}
    g = _section(arrays, "g.", GroupArrays)
    p = _section(arrays, "p.", PodArrays)
    n = _section(arrays, "n.", NodeArrays)
    return ClusterArrays(groups=g, pods=p, nodes=n), now_sec, span_ctx, tenant


def decode_request_full(
    data: bytes,
) -> Tuple[Optional[ClusterArrays], int, Optional[Dict[str, Any]],
           Optional[Dict[str, Any]], Optional[Dict[str, Any]]]:
    """:func:`decode_cluster_full` generalised to BOTH request frame kinds
    (round 18): returns ``(cluster, now_sec, span_ctx, tenant, delta)``
    where exactly one of ``cluster`` / ``delta`` is non-None. ``delta`` is
    a dict — ``{"shapes": (G, P, N), "pod_idx", "pod_vals": PodArrays,
    "node_idx", "node_vals": NodeArrays, "groups": GroupArrays | None}``
    — mirroring ``fleet.service.DeltaFrame``; the server owns turning it
    into one (and rejecting deltas when fleet mode is off), the same way
    it owns tenant validation. A torn ``__delta__`` sidecar is a hard
    named error, not a fallback: silently decoding a delta frame as a
    (sectionless) full frame would hand the engine an empty cluster."""
    arrays = _decode_arrays(data)
    now_sec = int(arrays.pop("__now__")[0])
    span_ctx = _unpack_sidecar(arrays, _SPAN_CTX_KEY)
    raw_tenant = arrays.get(_TENANT_KEY)
    if raw_tenant is None:
        tenant = None
    else:
        try:
            tenant = msgpack.unpackb(raw_tenant.tobytes())
        except Exception:  # noqa: BLE001 - torn sidecar: present but invalid
            tenant = {"id": None}
    raw_delta = arrays.get(_DELTA_KEY)
    if raw_delta is None:
        g = _section(arrays, "g.", GroupArrays)
        p = _section(arrays, "p.", PodArrays)
        n = _section(arrays, "n.", NodeArrays)
        return (ClusterArrays(groups=g, pods=p, nodes=n), now_sec, span_ctx,
                tenant, None)
    try:
        meta = msgpack.unpackb(raw_delta.tobytes())
        shapes = tuple(int(s) for s in meta["shapes"])
        assert len(shapes) == 3
    except Exception as e:  # noqa: BLE001 - torn delta header is fatal
        raise ValueError(
            "frame carries a torn __delta__ sidecar (cannot fall back to "
            "full-frame decode: a delta frame has no p./n. sections)"
        ) from e
    groups = (_section(arrays, "g.", GroupArrays)
              if any(k.startswith("g.") for k in arrays) else None)
    delta = {
        "shapes": shapes,
        "pod_idx": arrays["dp.idx"],
        "pod_vals": _section(arrays, "dp.", PodArrays),
        "node_idx": arrays["dn.idx"],
        "node_vals": _section(arrays, "dn.", NodeArrays),
        "groups": groups,
    }
    return None, now_sec, span_ctx, tenant, delta


def encode_decision(out, span_phases: Optional[List[Dict[str, Any]]] = None,
                    fleet: Optional[Dict[str, Any]] = None) -> bytes:
    """Encode DecisionArrays (device or numpy) to a frame. ``span_phases``
    (optional, ``spans.Phase.as_dict`` form) ships the server-side timeline
    back so the caller can graft it under its own tick span. ``fleet``
    (optional) is the fleet-mode response sidecar (``{"ordered": bool,
    ...}``) — its absence tells the client the decision came off the
    single-cluster path (orders always populated there)."""
    named = [(f.name, np.asarray(getattr(out, f.name))) for f in fields(out)]
    if span_phases:
        named.append((_SPANS_KEY, _msgpack_array(span_phases)))
    if fleet:
        named.append((_FLEET_KEY, _msgpack_array(fleet)))
    return _encode_arrays(named)


def decode_decision(data: bytes):
    """Decode to a namespace with the DecisionArrays field names as numpy arrays."""
    out, _phases = decode_decision_traced(data)
    return out


def decode_decision_traced(data: bytes):
    """:func:`decode_decision` plus the server's span phases (None when the
    peer sent none / predates tracing)."""
    out, phases, _fleet = decode_decision_full(data)
    return out, phases


def encode_migration(op: str, tenant: Optional[str] = None,
                     blob: bytes = b"", **extra: Any) -> bytes:
    """Encode one migration message (request or response — both are the
    same frame shape; see ``_MIGRATE_KEY``). ``blob`` is an opaque
    tenant-row snapshot in the ``ops.snapshot`` byte format; validation
    belongs to that format's reader, not the codec."""
    doc: Dict[str, Any] = {"op": str(op), **extra}
    if tenant is not None:
        doc["tenant"] = str(tenant)
    named: List[Tuple[str, np.ndarray]] = [
        (_MIGRATE_KEY, _msgpack_array(doc)),
        ("snap", np.frombuffer(blob, np.uint8)),
    ]
    return _encode_arrays(named)


def decode_migration(data: bytes) -> Tuple[Dict[str, Any], bytes]:
    """Decode a migration frame to ``(doc, blob)`` where ``doc`` is the
    ``__migrate__`` msgpack dict. A missing or torn sidecar is a hard
    named error: a frame on the migration RPCs that does not declare its
    op must never be guessed at."""
    arrays = _decode_arrays(data)
    raw = arrays.get(_MIGRATE_KEY)
    if raw is None:
        raise ValueError(
            "frame carries no __migrate__ sidecar (not a migration message)")
    try:
        doc = msgpack.unpackb(raw.tobytes())
        assert isinstance(doc, dict) and "op" in doc
    except Exception as e:  # noqa: BLE001 - torn migration header is fatal
        raise ValueError("frame carries a torn __migrate__ sidecar") from e
    snap = arrays.get("snap")
    return doc, (b"" if snap is None else snap.tobytes())


def decode_decision_full(data: bytes):
    """:func:`decode_decision_traced` plus the fleet response sidecar (None
    from a single-cluster peer / path)."""
    from escalator_tpu.ops.kernel import DecisionArrays

    arrays = _decode_arrays(data)
    phases = _unpack_sidecar(arrays, _SPANS_KEY)
    fleet = _unpack_sidecar(arrays, _FLEET_KEY)
    return DecisionArrays(**{
        f.name: arrays[f.name] for f in fields(DecisionArrays)
    }), phases, fleet
