"""Compute-plugin client + the controller-facing remote backend with CPU fallback.

``GrpcBackend`` implements ``ComputeBackend``: it packs object state to arrays,
ships one columnar frame to the plugin service, and unpacks the decision frame. When
the service is unreachable (or a call fails), it falls back to a local backend —
the north-star requirement ("controller calls the TPU solver over a local gRPC shim
and falls back to the existing CPU path when no device is present")."""

from __future__ import annotations

import logging
from typing import Optional

import grpc
import msgpack

from escalator_tpu import observability as obs
from escalator_tpu.controller.backend import (
    ComputeBackend,
    GoldenBackend,
    PackingPostPass,
    PaddedPacker,
    _decision_digest,
    _unpack,
)
from escalator_tpu.plugin import codec
from escalator_tpu.plugin.server import SERVICE_NAME

log = logging.getLogger("escalator_tpu.plugin")


class ComputeClient:
    """Thin RPC wrapper. bytes in / bytes out, codec at the edges."""

    def __init__(self, address: str = "127.0.0.1:50551",
                 timeout_sec: float = 10.0):
        self.address = address
        self.timeout_sec = timeout_sec
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )
        self._decide = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Decide",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Health",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._dump = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Dump",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )

    def health(self) -> dict:
        return msgpack.unpackb(self._health(b"", timeout=self.timeout_sec))

    def dump(self) -> dict:
        """The server's flight-recorder ring (the debug-dump CLI's source)."""
        import json

        return json.loads(self._dump(b"", timeout=self.timeout_sec))

    def decide_arrays(self, cluster, now_sec: int):
        out, _phases = self.decide_arrays_traced(cluster, now_sec)
        return out

    def decide_arrays_traced(self, cluster, now_sec: int,
                             span_ctx: Optional[dict] = None):
        """:meth:`decide_arrays` with span propagation: sends the caller's
        span context in the cluster frame and returns
        ``(decision, server_phases)`` — the server's timeline in
        ``Phase.as_dict`` form (None from a pre-tracing peer)."""
        frame = codec.encode_cluster(cluster, now_sec, span_ctx=span_ctx)
        resp = self._decide(frame, timeout=self.timeout_sec)
        return codec.decode_decision_traced(resp)

    def close(self) -> None:
        self._channel.close()


class GrpcBackend(ComputeBackend):
    """ComputeBackend over the plugin service, with automatic local fallback."""

    name = "grpc"

    def __init__(self, address: str = "127.0.0.1:50551",
                 fallback: Optional[ComputeBackend] = None,
                 timeout_sec: float = 10.0):
        self.client = ComputeClient(address, timeout_sec)
        self.fallback = fallback or GoldenBackend()
        self._packer = PaddedPacker()
        self._packing = PackingPostPass()

    def decide(self, group_inputs, now_sec, dry_mode_flags=None,
               taint_trackers=None):
        with obs.span(self.name):
            obs.annotate(backend=self.name, impl="remote")
            with obs.span("pack"):
                cluster = self._packer.pack(
                    group_inputs, dry_mode_flags, taint_trackers)
            try:
                with obs.span("rpc", kind="rpc"):
                    out, server_phases = self.client.decide_arrays_traced(
                        cluster, now_sec,
                        span_ctx={"path": obs.current_path()})
                if server_phases:
                    # nest the plugin-side phases under this tick's rpc span:
                    # the flight record then reads e.g.
                    # grpc/rpc/plugin_decide/decide across the process boundary
                    obs.graft(server_phases, under=obs.current_path() + "/rpc")
            except grpc.RpcError as e:
                log.warning(
                    "compute plugin unavailable (%s); falling back to %s"
                    " backend",
                    e.code() if hasattr(e, "code") else e, self.fallback.name,
                )
                results = self.fallback.decide(
                    group_inputs, now_sec, dry_mode_flags, taint_trackers
                )
                # AFTER the fallback ran: its own span re-annotated
                # backend=<fallback.name>, which would file this tick's
                # record (and phase series) under the wrong backend — the
                # operator greps the 'grpc' label for exactly these degraded
                # ticks. Re-assert the configured identity + the fallback tag.
                obs.annotate(backend=self.name, fallback=self.fallback.name)
                return results
            obs.annotate(digest=_decision_digest(out))
            with obs.span("unpack"):
                results = _unpack(out, group_inputs)
            # packing-aware override runs client-side: it needs only the object
            # inputs already in hand, keeping the wire format untouched. On a
            # jax-less client it degrades to the pure-Python FFD (same math);
            # packing_aware groups therefore do NOT offload this step to the
            # plugin — a deliberate trade against a wire-format revision.
            with obs.span("packing_post"):
                self._packing.apply(
                    results, group_inputs, dry_mode_flags, taint_trackers)
            return results
