"""Compute-plugin client + the controller-facing remote backend with CPU fallback.

``GrpcBackend`` implements ``ComputeBackend``: it packs object state to arrays,
ships one columnar frame to the plugin service, and unpacks the decision frame. When
the service is unreachable (or a call fails), it falls back to a local backend —
the north-star requirement ("controller calls the TPU solver over a local gRPC shim
and falls back to the existing CPU path when no device is present")."""

from __future__ import annotations

import logging
from typing import Optional

import grpc
import msgpack

from escalator_tpu.controller.backend import (
    ComputeBackend,
    GoldenBackend,
    PackingPostPass,
    PaddedPacker,
    _unpack,
)
from escalator_tpu.plugin import codec
from escalator_tpu.plugin.server import SERVICE_NAME

log = logging.getLogger("escalator_tpu.plugin")


class ComputeClient:
    """Thin RPC wrapper. bytes in / bytes out, codec at the edges."""

    def __init__(self, address: str = "127.0.0.1:50551",
                 timeout_sec: float = 10.0):
        self.address = address
        self.timeout_sec = timeout_sec
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )
        self._decide = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Decide",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Health",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )

    def health(self) -> dict:
        return msgpack.unpackb(self._health(b"", timeout=self.timeout_sec))

    def decide_arrays(self, cluster, now_sec: int):
        frame = codec.encode_cluster(cluster, now_sec)
        resp = self._decide(frame, timeout=self.timeout_sec)
        return codec.decode_decision(resp)

    def close(self) -> None:
        self._channel.close()


class GrpcBackend(ComputeBackend):
    """ComputeBackend over the plugin service, with automatic local fallback."""

    name = "grpc"

    def __init__(self, address: str = "127.0.0.1:50551",
                 fallback: Optional[ComputeBackend] = None,
                 timeout_sec: float = 10.0):
        self.client = ComputeClient(address, timeout_sec)
        self.fallback = fallback or GoldenBackend()
        self._packer = PaddedPacker()
        self._packing = PackingPostPass()

    def decide(self, group_inputs, now_sec, dry_mode_flags=None,
               taint_trackers=None):
        cluster = self._packer.pack(group_inputs, dry_mode_flags, taint_trackers)
        try:
            out = self.client.decide_arrays(cluster, now_sec)
        except grpc.RpcError as e:
            log.warning(
                "compute plugin unavailable (%s); falling back to %s backend",
                e.code() if hasattr(e, "code") else e, self.fallback.name,
            )
            return self.fallback.decide(
                group_inputs, now_sec, dry_mode_flags, taint_trackers
            )
        results = _unpack(out, group_inputs)
        # packing-aware override runs client-side: it needs only the object
        # inputs already in hand, keeping the wire format untouched. On a
        # jax-less client it degrades to the pure-Python FFD (same math);
        # packing_aware groups therefore do NOT offload this step to the
        # plugin — a deliberate trade against a wire-format revision.
        self._packing.apply(results, group_inputs, dry_mode_flags, taint_trackers)
        return results
