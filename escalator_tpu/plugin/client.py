"""Compute-plugin client + the controller-facing remote backend with CPU fallback.

``GrpcBackend`` implements ``ComputeBackend``: it packs object state to arrays,
ships one columnar frame to the plugin service, and unpacks the decision frame. When
the service is unreachable (or a call fails), it falls back to a local backend —
the north-star requirement ("controller calls the TPU solver over a local gRPC shim
and falls back to the existing CPU path when no device is present").

Round 11 hardened the degradation ladder (previously: one flat 10 s timeout
and an immediate per-call fallback on any ``grpc.RpcError``):

1. **Bounded retries** (:class:`RetryPolicy`): each decide gets up to
   ``max_attempts`` RPC tries with a per-attempt deadline and exponential
   backoff + jitter between them, all under one total budget — a transient
   server restart no longer costs a whole degraded tick, and a herd of
   controllers retrying a recovering plugin doesn't resynchronize into it.
2. **Fallback with attribution**: only after retries exhaust does the local
   fallback run, counted per status code in
   ``escalator_tpu_plugin_fallback_total{code}`` (the alertable signal the
   silent log line lacked).
3. **Circuit breaker**: ``breaker_threshold`` consecutive decide failures
   pin the backend to the fallback — no RPC attempt, no retry latency on
   every tick of an extended outage — until a probe tick
   (every ``breaker_probe_after`` ticks) finds the plugin answering again
   and closes the circuit. Probes use a single attempt so a still-dead
   plugin costs one deadline, not a full retry ladder.
"""

from __future__ import annotations

import logging
import random
import time
from dataclasses import dataclass
from typing import Optional

import grpc
import msgpack

from escalator_tpu import observability as obs
from escalator_tpu.chaos import CHAOS
from escalator_tpu.controller.backend import (
    ComputeBackend,
    GoldenBackend,
    PackingPostPass,
    PaddedPacker,
    _annotate_decision,
    _unpack,
)
from escalator_tpu.metrics import metrics
from escalator_tpu.plugin import codec
from escalator_tpu.plugin.server import SERVICE_NAME

log = logging.getLogger("escalator_tpu.plugin")

#: status codes worth retrying: the server may be restarting (UNAVAILABLE),
#: momentarily slow (DEADLINE_EXCEEDED), or shedding load (RESOURCE_EXHAUSTED).
#: Anything else — a codec error, an application failure — would fail the
#: same way again, so it goes straight to the fallback.
RETRYABLE_CODES = (
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
)


@dataclass
class RetryPolicy:
    """Per-decide RPC retry envelope. A worst-case decide is bounded at
    roughly ``total_deadline_sec`` — comfortably inside a scan interval —
    while a transient blip costs one backoff step (~50 ms). The default
    per-attempt deadline equals the total budget, so a SLOW server (cold
    jit compile on its first decide) behaves exactly like the pre-round-11
    flat timeout — one attempt, then fallback — and the ladder engages on
    fast failures (UNAVAILABLE during a restart). Deployments that prefer
    retrying timeouts too set ``rpc_timeout_sec`` below the total."""

    max_attempts: int = 3
    rpc_timeout_sec: float = 10.0       # per-attempt deadline
    total_deadline_sec: float = 10.0    # whole-decide budget incl. backoffs
    base_backoff_sec: float = 0.05
    backoff_multiplier: float = 2.0
    max_backoff_sec: float = 1.0
    jitter_frac: float = 0.5            # uniform [0, frac] added per sleep


class _InjectedRpcError(grpc.RpcError):
    """Chaos-injected RPC failure: carries a code like the real thing so the
    retry/breaker ladder treats it identically."""

    def __init__(self, code: grpc.StatusCode):
        super().__init__(f"chaos-injected {code.name}")
        self._code = code

    def code(self) -> grpc.StatusCode:
        return self._code


def _rpc_code(err) -> "grpc.StatusCode | None":
    code = getattr(err, "code", None)
    if callable(code):
        try:
            return code()
        except Exception:  # noqa: BLE001 - a broken stub error has no code
            return None
    return None


def _rpc_retry_after_sec(err) -> "float | None":
    """The server's backoff hint from the ``escalator-retry-after-ms``
    trailer (fleet backpressure ships it with RESOURCE_EXHAUSTED). None
    when absent/unreadable — the client's own backoff stands."""
    get_md = getattr(err, "trailing_metadata", None)
    if not callable(get_md):
        return None
    try:
        for key, value in (get_md() or ()):
            if key == "escalator-retry-after-ms":
                return max(0.0, float(value)) / 1e3
    except Exception:  # noqa: BLE001 - a torn trailer must not mask the error
        return None
    return None


def _chaos_rpc_attempt() -> None:
    """The plugin_rpc chaos site: raise a synthetic retryable error before
    the real RPC goes out (``code=`` rule param picks the status)."""
    if CHAOS.should_fire("plugin_rpc"):
        name = CHAOS.params("plugin_rpc").get("code", "unavailable").upper()
        raise _InjectedRpcError(getattr(grpc.StatusCode, name,
                                        grpc.StatusCode.UNAVAILABLE))


class ComputeClient:
    """Thin RPC wrapper. bytes in / bytes out, codec at the edges."""

    def __init__(self, address: str = "127.0.0.1:50551",
                 timeout_sec: float = 10.0,
                 retry: Optional[RetryPolicy] = None):
        self.address = address
        self.timeout_sec = timeout_sec
        self.retry = retry or RetryPolicy(rpc_timeout_sec=timeout_sec,
                                          total_deadline_sec=timeout_sec)
        self._channel = grpc.insecure_channel(
            address,
            options=[
                ("grpc.max_receive_message_length", -1),
                ("grpc.max_send_message_length", -1),
            ],
        )
        self._decide = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Decide",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._health = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Health",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._dump = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Dump",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._profile = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Profile",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._journal = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Journal",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._explain = self._channel.unary_unary(
            f"/{SERVICE_NAME}/Explain",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._tenant_snapshot = self._channel.unary_unary(
            f"/{SERVICE_NAME}/TenantSnapshot",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )
        self._tenant_adopt = self._channel.unary_unary(
            f"/{SERVICE_NAME}/TenantAdopt",
            request_serializer=lambda x: x,
            response_deserializer=lambda x: x,
        )

    def health(self) -> dict:
        return msgpack.unpackb(self._health(b"", timeout=self.timeout_sec))

    def dump(self) -> dict:
        """The server's flight-recorder ring (the debug-dump CLI's source)."""
        import json

        return json.loads(self._dump(b"", timeout=self.timeout_sec))

    def journal(self, since_seq: int = 0) -> dict:
        """The server's ops event journal (the debug-journal CLI's live
        source): ``{capacity, total_recorded, events: [...]}``, events
        newer than ``since_seq`` (all by default). Raises grpc.RpcError
        (UNIMPLEMENTED from a pre-round-17 server) on transport failure."""
        req = msgpack.packb({"since": int(since_seq)}) if since_seq else b""
        return msgpack.unpackb(self._journal(req, timeout=self.timeout_sec))

    def explain(self, tenant: Optional[str] = None,
                groups: Optional[list] = None) -> dict:
        """The server's decision-provenance surface (the debug-explain
        CLI's live source). Without a tenant: discovery —
        ``{keys: [...], health: {...}}``. With one: ``{key, explanations:
        [per-group docs], history: [...], flaps: [...]}`` re-derived live
        from the server's resident arenas. Raises grpc.RpcError
        (UNIMPLEMENTED from a pre-round-19 server, NOT_FOUND for a key no
        explainer or history covers) on transport failure."""
        req = b""
        if tenant is not None or groups is not None:
            body: dict = {}
            if tenant is not None:
                body["tenant"] = str(tenant)
            if groups is not None:
                body["groups"] = [int(g) for g in groups]
            req = msgpack.packb(body)
        return msgpack.unpackb(self._explain(req, timeout=self.timeout_sec))

    def profile(self, ticks: int = 4, timeout_sec: float = 60.0) -> dict:
        """Capture a jax profiler trace of the server's next ``ticks``
        decides (the debug-profile CLI's source). Returns the server's
        msgpack response: ``{"ok": True, "files": {relpath: bytes}, ...}``
        on success, ``{"ok": False, "unsupported"/"busy": ...}`` where the
        capture cannot run. The RPC deadline covers the capture window
        PLUS a generous serialization margin — ``stop_trace`` writes the
        whole XPlane artifact before the server can answer, and that write
        was measured taking tens of seconds in a long-lived process (a
        deadline of window+rpc_timeout reliably DEADLINE_EXCEEDED exactly
        when the capture had worked). Raises grpc.RpcError (e.g.
        UNIMPLEMENTED from a pre-round-15 server) on transport failure."""
        from escalator_tpu.observability.resources import ProfileCapture

        req = msgpack.packb({"ticks": int(ticks),
                             "timeout_sec": float(timeout_sec)})
        # the server may legitimately take window + its full stop bound
        # before answering — the deadline must cover BOTH or the RPC dies
        # exactly when the capture worked
        margin = ProfileCapture.STOP_TIMEOUT_SEC + self.timeout_sec
        return msgpack.unpackb(
            self._profile(req, timeout=timeout_sec + margin))

    def _decide_with_retry(self, frame: bytes,
                           max_attempts: Optional[int] = None) -> bytes:
        """One decide's RPC ladder: per-attempt deadlines, exponential
        backoff + jitter between retryable failures, all bounded by the
        policy's total budget. Raises the LAST error when the ladder
        exhausts — the caller's fallback owns what happens next."""
        policy = self.retry
        attempts = max_attempts if max_attempts is not None else policy.max_attempts
        deadline = time.monotonic() + policy.total_deadline_sec
        backoff = policy.base_backoff_sec
        last_err: Optional[grpc.RpcError] = None
        for attempt in range(attempts):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                _chaos_rpc_attempt()
                return self._decide(
                    frame, timeout=min(policy.rpc_timeout_sec, remaining))
            except grpc.RpcError as e:
                last_err = e
                code = _rpc_code(e)
                if code not in RETRYABLE_CODES or attempt + 1 >= attempts:
                    raise
                budget_left = deadline - time.monotonic()
                if budget_left <= 0:
                    # no retry will actually run (the guaranteed case when a
                    # single attempt consumed the whole budget, e.g. a
                    # DEADLINE_EXCEEDED under the default per-attempt ==
                    # total policy): don't count a phantom retry
                    raise
                metrics.plugin_rpc_retries.inc()
                sleep = backoff * (1.0 + random.uniform(0, policy.jitter_frac))
                retry_after = _rpc_retry_after_sec(e)
                if retry_after is not None:
                    # the server told us when it expects capacity (fleet
                    # backpressure): retrying sooner just re-rejects
                    sleep = max(sleep, retry_after)
                sleep = min(sleep, budget_left)
                log.warning(
                    "plugin decide attempt %d/%d failed (%s); retrying in "
                    "%.0f ms", attempt + 1, attempts,
                    code.name if code else e, sleep * 1e3)
                if sleep > 0:
                    time.sleep(sleep)
                backoff = min(backoff * policy.backoff_multiplier,
                              policy.max_backoff_sec)
        # total budget exhausted between attempts
        if last_err is not None:
            raise last_err
        raise _InjectedRpcError(grpc.StatusCode.DEADLINE_EXCEEDED)

    def decide_arrays(self, cluster, now_sec: int):
        out, _phases = self.decide_arrays_traced(cluster, now_sec)
        return out

    def decide_arrays_traced(self, cluster, now_sec: int,
                             span_ctx: Optional[dict] = None,
                             max_attempts: Optional[int] = None):
        """:meth:`decide_arrays` with span propagation: sends the caller's
        span context in the cluster frame and returns
        ``(decision, server_phases)`` — the server's timeline in
        ``Phase.as_dict`` form (None from a pre-tracing peer)."""
        frame = codec.encode_cluster(cluster, now_sec, span_ctx=span_ctx)
        resp = self._decide_with_retry(frame, max_attempts=max_attempts)
        return codec.decode_decision_traced(resp)

    def decide_arrays_fleet(self, cluster, now_sec: int, tenant_id: str,
                            span_ctx: Optional[dict] = None,
                            max_attempts: Optional[int] = None,
                            klass: Optional[str] = None):
        """Fleet-mode decide: tags the frame with the tenant sidecar and
        returns ``(decision, server_phases, fleet_meta)``. ``fleet_meta``
        is the server's ``__fleet__`` sidecar (``ordered`` — the lazy-
        orders flag the caller MUST honor before reading order windows —
        plus ``batch_size`` and ``shard``), or None from a server without
        fleet mode (which served the single-cluster decide: orders
        populated, treat as ordered=True). ``klass`` picks the admission
        priority class (server default when None; an unknown name is
        INVALID_ARGUMENT)."""
        tenant: dict = {"id": tenant_id}
        if klass is not None:
            tenant["class"] = klass
        frame = codec.encode_cluster(cluster, now_sec, span_ctx=span_ctx,
                                     tenant=tenant)
        resp = self._decide_with_retry(frame, max_attempts=max_attempts)
        return codec.decode_decision_full(resp)

    def snapshot_tenant(self, tenant_id: str,
                        timeout_sec: Optional[float] = None) -> bytes:
        """Freeze ``tenant_id``'s arena row on a fleet-mode server into
        portable snapshot bytes (round 20 warm migration: the blob feeds
        :meth:`adopt_tenant` on the target partition, or a checkpoint
        file — same container format). The server quiesces the tenant and
        freezes at a batch boundary; ``timeout_sec`` bounds that quiesce
        (the RPC deadline adds the client's own timeout on top). Raises
        grpc.RpcError: NOT_FOUND for an unknown tenant,
        FAILED_PRECONDITION from a non-fleet server, UNIMPLEMENTED from a
        pre-round-20 one."""
        t = float(timeout_sec if timeout_sec is not None
                  else self.timeout_sec)
        req = codec.encode_migration("snapshot", tenant_id, timeout_sec=t)
        resp = self._tenant_snapshot(req, timeout=t + self.timeout_sec)
        _doc, blob = codec.decode_migration(resp)
        return bytes(blob)

    def adopt_tenant(self, blob: bytes) -> dict:
        """Adopt a tenant-row snapshot blob (from :meth:`snapshot_tenant`
        or a checkpoint file) as a resident tenant on this server. Returns
        the ack doc ``{op: "ack", tenant, shard, row}``. Raises
        grpc.RpcError: INVALID_ARGUMENT for a corrupt blob,
        FAILED_PRECONDITION when the arena cannot hold it (bucket caps,
        already-resident id) — fall back to a cold full frame, never to a
        wrong adopt."""
        req = codec.encode_migration("adopt", blob=blob)
        resp = self._tenant_adopt(req, timeout=self.timeout_sec)
        doc, _blob = codec.decode_migration(resp)
        return doc

    def evict_tenant(self, tenant_id: str) -> dict:
        """Deregister ``tenant_id`` on a fleet-mode server. Returns the
        ack sidecar; raises grpc.RpcError (INVALID_ARGUMENT) when the
        tenant is unknown."""
        from escalator_tpu.core.arrays import pack_cluster

        frame = codec.encode_cluster(
            pack_cluster([]), 0, tenant={"id": tenant_id, "evict": True})
        _out, _phases, fleet = codec.decode_decision_full(
            self._decide(frame, timeout=self.timeout_sec))
        return fleet or {}

    def close(self) -> None:
        self._channel.close()


class FleetStreamSession:
    """Client-side streaming ingestion for ONE fleet tenant (round 18).

    Holds a state-store twin (``native.statestore.make_state_store`` — the
    same store the event-driven backend ingests watches into) of the
    tenant's cluster; callers apply their watch events to ``.store``
    (``upsert_pod`` / ``delete_node`` / batch variants) and call
    :meth:`decide`. The first decide — and any decide after the store grew
    (``generation`` changed) or an RPC failed — ships a FULL cluster frame
    (registering/resyncing the tenant server-side, byte-identical to the
    non-streaming path); every other decide ships only the packed dirty
    drain as a delta frame (``codec.encode_delta``), so the wire and the
    server's host work are O(churn) instead of O(arena). Group options ride
    along only when :meth:`set_groups` marked them dirty.

    NOT thread-safe (one session = one tenant's synchronous decide loop,
    exactly like a controller tick). Against an OLD server a delta frame
    fails loudly with the codec's named missing-array error — resync then
    pins the session to full frames one failure at a time, so a
    mixed-version fleet degrades to the diff path instead of wrong answers.
    """

    def __init__(self, client: ComputeClient, tenant_id: str,
                 pod_capacity: int = 1 << 12, node_capacity: int = 1 << 10,
                 store_kind: str = "auto", klass: Optional[str] = None):
        from escalator_tpu.native.statestore import make_state_store

        self.client = client
        self.tenant_id = tenant_id
        self.klass = klass
        self.store = make_state_store(
            pod_capacity=pod_capacity, node_capacity=node_capacity,
            kind=store_kind)
        self._groups = None
        self._groups_dirty = True
        #: store generation the server last saw a FULL frame for; None
        #: forces a full frame (first contact, post-error resync)
        self._synced_generation: "int | None" = None
        #: full frames / delta frames sent (bench + test surface)
        self.full_frames = 0
        self.delta_frames = 0

    def set_groups(self, groups) -> None:
        """(Re)load the tenant's group options (a ``GroupArrays``). The next
        decide ships them — as part of the full frame, or as the delta
        frame's optional ``g.`` section (which invalidates the server's
        digest cache: a group reload MUST miss, test-locked)."""
        self._groups = groups
        self._groups_dirty = True

    def _trim(self, idx, vals, capacity: int):
        """Drop the drain's pad lanes (pad idx == capacity) before encode:
        the wire carries only real entries, and the server validates every
        slot against the tenant's logical widths."""
        from dataclasses import fields as dfields

        keep = idx < capacity
        if keep.all():
            return idx, vals
        return idx[keep], type(vals)(**{
            f.name: getattr(vals, f.name)[keep] for f in dfields(vals)})

    def decide(self, now_sec: int,
               span_ctx: Optional[dict] = None,
               max_attempts: Optional[int] = None):
        """One streamed decide: ``(decision, server_phases, fleet_meta)``,
        exactly :meth:`ComputeClient.decide_arrays_fleet`'s contract. Any
        transport/application error marks the session for a full-frame
        resync (the server may have rolled the delta back, or never seen
        it) and re-raises."""
        from escalator_tpu.core.arrays import ClusterArrays

        if self._groups is None:
            raise ValueError(
                "FleetStreamSession.set_groups must run before decide "
                "(the tenant frame needs a group-options section)")
        tenant: dict = {"id": self.tenant_id}
        if self.klass is not None:
            tenant["class"] = self.klass
        pods, nodes = self.store.as_pod_node_arrays()
        shapes = (len(self._groups.valid), self.store.pod_capacity,
                  self.store.node_capacity)
        try:
            if self._synced_generation != self.store.generation:
                # first contact, growth, or resync: the full frame both
                # (re)registers the tenant and rebases the server twin;
                # drain the dirty sets so the next delta is post-full only
                frame = codec.encode_cluster(
                    ClusterArrays(groups=self._groups, pods=pods,
                                  nodes=nodes),
                    now_sec, span_ctx=span_ctx, tenant=tenant)
                self.store.drain_dirty()
                self.full_frames += 1
            else:
                pidx, pvals, nidx, nvals = self.store.drain_dirty_packed()
                pidx, pvals = self._trim(pidx, pvals, self.store.pod_capacity)
                nidx, nvals = self._trim(nidx, nvals, self.store.node_capacity)
                frame = codec.encode_delta(
                    now_sec, shapes, pidx, pvals, nidx, nvals,
                    groups=self._groups if self._groups_dirty else None,
                    span_ctx=span_ctx, tenant=tenant)
                self.delta_frames += 1
            resp = self.client._decide_with_retry(
                frame, max_attempts=max_attempts)
        except Exception:
            self._synced_generation = None
            self._groups_dirty = True
            raise
        self._synced_generation = self.store.generation
        self._groups_dirty = False
        return codec.decode_decision_full(resp)

    def evict(self) -> dict:
        """Deregister the tenant server-side; the session then needs a full
        frame again (and the server's digest cache for a recycled id starts
        empty — an evict→re-register MUST miss, test-locked)."""
        ack = self.client.evict_tenant(self.tenant_id)
        self._synced_generation = None
        self._groups_dirty = True
        return ack

    def rebind(self, client: ComputeClient, resync: bool = False) -> None:
        """Point the session at a DIFFERENT partition's client (round 20).

        After a warm migration the target's twin is the source's frozen
        row — the snapshot of the tenant's last committed tick — so the
        delta path simply continues: the first post-rebind decide folds
        everything dirtied since into one delta batch, exactly the PR-6
        killed-leader warm start. ``resync=True`` is for FAILOVER, where
        the new home adopted from a rolling checkpoint that may predate
        the last served tick: it forces a full frame, rebasing the server
        twin from the client's live store instead of trusting a possibly
        stale one."""
        self.client = client
        if resync:
            self._synced_generation = None
            self._groups_dirty = True


class GrpcBackend(ComputeBackend):
    """ComputeBackend over the plugin service, with automatic local fallback
    behind the retry ladder and a consecutive-failure circuit breaker."""

    name = "grpc"

    def __init__(self, address: str = "127.0.0.1:50551",
                 fallback: Optional[ComputeBackend] = None,
                 timeout_sec: float = 10.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker_threshold: int = 3,
                 breaker_probe_after: int = 5,
                 tenant_id: Optional[str] = None,
                 tenant_class: Optional[str] = None):
        self.client = ComputeClient(address, timeout_sec, retry=retry)
        self.fallback = fallback or GoldenBackend()
        self._packer = PaddedPacker()
        self._packing = PackingPostPass()
        #: fleet mode (round 14): tag every decide with this tenant id so a
        #: fleet-enabled plugin coalesces it with other tenants' ticks; a
        #: server without fleet mode ignores the tag (single-cluster path)
        self.tenant_id = tenant_id
        #: admission priority class for the fleet scheduler (round 16);
        #: None rides the server's default class
        self.tenant_class = tenant_class
        #: consecutive decide failures (post-retry) that open the breaker
        self.breaker_threshold = int(breaker_threshold)
        #: fallback-served ticks between recovery probes while open
        self.breaker_probe_after = int(breaker_probe_after)
        self._consecutive_failures = 0
        self._breaker_open = False
        self._ticks_since_open = 0

    @property
    def breaker_open(self) -> bool:
        return self._breaker_open

    def _serve_fallback(self, group_inputs, now_sec, dry_mode_flags,
                        taint_trackers, code: str):
        metrics.plugin_fallback.labels(code).inc()
        results = self.fallback.decide(
            group_inputs, now_sec, dry_mode_flags, taint_trackers
        )
        # AFTER the fallback ran: its own span re-annotated
        # backend=<fallback.name>, which would file this tick's record (and
        # phase series) under the wrong backend — the operator greps the
        # 'grpc' label for exactly these degraded ticks. Re-assert the
        # configured identity + the fallback tag.
        obs.annotate(backend=self.name, fallback=self.fallback.name,
                     fallback_code=code)
        return results

    def decide(self, group_inputs, now_sec, dry_mode_flags=None,
               taint_trackers=None):
        with obs.span(self.name):
            obs.annotate(backend=self.name, impl="remote")
            probing = False
            if self._breaker_open:
                self._ticks_since_open += 1
                if self._ticks_since_open < self.breaker_probe_after:
                    # pinned to the fallback: an extended outage must not
                    # pay the retry ladder's latency on every single tick
                    return self._serve_fallback(
                        group_inputs, now_sec, dry_mode_flags,
                        taint_trackers, code="circuit-open")
                probing = True
            with obs.span("pack"):
                cluster = self._packer.pack(
                    group_inputs, dry_mode_flags, taint_trackers)
            fleet_meta = None
            try:
                with obs.span("rpc", kind="rpc"):
                    if self.tenant_id is not None:
                        out, server_phases, fleet_meta = (
                            self.client.decide_arrays_fleet(
                                cluster, now_sec, self.tenant_id,
                                span_ctx={"path": obs.current_path()},
                                max_attempts=1 if probing else None,
                                klass=self.tenant_class))
                    else:
                        out, server_phases = self.client.decide_arrays_traced(
                            cluster, now_sec,
                            span_ctx={"path": obs.current_path()},
                            # a probe pays one deadline, never the full
                            # ladder: a still-dead plugin must not stall
                            # the probe tick
                            max_attempts=1 if probing else None)
                if server_phases:
                    # nest the plugin-side phases under this tick's rpc span:
                    # the flight record then reads e.g.
                    # grpc/rpc/plugin_decide/decide across the process boundary
                    obs.graft(server_phases, under=obs.current_path() + "/rpc")
            except grpc.RpcError as e:
                code = _rpc_code(e)
                code_name = code.name if code else "UNKNOWN"
                self._consecutive_failures += 1
                if probing:
                    # probe failed: stay open, restart the probe countdown
                    self._ticks_since_open = 0
                    log.warning(
                        "compute plugin still down at recovery probe (%s); "
                        "circuit stays open", code_name)
                elif (not self._breaker_open
                        and self._consecutive_failures >= self.breaker_threshold):
                    self._breaker_open = True
                    self._ticks_since_open = 0
                    log.error(
                        "compute plugin failed %d consecutive decides; "
                        "opening circuit — serving from %s backend, probing "
                        "every %d ticks", self._consecutive_failures,
                        self.fallback.name, self.breaker_probe_after)
                else:
                    log.warning(
                        "compute plugin unavailable (%s); falling back to %s"
                        " backend", code_name, self.fallback.name,
                    )
                return self._serve_fallback(
                    group_inputs, now_sec, dry_mode_flags, taint_trackers,
                    code=code_name)
            if self._breaker_open:
                log.warning("compute plugin answered the recovery probe; "
                            "closing circuit")
            self._breaker_open = False
            self._ticks_since_open = 0
            self._consecutive_failures = 0
            _annotate_decision(self.name, out)
            if fleet_meta is not None:
                obs.annotate(fleet_batch_size=fleet_meta.get("batch_size"),
                             fleet_ordered=fleet_meta.get("ordered"))
            with obs.span("unpack"):
                # fleet responses carry the lazy-orders flag: ordered=False
                # means the order fields are placeholders and candidate
                # lists populate as unordered membership from the packed
                # node masks (exactly the array backends' protocol); a
                # single-cluster response (no sidecar) always has orders
                ordered = (True if fleet_meta is None
                           else bool(fleet_meta.get("ordered", True)))
                results = _unpack(out, group_inputs, ordered=ordered,
                                  node_masks=cluster.nodes)
            # packing-aware override runs client-side: it needs only the object
            # inputs already in hand, keeping the wire format untouched. On a
            # jax-less client it degrades to the pure-Python FFD (same math);
            # packing_aware groups therefore do NOT offload this step to the
            # plugin — a deliberate trade against a wire-format revision.
            with obs.span("packing_post"):
                self._packing.apply(
                    results, group_inputs, dry_mode_flags, taint_trackers)
            return results
