"""Kubernetes API client abstraction.

The reference talks to the API server through client-go
(/root/reference/pkg/k8s/client.go:12-40). Here the controller depends only on the
small ``KubernetesClient`` protocol below; implementations:

- ``InMemoryKubernetesClient`` — thread-safe in-process cluster state. The framework's
  equivalent of the reference's fake clientset with reactors
  (pkg/test/builder.go:29-101), and the backing store for dry-run simulation.
- a real apiserver-backed client can be plugged in by implementing the same protocol
  (the ``kubernetes`` Python package is not vendored here; see ``load_incluster`` for
  the gated import).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Protocol

from escalator_tpu.k8s import types as k8s


class KubernetesClient(Protocol):
    def list_pods(self) -> List[k8s.Pod]:
        ...

    def list_nodes(self) -> List[k8s.Node]:
        ...

    def get_node(self, name: str) -> Optional[k8s.Node]:
        ...

    def update_node(self, node: k8s.Node) -> k8s.Node:
        ...

    def delete_node(self, name: str) -> None:
        ...

    def create_event(self, event: k8s.Event) -> None:
        """Broadcast an Event (reference analog: the election broadcaster's
        recorder, cmd/main.go:166-170). Best-effort: implementations must not
        raise into the control loop."""
        ...


class InMemoryKubernetesClient:
    """In-process cluster store. Update/delete observers let tests assert on write
    traffic the way the reference's reactor channels do (pkg/test/builder.go:44-76)."""

    def __init__(self, nodes: Optional[List[k8s.Node]] = None,
                 pods: Optional[List[k8s.Pod]] = None):
        self._lock = threading.RLock()
        self._nodes: Dict[str, k8s.Node] = {}
        self._pods: Dict[str, k8s.Pod] = {}
        self.on_node_update: List[Callable[[k8s.Node], None]] = []
        self.on_node_delete: List[Callable[[str], None]] = []
        #: recorded Events, observable by tests the way the reference's fake
        #: broadcaster sink is (real adapters POST these to the apiserver)
        self.events: List[k8s.Event] = []
        for n in nodes or []:
            self._nodes[n.name] = n
        for p in pods or []:
            self._pods[self._pod_key(p)] = p

    @staticmethod
    def _pod_key(pod: k8s.Pod) -> str:
        return f"{pod.namespace}/{pod.name}"

    # -- reads ---------------------------------------------------------------
    def list_pods(self) -> List[k8s.Pod]:
        with self._lock:
            # informer semantics: Succeeded/Failed pods are excluded from the cache
            # (reference: pkg/k8s/cache.go:17)
            return [
                p for p in self._pods.values() if p.phase not in ("Succeeded", "Failed")
            ]

    def list_nodes(self) -> List[k8s.Node]:
        with self._lock:
            return list(self._nodes.values())

    def get_node(self, name: str) -> Optional[k8s.Node]:
        with self._lock:
            node = self._nodes.get(name)
            return node.copy() if node is not None else None

    # -- writes --------------------------------------------------------------
    def update_node(self, node: k8s.Node) -> k8s.Node:
        with self._lock:
            if node.name not in self._nodes:
                raise KeyError(f"node {node.name} not found")
            self._nodes[node.name] = node
        for cb in self.on_node_update:
            cb(node)
        return node

    def delete_node(self, name: str) -> None:
        with self._lock:
            if name not in self._nodes:
                raise KeyError(f"node {name} not found")
            del self._nodes[name]
        for cb in self.on_node_delete:
            cb(name)

    #: retained Events cap — long sim runs must not grow the list unboundedly
    MAX_EVENTS = 4096

    def create_event(self, event: k8s.Event) -> None:
        with self._lock:
            # compact repeats the way the apiserver's event series do: same
            # (reason, object) within the retention window bumps count. The
            # message is NOT part of the key — emitted messages embed counts
            # ("increased ... by 6"), so near-duplicates would never compact
            for e in reversed(self.events[-16:]):
                if (
                    e.reason == event.reason
                    and e.involved_kind == event.involved_kind
                    and e.involved_name == event.involved_name
                ):
                    e.count += 1
                    e.message = event.message  # keep the freshest text
                    e.timestamp_sec = event.timestamp_sec
                    return
            self.events.append(event)
            if len(self.events) > self.MAX_EVENTS:
                del self.events[: len(self.events) - self.MAX_EVENTS]

    # -- simulation helpers ---------------------------------------------------
    def add_node(self, node: k8s.Node) -> None:
        with self._lock:
            self._nodes[node.name] = node

    def add_pod(self, pod: k8s.Pod) -> None:
        with self._lock:
            self._pods[self._pod_key(pod)] = pod

    def remove_pod(self, pod: k8s.Pod) -> None:
        with self._lock:
            self._pods.pop(self._pod_key(pod), None)


def load_incluster() -> KubernetesClient:
    """Client against the cluster this process runs in: serviceaccount token +
    KUBERNETES_SERVICE_HOST, the rest.InClusterConfig flow (reference:
    pkg/k8s/client.go:28-40). Speaks the REST list+watch wire protocol directly
    (restclient.py) — no ``kubernetes`` package needed. Blocks until the
    informer caches sync, like the reference's WaitForSync gate
    (cmd/main.go:130-137)."""
    from escalator_tpu.k8s import restclient

    return restclient.connect(restclient.incluster_config())


def load_kubeconfig(path: str, context: str = "") -> KubernetesClient:
    """Out-of-cluster client from a kubeconfig file (reference:
    pkg/k8s/client.go:12-26, clientcmd.BuildConfigFromFlags)."""
    from escalator_tpu.k8s import restclient

    return restclient.connect(restclient.kubeconfig_config(path, context))
