"""Watch-event ingestion: cluster events -> native dense arrays, incrementally.

The reference's informer caches (pkg/k8s/cache.go:16-66) keep Go object stores warm
and the controller re-walks them every tick (O(cluster) per tick). Here the same
event stream feeds the native C++ state store instead, so per-tick host work is
O(changes): the kernel's pod/node columns are always current and ready for
``jax.device_put``.

Pieces:
- ``WatchEvent`` / ``EventfulClient`` — an in-memory cluster client that emits
  add/modify/delete events for pods and nodes (the sim-world analog of a k8s watch;
  a real apiserver watch adapter produces the same events).
- ``WatchBridge`` — subscribes to events, resolves each object's nodegroup via the
  configured filters (first match wins; reference groups are disjoint by label
  selector), and applies upsert/delete deltas to a ``NativeStateStore``. Maintains
  the slot<->object-name mapping the executors need to turn kernel node indices
  back into API objects.

Pods counted per group follow the reference's lister semantics exactly: the
affinity/default filters (pkg/controller/node_group.go:218-275) decide membership,
and Succeeded/Failed pods are never ingested (pkg/k8s/cache.go:17).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple


from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.client import InMemoryKubernetesClient
from escalator_tpu.native.statestore import NO_TAINT_TIME

log = logging.getLogger("escalator_tpu.k8s.cache")

ADDED = "added"
MODIFIED = "modified"
DELETED = "deleted"


@dataclass
class WatchEvent:
    kind: str  # "pod" | "node"
    type: str  # added | modified | deleted
    obj: object  # Pod or Node (for deletes: the last-known object)


class EventfulClient(InMemoryKubernetesClient):
    """InMemoryKubernetesClient that emits WatchEvents on every mutation."""

    def __init__(self, nodes=None, pods=None):
        super().__init__(nodes=nodes, pods=pods)
        self.watchers: List[Callable[[WatchEvent], None]] = []

    def _emit(self, event: WatchEvent) -> None:
        for w in self.watchers:
            w(event)

    def subscribe(self, watcher: Callable[[WatchEvent], None],
                  replay: bool = True) -> None:
        """Add a watcher; replay=True first delivers the current state as ADDED
        events (list-then-watch semantics). Runs under the client lock so no
        mutation can slip between the replay and the subscription."""
        with self._lock:
            if replay:
                for node in self.list_nodes():
                    watcher(WatchEvent("node", ADDED, node))
                for pod in self.list_pods():
                    watcher(WatchEvent("pod", ADDED, pod))
            self.watchers.append(watcher)

    # -- mutations emit events ----------------------------------------------
    # Each mutation emits UNDER the client lock (RLock, so the nested super()
    # call is fine): a real apiserver watch stream delivers events in
    # apply-order, and emitting outside the lock would let two threads'
    # events arrive transposed — the bridge's state would then permanently
    # diverge from the client's (caught by tests/test_concurrency_soak.py).
    def add_node(self, node: k8s.Node) -> None:
        with self._lock:
            super().add_node(node)
            self._emit(WatchEvent("node", ADDED, node))

    def update_node(self, node: k8s.Node) -> k8s.Node:
        with self._lock:
            out = super().update_node(node)
            self._emit(WatchEvent("node", MODIFIED, out))
        return out

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self.get_node(name)
            super().delete_node(name)
            if node is not None:
                self._emit(WatchEvent("node", DELETED, node))

    def add_pod(self, pod: k8s.Pod) -> None:
        with self._lock:
            super().add_pod(pod)
            if pod.phase not in ("Succeeded", "Failed"):
                self._emit(WatchEvent("pod", ADDED, pod))

    def update_pod(self, pod: k8s.Pod) -> None:
        with self._lock:
            super().add_pod(pod)  # upsert
            if pod.phase in ("Succeeded", "Failed"):
                # informer field-selector semantics: completed pods drop out
                self._emit(WatchEvent("pod", DELETED, pod))
            else:
                self._emit(WatchEvent("pod", MODIFIED, pod))

    def remove_pod(self, pod: k8s.Pod) -> None:
        with self._lock:
            super().remove_pod(pod)
            self._emit(WatchEvent("pod", DELETED, pod))


#: provider_id stamped on placeholder Node objects seeded by a warm restore —
#: never equal to any live node, so the first resync audit re-applies them
_WARM_RESTORE_SENTINEL = "escalator-tpu://warm-restore-placeholder"


@dataclass
class GroupFilters:
    """One nodegroup's membership filters (from controller.node_group)."""

    name: str
    pod_filter: Callable[[k8s.Pod], bool]
    node_filter: Callable[[k8s.Node], bool]


class WatchBridge:
    """Applies watch events to a NativeStateStore; keeps slot<->name maps."""

    def __init__(self, store, groups: Sequence[GroupFilters]):
        import threading

        self.store = store
        self._fallback_lock = threading.RLock()
        self.groups = list(groups)
        self.node_objects: Dict[str, k8s.Node] = {}
        self._node_slot_names: Dict[int, str] = {}
        # pod<->node binding maps: bindings are by NAME and re-resolved to slots on
        # node churn, so out-of-order events (pod before its node) and slot reuse
        # after node deletion can never leave stale slot references
        self._pod_records: Dict[str, Tuple[int, int, int, str]] = {}  # uid -> (gi, cpu, mem, node_name)
        self._pods_on_node: Dict[str, set] = {}  # node name -> pod uids
        # slot -> uid, the pod analogue of _node_slot_names (round 18): the
        # snapshot key sidecar needs a per-slot key table so a warm restore
        # can reproduce the ingestion-ordered slot layout byte-for-byte
        self._pod_slot_uids: Dict[int, str] = {}
        self.events_applied = 0
        self.events_ignored = 0

    # -- group resolution ----------------------------------------------------
    def _pod_group(self, pod: k8s.Pod) -> int:
        for gi, g in enumerate(self.groups):
            if g.pod_filter(pod):
                return gi
        return -1

    def _node_group(self, node: k8s.Node) -> int:
        for gi, g in enumerate(self.groups):
            if g.node_filter(node):
                return gi
        return -1

    # -- event application ---------------------------------------------------
    def apply(self, event: WatchEvent) -> None:
        # Events may arrive on a watch thread while the backend reads the store
        # on the controller thread; the store's lock is the single-writer
        # contract both sides share (NativeStateStore.lock). Falls back to a
        # bridge-local lock for store fakes without one.
        lock = getattr(self.store, "lock", None)
        if lock is None:
            lock = self._fallback_lock
        with lock:
            if event.kind == "pod":
                self._apply_pod(event)
            else:
                self._apply_node(event)

    def _forget_pod(self, uid: str) -> None:
        record = self._pod_records.pop(uid, None)
        if record is not None and record[3]:
            bucket = self._pods_on_node.get(record[3])
            if bucket is not None:
                bucket.discard(uid)

    def _apply_pod(self, event: WatchEvent) -> None:
        pod: k8s.Pod = event.obj
        uid = f"{pod.namespace}/{pod.name}"
        if event.type == DELETED:
            self._forget_pod(uid)
            slot = self.store.delete_pod(uid)
            if slot >= 0:
                self._pod_slot_uids.pop(slot, None)
                self.events_applied += 1
            return
        gi = self._pod_group(pod)
        if gi < 0:
            # not in any nodegroup (daemonset/static/unmatched): keep it out of
            # the store, and evict any stale prior version
            self._forget_pod(uid)
            slot = self.store.delete_pod(uid)
            if slot >= 0:
                self._pod_slot_uids.pop(slot, None)
                self.events_applied += 1
            else:
                self.events_ignored += 1
            return
        req = k8s.compute_pod_resource_request(pod)
        self._forget_pod(uid)
        self._pod_records[uid] = (gi, req.cpu_milli, req.mem_bytes, pod.node_name)
        if pod.node_name:
            self._pods_on_node.setdefault(pod.node_name, set()).add(uid)
        node_slot = (
            self.store.node_slot(pod.node_name) if pod.node_name else -1
        )
        slot = self.store.upsert_pod(
            uid, gi, req.cpu_milli, req.mem_bytes, node_slot)
        self._pod_slot_uids[slot] = uid
        self.events_applied += 1

    def _rebind_pods(self, node_name: str, node_slot: int) -> None:
        """Point every pod bound to ``node_name`` at ``node_slot`` (slot -1 when
        the node is gone). Heals out-of-order pod-before-node events and prevents
        recycled slots from inheriting another node's pods."""
        for uid in self._pods_on_node.get(node_name, ()):
            record = self._pod_records.get(uid)
            if record is not None:
                gi, cpu, mem, _ = record
                self.store.upsert_pod(uid, gi, cpu, mem, node_slot)

    def _drop_node(self, node: k8s.Node) -> bool:
        slot = self.store.delete_node(node.name)
        if slot >= 0:
            self._node_slot_names.pop(slot, None)
            self.node_objects.pop(node.name, None)
            self._rebind_pods(node.name, -1)
            return True
        return False

    def _apply_node(self, event: WatchEvent) -> None:
        node: k8s.Node = event.obj
        if event.type == DELETED:
            if self._drop_node(node):
                self.events_applied += 1
            return
        gi = self._node_group(node)
        if gi < 0:
            if self._drop_node(node):
                self.events_applied += 1
            else:
                self.events_ignored += 1
            return
        taint = k8s.get_to_be_removed_taint(node)
        taint_time = None
        if taint is not None:
            try:
                taint_time = int(taint.value)
            except ValueError:
                taint_time = None
        prev_slot = self.store.node_slot(node.name)
        slot = self.store.upsert_node(
            node.name, gi, node.cpu_allocatable_milli, node.mem_allocatable_bytes,
            creation_ns=node.creation_time_ns,
            tainted=taint is not None,
            cordoned=node.unschedulable,
            no_delete=bool(
                node.annotations.get(k8s.NODE_ESCALATOR_IGNORE_ANNOTATION)
            ),
            taint_time_sec=taint_time if taint_time is not None else NO_TAINT_TIME,
        )
        self._node_slot_names[slot] = node.name
        self.node_objects[node.name] = node
        # heal pods that arrived before this node (prev_slot -1) or rebind
        # after a slot change; a same-slot re-apply (resync audit, label-only
        # node update, warm-restore re-apply) leaves its pods' rows clean —
        # they are already bound to this slot, and re-upserting them would
        # turn every node touch into an O(pods-on-node) dirty cascade
        if slot != prev_slot:
            self._rebind_pods(node.name, slot)
        self.events_applied += 1

    # -- lookups for executors -----------------------------------------------
    def node_at_slot(self, slot: int) -> Optional[k8s.Node]:
        name = self._node_slot_names.get(slot)
        return self.node_objects.get(name) if name is not None else None

    # -- snapshot key sidecars (round 18: native warm restore) ----------------
    def slot_key_tables(self) -> Tuple[List[str], List[str]]:
        """Per-slot ``(pod_keys, node_keys)`` tables, ``""`` at free slots,
        sized to the store capacities. Checkpointed alongside the decider
        leaves so a restarted process can re-seed a fresh store in the
        snapshot's exact slot order (slots assign freelist-then-sequential,
        so ordered upserts on an empty store reproduce any layout). Caller
        holds the store lock."""
        pod_keys = [""] * self.store.pod_capacity
        for slot, uid in self._pod_slot_uids.items():
            pod_keys[slot] = uid
        node_keys = [""] * self.store.node_capacity
        for slot, name in self._node_slot_names.items():
            node_keys[slot] = name
        return pod_keys, node_keys

    def seed_from_snapshot(self, pod_keys: List[str], node_keys: List[str],
                           pods, nodes) -> None:
        """Rebuild the bridge's record maps from a snapshot's host columns +
        key sidecars, so the first :meth:`resync` audit compares live objects
        against the CHECKPOINT baseline — an object unchanged since the
        checkpoint skips its upsert and stays clean, leaving the first warm
        tick's delta batch O(changed-since-checkpoint). Node objects get a
        sentinel placeholder (no live node carries the sentinel provider_id,
        and the dataclass equality includes it), so the first resync
        re-applies every live node — N << P, cheap — while stale-node
        deletion still works by name. Caller holds the store lock."""
        for slot, name in enumerate(node_keys):
            if not name:
                continue
            self._node_slot_names[slot] = name
            self.node_objects[name] = k8s.Node(
                name=name, provider_id=_WARM_RESTORE_SENTINEL)
        for slot, uid in enumerate(pod_keys):
            if not uid:
                continue
            node = int(pods.node[slot])
            node_name = node_keys[node] if 0 <= node < len(node_keys) else ""
            self._pod_records[uid] = (
                int(pods.group[slot]), int(pods.cpu_milli[slot]),
                int(pods.mem_bytes[slot]), node_name)
            if node_name:
                self._pods_on_node.setdefault(node_name, set()).add(uid)
            self._pod_slot_uids[slot] = uid

    # -- re-list reconciliation (round 12) -----------------------------------
    def set_groups(self, groups: Sequence[GroupFilters],
                   client=None) -> Optional[dict]:
        """Replace the nodegroup filter set (a config reload: group added,
        removed, or re-labelled). Filters decide membership, and the bridge
        does NOT retain pod objects (only their resolved records), so a
        filter change must re-resolve membership from a full re-list:
        when ``client`` is given, :meth:`resync` runs immediately and its
        stats are returned; otherwise the caller owns scheduling the resync
        before the next decide (until then, pod group assignments reflect
        the OLD filter set)."""
        lock = getattr(self.store, "lock", None) or self._fallback_lock
        with lock:
            self.groups = list(groups)
        # a filter change invalidates every group resolution: full re-apply
        return self.resync(client, full=True) if client is not None else None

    def resync(self, client, full: bool = False) -> dict:
        """Full re-list reconciliation — the O(cluster) operation the
        streaming path demotes re-listing to (bootstrap / audit / filter
        change). Re-delivers the client's CURRENT state as ADDED events
        (re-resolving every object's group under the current filters) and
        deletes store entries for objects that no longer exist — healing
        any drift a lost/transposed event could have caused, exactly as a
        k8s informer's relist does. Runs under the client lock so no
        mutation lands between the list and the reconcile, and under the
        store lock so a concurrent decide never sees a half-applied
        resync. Returns ``{"pods_dropped", "nodes_dropped",
        "events_reapplied"}``.

        ``full=False`` (the cadence audit) re-applies only objects that
        DIFFER from the bridge's records: an unchanged object skips its
        store upsert, so a clean audit marks zero slots dirty and the next
        tick's delta batch stays empty instead of rescattering the whole
        cluster (at 1M pods an unconditional re-apply would drain a
        full-capacity packed batch and compile a fresh full-size scatter —
        the exact spike an audit tick must not have). ``full=True``
        (:meth:`set_groups`) re-applies everything — a filter change moves
        membership without changing any object."""
        import contextlib

        store_lock = getattr(self.store, "lock", None) or self._fallback_lock
        # the in-memory client exposes its lock; a real apiserver adapter has
        # no global lock to take (its LIST is a consistent snapshot already)
        client_lock = getattr(client, "_lock", None) or contextlib.nullcontext()
        with client_lock, store_lock:
            live_pods = [p for p in client.list_pods()
                         if p.phase not in ("Succeeded", "Failed")]
            live_nodes = client.list_nodes()
            live_pod_uids = {f"{p.namespace}/{p.name}" for p in live_pods}
            live_node_names = {n.name for n in live_nodes}
            # drop what the world no longer has (a DELETED event we missed)
            stale_pods = [uid for uid in self._pod_records
                          if uid not in live_pod_uids]
            for uid in stale_pods:
                self._forget_pod(uid)
                slot = self.store.delete_pod(uid)
                if slot >= 0:
                    self._pod_slot_uids.pop(slot, None)
            stale_nodes = [name for name in list(self.node_objects)
                           if name not in live_node_names]
            for name in stale_nodes:
                self._drop_node(self.node_objects[name])
            # re-deliver current state (nodes first: pods bind to slots)
            before = self.events_applied
            for node in live_nodes:
                if not full and self.node_objects.get(node.name) == node:
                    continue   # identical object, same filters: no drift
                self._apply_node(WatchEvent("node", ADDED, node))
            for pod in live_pods:
                if not full:
                    uid = f"{pod.namespace}/{pod.name}"
                    rec = self._pod_records.get(uid)
                    if rec is not None:
                        req = k8s.compute_pod_resource_request(pod)
                        if rec == (self._pod_group(pod), req.cpu_milli,
                                   req.mem_bytes, pod.node_name):
                            continue   # record matches: store is current
                self._apply_pod(WatchEvent("pod", ADDED, pod))
            return {
                "pods_dropped": len(stale_pods),
                "nodes_dropped": len(stale_nodes),
                "events_reapplied": self.events_applied - before,
            }
