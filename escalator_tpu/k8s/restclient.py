"""Real-apiserver client: the k8s REST list+watch wire protocol over stdlib HTTP.

The reference reaches the apiserver through client-go — clientset construction at
/root/reference/pkg/k8s/client.go:12-40, reflector-style informer caches at
pkg/k8s/cache.go:16-66, and a Lease-based leader-election lock at
pkg/k8s/election.go:25-58. The ``kubernetes`` Python package is not vendored in
this image, so this module speaks the wire protocol directly:

- :class:`Transport` — token/TLS HTTP with streaming responses (http.client).
- :class:`Informer` — list+watch reflector for one resource: paged LIST,
  then a chunked WATCH from the returned resourceVersion, relisting on 410
  Gone exactly like client-go's Reflector. Emits the same add/modify/delete
  :class:`~escalator_tpu.k8s.cache.WatchEvent` stream the in-memory
  ``EventfulClient`` does, so ``WatchBridge``/the native backend consume a real
  cluster and a simulated one identically.
- :class:`ApiserverClient` — the ``KubernetesClient`` protocol against a live
  apiserver: cached list_pods/list_nodes (informer semantics: reads never hit
  the wire, matching pkg/k8s/cache.go), GET-then-PUT node updates that
  round-trip the server's raw JSON (fields this model doesn't carry are
  preserved), node deletion, and Event POSTs.
- :class:`LeaseResourceLock` — the elector's CAS lock over a
  coordination.k8s.io/v1 Lease with resourceVersion optimistic concurrency.
- :func:`load_incluster` / :func:`load_kubeconfig` — config discovery mirroring
  rest.InClusterConfig / clientcmd.BuildConfigFromFlags.

Field selectors match the reference informers: pods are watched with
``status.phase!=Succeeded,status.phase!=Failed`` (pkg/k8s/cache.go:17), nodes
unfiltered (cache.go:37).
"""

from __future__ import annotations

import base64
import calendar
import http.client
import json
import logging
import os
import ssl
import tempfile
import threading
import time
import urllib.parse
from fractions import Fraction
from typing import Callable, Dict, Iterator, List, Optional

from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.cache import ADDED, DELETED, MODIFIED, WatchEvent
from escalator_tpu.k8s.election import LeaderRecord

log = logging.getLogger("escalator_tpu.k8s.restclient")

SERVICEACCOUNT_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


class ApiError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"apiserver HTTP {status}: {message}")
        self.status = status


class ConflictError(ApiError):
    """HTTP 409 — optimistic-concurrency failure (stale resourceVersion)."""


class StaleResourceVersion(RuntimeError):
    """HTTP 410 Gone on watch — the reflector must relist."""


# ---------------------------------------------------------------------------
# resource.Quantity — parse the canonical k8s quantity grammar
# ---------------------------------------------------------------------------

_BIN_SUFFIX = {"Ki": 1024, "Mi": 1024**2, "Gi": 1024**3,
               "Ti": 1024**4, "Pi": 1024**5, "Ei": 1024**6}
_DEC_SUFFIX = {"n": Fraction(1, 10**9), "u": Fraction(1, 10**6),
               "m": Fraction(1, 1000), "": Fraction(1),
               "k": Fraction(10**3), "M": Fraction(10**6), "G": Fraction(10**9),
               "T": Fraction(10**12), "P": Fraction(10**15), "E": Fraction(10**18)}


def parse_quantity(s: str) -> Fraction:
    """Exact value of a k8s quantity string ("500m", "2", "1.5Gi", "1e3")."""
    s = s.strip()
    if not s:
        return Fraction(0)
    for suf, mult in _BIN_SUFFIX.items():
        if s.endswith(suf):
            return Fraction(s[: -len(suf)]) * mult
    if s[-1] in _DEC_SUFFIX and s[-1] not in "0123456789.":
        return Fraction(s[:-1]) * _DEC_SUFFIX[s[-1]]
    if "e" in s or "E" in s:
        mant, _, exp = s.replace("E", "e").partition("e")
        return Fraction(mant) * Fraction(10) ** int(exp)
    return Fraction(s)


def quantity_milli(s: str) -> int:
    """MilliValue(): value*1000 rounded up (resource.Quantity convention)."""
    v = parse_quantity(s) * 1000
    return -((-v.numerator) // v.denominator)  # ceil


def quantity_bytes(s: str) -> int:
    """Value(): rounded up to an integer."""
    v = parse_quantity(s)
    return -((-v.numerator) // v.denominator)


def _rfc3339_to_ns(ts: str) -> int:
    """k8s creationTimestamp ('2026-07-29T12:00:00Z', optional fraction) → unix ns."""
    return int(_parse_micro_time(ts) * 1e9)


def _ns_to_rfc3339(ns: int) -> str:
    return time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime(ns / 1e9))


def _micro_time(sec: float) -> str:
    # round to total microseconds FIRST: rounding the fraction independently
    # can yield ".1000000" (7 digits) near x.9999996, which parses back as x.1
    total_us = int(round(sec * 1e6))
    secs, frac = divmod(total_us, 10**6)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.gmtime(secs)) + f".{frac:06d}Z"


def _parse_micro_time(ts: Optional[str]) -> float:
    """RFC3339 with optional fractional seconds → unix seconds (MicroTime and
    Time fields alike)."""
    if not ts:
        return 0.0
    base = ts.strip().rstrip("Z")
    frac = 0.0
    if "." in base:
        base, _, fs = base.partition(".")
        frac = float("0." + fs) if fs else 0.0
    return calendar.timegm(time.strptime(base, "%Y-%m-%dT%H:%M:%S")) + frac


# ---------------------------------------------------------------------------
# JSON <-> model mapping (the slices of core/v1 the reference consumes)
# ---------------------------------------------------------------------------


def _requests_from_container(c: dict) -> k8s.ResourceRequests:
    req = (c.get("resources") or {}).get("requests") or {}
    return k8s.ResourceRequests(
        cpu_milli=quantity_milli(str(req.get("cpu", "0"))),
        mem_bytes=quantity_bytes(str(req.get("memory", "0"))),
    )


def _affinity_from_json(spec_affinity: Optional[dict]) -> Optional[k8s.Affinity]:
    if not spec_affinity:
        return None
    node_aff = spec_affinity.get("nodeAffinity") or {}
    required = node_aff.get("requiredDuringSchedulingIgnoredDuringExecution") or {}
    terms = []
    for term in required.get("nodeSelectorTerms") or []:
        exprs = tuple(
            k8s.NodeSelectorRequirement(
                key=e.get("key", ""),
                operator=e.get("operator", "In"),
                values=tuple(e.get("values") or ()),
            )
            for e in term.get("matchExpressions") or []
        )
        terms.append(k8s.NodeSelectorTerm(match_expressions=exprs))
    return k8s.Affinity(
        node_affinity_required_terms=tuple(terms) if terms else None,
        has_node_affinity=bool(node_aff),
        has_pod_affinity=bool(spec_affinity.get("podAffinity")),
        has_pod_anti_affinity=bool(spec_affinity.get("podAntiAffinity")),
    )


def pod_from_json(obj: dict) -> k8s.Pod:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    overhead_json = spec.get("overhead")
    overhead = None
    if overhead_json:
        overhead = k8s.ResourceRequests(
            cpu_milli=quantity_milli(str(overhead_json.get("cpu", "0"))),
            mem_bytes=quantity_bytes(str(overhead_json.get("memory", "0"))),
        )
    owner_kind = ""
    for ref in meta.get("ownerReferences") or []:
        if ref.get("controller"):
            owner_kind = ref.get("kind", "")
            break
        owner_kind = owner_kind or ref.get("kind", "")
    return k8s.Pod(
        name=meta.get("name", ""),
        namespace=meta.get("namespace", "default"),
        node_name=spec.get("nodeName", "") or "",
        containers=[_requests_from_container(c) for c in spec.get("containers") or []],
        init_containers=[
            _requests_from_container(c) for c in spec.get("initContainers") or []
        ],
        overhead=overhead,
        node_selector=dict(spec.get("nodeSelector") or {}),
        affinity=_affinity_from_json(spec.get("affinity")),
        owner_kind=owner_kind,
        annotations=dict(meta.get("annotations") or {}),
        phase=status.get("phase", "Running") or "Running",
    )


def node_from_json(obj: dict) -> k8s.Node:
    meta = obj.get("metadata") or {}
    spec = obj.get("spec") or {}
    status = obj.get("status") or {}
    alloc = status.get("allocatable") or {}
    taints = [
        k8s.Taint(
            key=t.get("key", ""),
            value=str(t.get("value", "") or ""),
            effect=t.get("effect", k8s.TaintEffect.NO_SCHEDULE.value),
        )
        for t in spec.get("taints") or []
    ]
    return k8s.Node(
        name=meta.get("name", ""),
        creation_time_ns=_rfc3339_to_ns(meta.get("creationTimestamp", "")),
        cpu_allocatable_milli=quantity_milli(str(alloc.get("cpu", "0"))),
        mem_allocatable_bytes=quantity_bytes(str(alloc.get("memory", "0"))),
        labels=dict(meta.get("labels") or {}),
        annotations=dict(meta.get("annotations") or {}),
        taints=taints,
        unschedulable=bool(spec.get("unschedulable", False)),
        provider_id=spec.get("providerID", "") or "",
    )


def node_to_json(node: k8s.Node, raw: Optional[dict] = None) -> dict:
    """Project our Node onto raw apiserver JSON. Only the fields this framework
    owns are written — taints, unschedulable, labels, annotations — so a PUT
    round-trips every field the model doesn't carry (status, conditions, images,
    ...). With no raw base (tests / object creation) a minimal object is built."""
    obj = json.loads(json.dumps(raw)) if raw else {
        "apiVersion": "v1", "kind": "Node",
        "metadata": {"name": node.name},
        "spec": {},
        "status": {"allocatable": {
            "cpu": f"{node.cpu_allocatable_milli}m",
            "memory": str(node.mem_allocatable_bytes),
        }},
    }
    meta = obj.setdefault("metadata", {})
    spec = obj.setdefault("spec", {})
    meta["name"] = node.name
    meta["labels"] = dict(node.labels)
    meta["annotations"] = dict(node.annotations)
    if not raw and node.creation_time_ns:
        meta["creationTimestamp"] = _ns_to_rfc3339(node.creation_time_ns)
    spec["taints"] = [
        {"key": t.key, "value": t.value, "effect": t.effect} for t in node.taints
    ]
    spec["unschedulable"] = bool(node.unschedulable)
    if node.provider_id:
        spec["providerID"] = node.provider_id
    return obj


def pod_to_json(pod: k8s.Pod) -> dict:
    """Minimal core/v1 Pod JSON (test/fake-server helper; the controller never
    creates pods)."""
    containers = [
        {"name": f"c{i}", "resources": {"requests": {
            "cpu": f"{c.cpu_milli}m", "memory": str(c.mem_bytes)}}}
        for i, c in enumerate(pod.containers)
    ]
    spec: dict = {"containers": containers}
    if pod.init_containers:
        spec["initContainers"] = [
            {"name": f"ic{i}", "resources": {"requests": {
                "cpu": f"{c.cpu_milli}m", "memory": str(c.mem_bytes)}}}
            for i, c in enumerate(pod.init_containers)
        ]
    if pod.overhead is not None:
        spec["overhead"] = {"cpu": f"{pod.overhead.cpu_milli}m",
                            "memory": str(pod.overhead.mem_bytes)}
    if pod.node_name:
        spec["nodeName"] = pod.node_name
    if pod.node_selector:
        spec["nodeSelector"] = dict(pod.node_selector)
    if pod.affinity is not None:
        affinity: dict = {}
        if pod.affinity.node_affinity_required_terms:
            affinity["nodeAffinity"] = {
                "requiredDuringSchedulingIgnoredDuringExecution": {
                    "nodeSelectorTerms": [
                        {"matchExpressions": [
                            {"key": e.key, "operator": e.operator,
                             "values": list(e.values)}
                            for e in term.match_expressions
                        ]}
                        for term in pod.affinity.node_affinity_required_terms
                    ]
                }
            }
        elif pod.affinity.has_node_affinity:
            affinity["nodeAffinity"] = {}
        if pod.affinity.has_pod_affinity:
            affinity["podAffinity"] = {}
        if pod.affinity.has_pod_anti_affinity:
            affinity["podAntiAffinity"] = {}
        if affinity:
            spec["affinity"] = affinity
    meta: dict = {"name": pod.name, "namespace": pod.namespace}
    if pod.annotations:
        meta["annotations"] = dict(pod.annotations)
    if pod.owner_kind:
        meta["ownerReferences"] = [
            {"kind": pod.owner_kind, "name": "owner", "controller": True}
        ]
    return {"apiVersion": "v1", "kind": "Pod", "metadata": meta, "spec": spec,
            "status": {"phase": pod.phase}}


def event_to_json(event: k8s.Event) -> dict:
    ts = _ns_to_rfc3339(int(event.timestamp_sec * 1e9)) if event.timestamp_sec else ""
    return {
        "apiVersion": "v1", "kind": "Event",
        "metadata": {"generateName": "escalator-tpu-",
                     "namespace": event.namespace},
        "reason": event.reason,
        "message": event.message,
        "type": event.type,
        "count": event.count,
        "firstTimestamp": ts,
        "lastTimestamp": ts,
        "involvedObject": {"kind": event.involved_kind,
                           "name": event.involved_name,
                           "namespace": event.namespace},
        "source": {"component": event.source},
    }


# ---------------------------------------------------------------------------
# Transport
# ---------------------------------------------------------------------------


class ApiserverConfig:
    """Connection parameters (rest.Config analog). ``token_file`` takes
    precedence over ``token`` and is re-read on change — bound serviceaccount
    tokens rotate on disk (~hourly since k8s 1.21) and client-go reloads them;
    a cached startup token would turn into permanent 401s an hour in."""

    def __init__(self, base_url: str, token: str = "",
                 ca_file: Optional[str] = None, verify: bool = True,
                 namespace: str = "default",
                 token_file: Optional[str] = None):
        self.base_url = base_url.rstrip("/")
        self._token = token
        self.token_file = token_file
        self.ca_file = ca_file
        self.verify = verify
        self.namespace = namespace
        self._token_mtime: Optional[float] = None

    @property
    def token(self) -> str:
        if self.token_file:
            try:
                mtime = os.stat(self.token_file).st_mtime
                if mtime != self._token_mtime:
                    with open(self.token_file) as f:
                        self._token = f.read().strip()
                    self._token_mtime = mtime
            except OSError:
                pass  # keep the last-known token
        return self._token


class Transport:
    """One apiserver endpoint; a fresh connection per request (the watch holds
    its connection open for minutes — pooling buys nothing for this traffic)."""

    def __init__(self, config: ApiserverConfig):
        self.config = config
        parsed = urllib.parse.urlsplit(config.base_url)
        self._scheme = parsed.scheme or "https"
        self._host = parsed.hostname or "localhost"
        self._port = parsed.port or (443 if self._scheme == "https" else 80)
        self._prefix = parsed.path.rstrip("/")
        if self._scheme == "https":
            if config.verify:
                self._ssl = ssl.create_default_context(cafile=config.ca_file)
            else:
                self._ssl = ssl._create_unverified_context()  # noqa: S323 - explicit opt-in
        else:
            self._ssl = None

    def _connect(self, timeout: float) -> http.client.HTTPConnection:
        if self._ssl is not None:
            return http.client.HTTPSConnection(
                self._host, self._port, timeout=timeout, context=self._ssl)
        return http.client.HTTPConnection(self._host, self._port, timeout=timeout)

    def _headers(self, has_body: bool) -> Dict[str, str]:
        h = {"Accept": "application/json", "User-Agent": "escalator-tpu"}
        if self.config.token:
            h["Authorization"] = f"Bearer {self.config.token}"
        if has_body:
            h["Content-Type"] = "application/json"
        return h

    def request(self, method: str, path: str,
                params: Optional[Dict[str, str]] = None,
                body: Optional[dict] = None, timeout: float = 30.0) -> dict:
        """One JSON request/response. Raises ApiError/ConflictError on non-2xx."""
        conn = self._connect(timeout)
        try:
            url = self._prefix + path
            if params:
                url += "?" + urllib.parse.urlencode(params)
            payload = json.dumps(body).encode() if body is not None else None
            conn.request(method, url, body=payload,
                         headers=self._headers(payload is not None))
            resp = conn.getresponse()
            data = resp.read()
            if resp.status == 409:
                raise ConflictError(409, data.decode(errors="replace")[:512])
            if resp.status == 410:
                raise StaleResourceVersion(data.decode(errors="replace")[:512])
            if not 200 <= resp.status < 300:
                raise ApiError(resp.status, data.decode(errors="replace")[:512])
            return json.loads(data) if data else {}
        finally:
            conn.close()

    def stream_watch(self, path: str, params: Dict[str, str],
                     read_timeout: float) -> Iterator[dict]:
        """Chunked watch stream: yields decoded watch-event JSON objects until
        the server ends the stream (timeoutSeconds) or the socket times out."""
        conn = self._connect(read_timeout)
        try:
            url = self._prefix + path + "?" + urllib.parse.urlencode(params)
            conn.request("GET", url, headers=self._headers(False))
            resp = conn.getresponse()
            if resp.status == 410:
                raise StaleResourceVersion(resp.read().decode(errors="replace")[:256])
            if not 200 <= resp.status < 300:
                raise ApiError(resp.status, resp.read().decode(errors="replace")[:256])
            buf = b""
            while True:
                chunk = resp.read1(65536)
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, _, buf = buf.partition(b"\n")
                    line = line.strip()
                    if line:
                        yield json.loads(line)
        finally:
            conn.close()


# ---------------------------------------------------------------------------
# Informer: the reflector loop (list once, watch forever, relist on 410)
# ---------------------------------------------------------------------------

_POD_FIELD_SELECTOR = "status.phase!=Succeeded,status.phase!=Failed"


class Informer:
    """List+watch reflector for one resource collection, mirroring the
    IndexerInformer construction at /root/reference/pkg/k8s/cache.go:16-66.

    Maintains {name: raw JSON} and emits WatchEvents through ``on_event`` in
    apply order under ``lock`` — the same ordering contract EventfulClient
    gives WatchBridge."""

    def __init__(self, transport: Transport, path: str, kind: str,
                 parse: Callable[[dict], object],
                 on_event: Callable[[WatchEvent, dict], None],
                 lock: threading.RLock,
                 field_selector: str = "",
                 watch_timeout_sec: int = 300):
        self.transport = transport
        self.path = path
        self.kind = kind  # "pod" | "node"
        self.parse = parse
        self.on_event = on_event
        self.lock = lock
        self.field_selector = field_selector
        self.watch_timeout_sec = watch_timeout_sec
        self.raw: Dict[str, dict] = {}
        #: parsed twin of ``raw`` — lister reads per tick would otherwise
        #: re-parse the whole cluster under the watch-ingestion lock
        self.parsed: Dict[str, object] = {}
        self.resource_version = ""
        self.synced = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.relists = 0

    @staticmethod
    def _name(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        ns = meta.get("namespace")
        name = meta.get("name", "")
        return f"{ns}/{name}" if ns else name

    # -- list --------------------------------------------------------------
    def _list(self) -> None:
        params = {"limit": "500"}
        if self.field_selector:
            params["fieldSelector"] = self.field_selector
        items: Dict[str, dict] = {}
        cont = ""
        while True:
            if cont:
                params["continue"] = cont
            doc = self.transport.request("GET", self.path, params=dict(params))
            for obj in doc.get("items") or []:
                items[self._name(obj)] = obj
            meta = doc.get("metadata") or {}
            cont = meta.get("continue") or ""
            if not cont:
                self.resource_version = str(meta.get("resourceVersion", ""))
                break
        # replace-style reconciliation: diff the relist against the cache so
        # downstream consumers see exactly the deltas (client-go Replace)
        with self.lock:
            old = self.raw
            old_parsed = self.parsed
            self.raw = items
            self.parsed = {n: self.parse(o) for n, o in items.items()}
            for name, obj in items.items():
                prev = old.pop(name, None)
                if prev is None:
                    self.on_event(
                        WatchEvent(self.kind, ADDED, self.parsed[name]), obj)
                elif prev != obj:
                    self.on_event(
                        WatchEvent(self.kind, MODIFIED, self.parsed[name]), obj)
            for name, obj in old.items():
                gone = old_parsed.get(name) or self.parse(obj)
                self.on_event(WatchEvent(self.kind, DELETED, gone), obj)
        self.synced.set()

    # -- watch -------------------------------------------------------------
    def _watch_once(self) -> None:
        params = {
            "watch": "true",
            "resourceVersion": self.resource_version,
            "allowWatchBookmarks": "true",
            "timeoutSeconds": str(self.watch_timeout_sec),
        }
        if self.field_selector:
            params["fieldSelector"] = self.field_selector
        for raw_event in self.transport.stream_watch(
            self.path, params, read_timeout=self.watch_timeout_sec + 30
        ):
            etype = raw_event.get("type", "")
            obj = raw_event.get("object") or {}
            if etype == "ERROR":
                code = (obj.get("code") or 0)
                if code == 410:
                    raise StaleResourceVersion(obj.get("message", "410 Gone"))
                raise ApiError(int(code) or 500, obj.get("message", "watch error"))
            rv = str(((obj.get("metadata") or {}).get("resourceVersion")) or "")
            if rv:
                self.resource_version = rv
            if etype == "BOOKMARK":
                continue
            name = self._name(obj)
            with self.lock:
                if etype in ("ADDED", "MODIFIED"):
                    parsed = self.parse(obj)
                    self.raw[name] = obj
                    self.parsed[name] = parsed
                    wire = ADDED if etype == "ADDED" else MODIFIED
                    self.on_event(WatchEvent(self.kind, wire, parsed), obj)
                elif etype == "DELETED":
                    self.raw.pop(name, None)
                    gone = self.parsed.pop(name, None) or self.parse(obj)
                    self.on_event(WatchEvent(self.kind, DELETED, gone), obj)

    def _run(self) -> None:
        backoff = 0.2
        while not self._stop.is_set():
            try:
                self._list()
                backoff = 0.2
                while not self._stop.is_set():
                    self._watch_once()  # returns on server timeout; re-watch
            except StaleResourceVersion:
                self.relists += 1
                log.info("%s watch expired (410); relisting", self.path)
            except Exception as e:
                if self._stop.is_set():
                    return
                log.warning("%s list/watch failed: %s (retry in %.1fs)",
                            self.path, e, backoff)
                self._stop.wait(backoff)
                backoff = min(backoff * 2, 30.0)

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"informer-{self.kind}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def wait_for_sync(self, timeout: float = 30.0) -> bool:
        """WaitForSync analog (pkg/k8s/cache.go:59-66)."""
        return self.synced.wait(timeout)


# ---------------------------------------------------------------------------
# ApiserverClient — KubernetesClient over a live cluster
# ---------------------------------------------------------------------------


class ApiserverClient:
    """The controller's cluster interface against a real apiserver.

    Reads (list_pods/list_nodes) are served from the informer caches — never
    the wire — matching the reference where every read goes through listers
    over informer stores (pkg/k8s/cache.go). Writes (update_node/delete_node/
    create_event) go straight to the apiserver. ``subscribe`` delivers the
    merged pod+node watch stream with list-then-watch replay, the same
    contract EventfulClient.subscribe gives WatchBridge."""

    def __init__(self, config: ApiserverConfig,
                 watch_timeout_sec: int = 300):
        self.config = config
        self.transport = Transport(config)
        self._lock = threading.RLock()
        self.watchers: List[Callable[[WatchEvent], None]] = []
        self._pods = Informer(
            self.transport, "/api/v1/pods", "pod", pod_from_json,
            self._dispatch, self._lock,
            field_selector=_POD_FIELD_SELECTOR,
            watch_timeout_sec=watch_timeout_sec,
        )
        self._nodes = Informer(
            self.transport, "/api/v1/nodes", "node", node_from_json,
            self._dispatch, self._lock,
            watch_timeout_sec=watch_timeout_sec,
        )

    # -- lifecycle ---------------------------------------------------------
    def start(self, sync_timeout: float = 60.0) -> None:
        self._pods.start()
        self._nodes.start()
        if not (self._pods.wait_for_sync(sync_timeout)
                and self._nodes.wait_for_sync(sync_timeout)):
            raise RuntimeError(
                f"informer caches failed to sync within {sync_timeout}s"
            )

    def stop(self) -> None:
        self._pods.stop()
        self._nodes.stop()

    # -- watch fan-out -----------------------------------------------------
    def _dispatch(self, event: WatchEvent, raw: dict) -> None:
        for w in self.watchers:
            w(event)

    def subscribe(self, watcher: Callable[[WatchEvent], None],
                  replay: bool = True) -> None:
        with self._lock:
            if replay:
                for parsed in self._nodes.parsed.values():
                    watcher(WatchEvent("node", ADDED, parsed))
                for parsed in self._pods.parsed.values():
                    watcher(WatchEvent("pod", ADDED, parsed))
            self.watchers.append(watcher)

    # -- reads -------------------------------------------------------------
    def list_pods(self) -> List[k8s.Pod]:
        with self._lock:
            return list(self._pods.parsed.values())

    def list_nodes(self) -> List[k8s.Node]:
        with self._lock:
            return list(self._nodes.parsed.values())

    def get_node(self, name: str) -> Optional[k8s.Node]:
        """Live GET (not the cache): the taint flow is GET-then-UPDATE and must
        see the node's current resourceVersion (pkg/k8s/taint.go:41-47)."""
        try:
            obj = self.transport.request("GET", f"/api/v1/nodes/{name}")
        except ApiError as e:
            if e.status == 404:
                return None
            raise
        parsed = node_from_json(obj)
        with self._lock:
            self._nodes.raw[name] = obj
            self._nodes.parsed[name] = parsed
        # copy, matching InMemoryKubernetesClient.get_node: the taint flow
        # mutates the returned node BEFORE the PUT — handing out the cache
        # resident would plant a phantom taint in the cache if the PUT fails
        return parsed.copy()

    # -- writes ------------------------------------------------------------
    def update_node(self, node: k8s.Node) -> k8s.Node:
        """PUT the node, projecting our fields onto the freshest raw JSON so
        everything the model doesn't carry round-trips. ConflictError (409)
        propagates — callers re-GET and retry like client-go users do."""
        with self._lock:
            raw = self._nodes.raw.get(node.name)
        if raw is None:
            raw = self.transport.request("GET", f"/api/v1/nodes/{node.name}")
        body = node_to_json(node, raw=raw)
        out = self.transport.request("PUT", f"/api/v1/nodes/{node.name}", body=body)
        parsed = node_from_json(out)
        with self._lock:
            self._nodes.raw[node.name] = out
            self._nodes.parsed[node.name] = parsed
        return parsed

    def delete_node(self, name: str) -> None:
        self.transport.request("DELETE", f"/api/v1/nodes/{name}")

    def create_event(self, event: k8s.Event) -> None:
        ns = event.namespace or self.config.namespace
        try:
            self.transport.request(
                "POST", f"/api/v1/namespaces/{ns}/events",
                body=event_to_json(event))
        except Exception as e:  # best-effort: never raise into the control loop
            log.warning("failed to POST event %s: %s", event.reason, e)


# ---------------------------------------------------------------------------
# Lease resource lock (coordination.k8s.io/v1) — election.ResourceLock impl
# ---------------------------------------------------------------------------


class LeaseResourceLock:
    """CAS lock over a Lease object, the lock type the reference elects with
    (/root/reference/pkg/k8s/election.go:57-76, resourcelock.LeasesResourceLock).
    Optimistic concurrency: every update PUTs with the resourceVersion of the
    Lease it read; a 409 means another holder raced us -> CAS failure."""

    def __init__(self, transport: Transport, namespace: str = "kube-system",
                 name: str = "escalator-tpu", lease_duration_sec: float = 15.0):
        self.transport = transport
        self.path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        )
        self.namespace = namespace
        self.name = name
        # coordination/v1 validation requires leaseDurationSeconds > 0
        # (a 0 would be 422 Invalid on every write -> election livelock)
        self.lease_duration_sec = max(1, int(round(lease_duration_sec)))

    def _lease_to_record(self, obj: dict) -> Optional[LeaderRecord]:
        spec = obj.get("spec") or {}
        holder = spec.get("holderIdentity")
        if not holder:
            return None
        return LeaderRecord(
            holder=holder,
            acquire_time=_parse_micro_time(spec.get("acquireTime")),
            renew_time=_parse_micro_time(spec.get("renewTime")),
        )

    def get(self) -> Optional[LeaderRecord]:
        try:
            obj = self.transport.request("GET", f"{self.path}/{self.name}")
        except ApiError as e:
            if e.status == 404:
                return None
            raise
        return self._lease_to_record(obj)

    def _lease_body(self, record: LeaderRecord, rv: Optional[str]) -> dict:
        meta: dict = {"name": self.name, "namespace": self.namespace}
        if rv:
            meta["resourceVersion"] = rv
        return {
            "apiVersion": "coordination.k8s.io/v1",
            "kind": "Lease",
            "metadata": meta,
            "spec": {
                "holderIdentity": record.holder,
                "acquireTime": _micro_time(record.acquire_time),
                "renewTime": _micro_time(record.renew_time),
                "leaseDurationSeconds": self.lease_duration_sec,
            },
        }

    def create_or_update(self, record: LeaderRecord,
                         expected_holder: Optional[str]) -> bool:
        try:
            if expected_holder is None:
                # create-if-absent: POST; on 409 AlreadyExists the Lease may
                # exist with an EMPTY holderIdentity (released client-go-style
                # or pre-created by a manifest) — claim it via CAS PUT instead
                # of livelocking on POST forever
                try:
                    self.transport.request(
                        "POST", self.path, body=self._lease_body(record, None))
                    return True
                except ConflictError:
                    obj = self.transport.request(
                        "GET", f"{self.path}/{self.name}")
                    if self._lease_to_record(obj) is not None:
                        return False  # someone holds it; caller re-evaluates
                    rv = str((obj.get("metadata") or {}).get(
                        "resourceVersion", ""))
                    self.transport.request(
                        "PUT", f"{self.path}/{self.name}",
                        body=self._lease_body(record, rv))
                    return True
            # re-read so the CAS sees the freshest holder + resourceVersion
            try:
                obj = self.transport.request("GET", f"{self.path}/{self.name}")
            except ApiError as e:
                if e.status == 404:
                    return False  # expected a holder; lease vanished
                raise
            current = self._lease_to_record(obj)
            if current is None or current.holder != expected_holder:
                return False
            rv = str((obj.get("metadata") or {}).get("resourceVersion", ""))
            self.transport.request(
                "PUT", f"{self.path}/{self.name}",
                body=self._lease_body(record, rv))
            return True
        except ConflictError:
            return False
        except ApiError as e:
            log.warning("lease CAS failed: %s", e)
            return False
        except (OSError, ssl.SSLError) as e:
            # refused connection / timeout / TLS reset during an apiserver
            # rolling restart: a CAS failure, not a crash — retry next period
            log.warning("lease CAS failed transiently: %s", e)
            return False


# ---------------------------------------------------------------------------
# Config discovery
# ---------------------------------------------------------------------------


def incluster_config() -> ApiserverConfig:
    """rest.InClusterConfig analog (reference: pkg/k8s/client.go:28-40):
    serviceaccount token + CA + KUBERNETES_SERVICE_HOST/PORT."""
    host = os.environ.get("KUBERNETES_SERVICE_HOST")
    port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
    if not host:
        raise RuntimeError(
            "not in a cluster: KUBERNETES_SERVICE_HOST is unset"
        )
    token_path = os.path.join(SERVICEACCOUNT_DIR, "token")
    ca_path = os.path.join(SERVICEACCOUNT_DIR, "ca.crt")
    ns_path = os.path.join(SERVICEACCOUNT_DIR, "namespace")
    if not os.path.exists(token_path):
        raise RuntimeError(f"serviceaccount token missing at {token_path}")
    namespace = "default"
    if os.path.exists(ns_path):
        with open(ns_path) as f:
            namespace = f.read().strip() or "default"
    if ":" in host and not host.startswith("["):
        host = f"[{host}]"
    return ApiserverConfig(
        base_url=f"https://{host}:{port}",
        token_file=token_path,  # re-read on rotation
        ca_file=ca_path if os.path.exists(ca_path) else None,
        namespace=namespace,
    )


def kubeconfig_config(path: str, context: str = "") -> ApiserverConfig:
    """clientcmd.BuildConfigFromFlags analog (reference: pkg/k8s/client.go:12-26).
    Supports the common fields: cluster server/CA(-data)/insecure, user
    token(-file). Exec/auth-provider/client-cert flows are out of scope."""
    import yaml

    with open(path) as f:
        doc = yaml.safe_load(f) or {}
    ctx_name = context or doc.get("current-context") or ""
    contexts = {c["name"]: c["context"] for c in doc.get("contexts") or []}
    clusters = {c["name"]: c["cluster"] for c in doc.get("clusters") or []}
    users = {u["name"]: u.get("user") or {} for u in doc.get("users") or []}
    if ctx_name not in contexts:
        raise RuntimeError(f"kubeconfig {path}: context {ctx_name!r} not found")
    ctx = contexts[ctx_name]
    cluster = clusters.get(ctx.get("cluster", ""))
    if cluster is None:
        raise RuntimeError(f"kubeconfig {path}: cluster {ctx.get('cluster')!r} not found")
    user = users.get(ctx.get("user", ""), {})
    token = user.get("token", "")
    token_file = user.get("tokenFile") if not token else None
    ca_file = cluster.get("certificate-authority")
    ca_data = cluster.get("certificate-authority-data")
    if ca_data and not ca_file:
        tmp = tempfile.NamedTemporaryFile(
            "wb", suffix=".crt", delete=False, prefix="escalator-ca-")
        tmp.write(base64.b64decode(ca_data))
        tmp.close()
        ca_file = tmp.name
    return ApiserverConfig(
        base_url=cluster.get("server", ""),
        token=token,
        token_file=token_file,
        ca_file=ca_file,
        verify=not cluster.get("insecure-skip-tls-verify", False),
        namespace=ctx.get("namespace", "default"),
    )


def connect(config: ApiserverConfig, sync_timeout: float = 60.0) -> ApiserverClient:
    client = ApiserverClient(config)
    client.start(sync_timeout=sync_timeout)
    return client
