"""Filtered listers over the cluster cache — mirror of
/root/reference/pkg/k8s/pod_listers.go and node_listers.go. A lister = a list source
plus a filter predicate; the controller builds one pair per nodegroup.

Round 12: with streaming ingestion primary (watch-event deltas feeding the
state store, controller/native_backend.py), the per-tick lister walk is
DEMOTED to bootstrap, the re-list audit, and object-level backends —
:func:`relist_group_inputs` is that reference path made explicit, shared by
the digest-parity tests/smoke/bench that hold the event-driven path to it."""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.client import KubernetesClient

PodFilterFunc = Callable[[k8s.Pod], bool]
NodeFilterFunc = Callable[[k8s.Node], bool]


class PodLister:
    def __init__(self, client: KubernetesClient, filter_func: PodFilterFunc):
        self._client = client
        self._filter = filter_func

    def list(self) -> List[k8s.Pod]:
        return [p for p in self._client.list_pods() if self._filter(p)]


class NodeLister:
    def __init__(self, client: KubernetesClient, filter_func: NodeFilterFunc):
        self._client = client
        self._filter = filter_func

    def list(self) -> List[k8s.Node]:
        return [n for n in self._client.list_nodes() if self._filter(n)]


def relist_group_inputs(
    client: KubernetesClient,
    filters: Sequence,                       # GroupFilters (k8s.cache)
    configs: Sequence,                       # semantics.GroupConfig per group
    states: Sequence,                        # semantics.GroupState per group
) -> List[Tuple[list, list, object, object]]:
    """The RE-LIST path, as one call: walk the client's full object world
    through each group's membership filters (first match wins — the same
    disjoint-selector semantics the WatchBridge applies per event, and the
    same Succeeded/Failed exclusion) and return backend-ready
    ``group_inputs``. O(groups x cluster) by construction — this is the
    cost the streaming path exists to avoid, kept as the ground truth the
    event-maintained store is digest-compared against (bootstrap, audit,
    parity suites)."""
    pods = [p for p in client.list_pods()
            if p.phase not in ("Succeeded", "Failed")]
    nodes = client.list_nodes()
    out: List[Tuple[list, list, object, object]] = []
    for gi, g in enumerate(filters):
        gpods = [p for p in pods
                 if g.pod_filter(p)
                 and not any(h.pod_filter(p) for h in filters[:gi])]
        gnodes = [n for n in nodes
                  if g.node_filter(n)
                  and not any(h.node_filter(n) for h in filters[:gi])]
        out.append((gpods, gnodes, configs[gi], states[gi]))
    return out


class FakeLister:
    """Error-injectable lister for tests (reference: pkg/test/node_lister.go:12-44)."""

    def __init__(self, items: Optional[list] = None, error: Optional[Exception] = None):
        self.items = items or []
        self.error = error

    def list(self) -> list:
        if self.error is not None:
            raise self.error
        return list(self.items)
