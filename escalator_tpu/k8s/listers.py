"""Filtered listers over the cluster cache — mirror of
/root/reference/pkg/k8s/pod_listers.go and node_listers.go. A lister = a list source
plus a filter predicate; the controller builds one pair per nodegroup."""

from __future__ import annotations

from typing import Callable, List, Optional

from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.client import KubernetesClient

PodFilterFunc = Callable[[k8s.Pod], bool]
NodeFilterFunc = Callable[[k8s.Node], bool]


class PodLister:
    def __init__(self, client: KubernetesClient, filter_func: PodFilterFunc):
        self._client = client
        self._filter = filter_func

    def list(self) -> List[k8s.Pod]:
        return [p for p in self._client.list_pods() if self._filter(p)]


class NodeLister:
    def __init__(self, client: KubernetesClient, filter_func: NodeFilterFunc):
        self._client = client
        self._filter = filter_func

    def list(self) -> List[k8s.Node]:
        return [n for n in self._client.list_nodes() if self._filter(n)]


class FakeLister:
    """Error-injectable lister for tests (reference: pkg/test/node_lister.go:12-44)."""

    def __init__(self, items: Optional[list] = None, error: Optional[Exception] = None):
        self.items = items or []
        self.error = error

    def list(self) -> list:
        if self.error is not None:
            raise self.error
        return list(self.items)
