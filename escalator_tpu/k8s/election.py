"""Leader election — active/passive HA, mirror of
/root/reference/pkg/k8s/election.go + cmd/main.go:157-185.

The reference elects over a k8s Lease object; deposition cancels a context and the
process crashes to restart (crash-to-restart HA). Here election runs over the
pluggable ``ResourceLock`` below; implementations:

- ``InMemoryResourceLock`` — single-process/testing
- ``FileResourceLock`` — lease in a file with atomic renew (multi-process on one host)
- a k8s Lease adapter plugs in when a real apiserver client is available.

``LeaderElector.run`` blocks until leadership, spawns a renew loop, and invokes
``on_deposed`` when the lease is lost — callers should treat that as fatal, like the
reference's ``awaitLeaderDeposed`` -> log.Fatal (cmd/main.go:147-154).
"""

from __future__ import annotations

import json
import os
import threading
import uuid
from dataclasses import dataclass
from typing import Callable, Optional, Protocol

from escalator_tpu.utils.clock import Clock


@dataclass
class LeaderRecord:
    holder: str
    acquire_time: float
    renew_time: float


class ResourceLock(Protocol):
    def get(self) -> Optional[LeaderRecord]:
        ...

    def create_or_update(self, record: LeaderRecord, expected_holder: Optional[str]) -> bool:
        """Compare-and-swap: write only when the current holder is exactly
        ``expected_holder`` (None = only when no record exists). Returns success."""
        ...


class InMemoryResourceLock:
    def __init__(self):
        self._lock = threading.Lock()
        self._record: Optional[LeaderRecord] = None

    def get(self) -> Optional[LeaderRecord]:
        with self._lock:
            return self._record

    def create_or_update(self, record, expected_holder) -> bool:
        with self._lock:
            current = self._record.holder if self._record else None
            if current != expected_holder:
                return False
            self._record = record
            return True


class FileResourceLock:
    """Lease in a JSON file. The read-check-write is serialized ACROSS PROCESSES with
    an fcntl advisory lock on a sidecar file (an in-process threading.Lock cannot
    prevent two processes from both winning), making this safe for single-host HA
    pairs. NOT a distributed lock across hosts without a shared filesystem that
    honors fcntl."""

    def __init__(self, path: str):
        self.path = path
        self._guard_path = f"{path}.lock"

    def _read(self) -> Optional[LeaderRecord]:
        try:
            with open(self.path) as f:
                data = json.load(f)
            return LeaderRecord(**data)
        except (OSError, ValueError, TypeError):
            return None

    def get(self) -> Optional[LeaderRecord]:
        return self._read()

    def create_or_update(self, record, expected_holder) -> bool:
        import fcntl

        with open(self._guard_path, "a+") as guard:
            fcntl.flock(guard, fcntl.LOCK_EX)
            try:
                current = self._read()
                holder = current.holder if current else None
                if holder != expected_holder:
                    return False
                # crash-consistent write (round 11, the ONE shared recipe —
                # utils.atomicio — also used by the flight recorder's dumps
                # and ops/snapshot.py): flush + fsync BEFORE the atomic
                # rename, so a host crash can never leave a zero-length or
                # half-written lease where a standby would read "no holder"
                # and split-brain past a live leader whose renewal simply
                # hadn't re-materialized yet
                from escalator_tpu.utils.atomicio import atomic_write

                atomic_write(self.path,
                             lambda f: json.dump(record.__dict__, f),
                             mode="w")
                return True
            finally:
                fcntl.flock(guard, fcntl.LOCK_UN)


@dataclass
class LeaderElectionConfig:
    """Mirrors the reference's flags (cmd/main.go:39-45): lease duration, renew
    deadline, retry period."""

    lease_duration_sec: float = 15.0
    renew_deadline_sec: float = 10.0
    retry_period_sec: float = 2.0


class LeaderElector:
    def __init__(
        self,
        lock: ResourceLock,
        config: LeaderElectionConfig,
        identity: Optional[str] = None,
        clock: Optional[Clock] = None,
        on_started_leading: Optional[Callable[[], None]] = None,
        on_deposed: Optional[Callable[[], None]] = None,
    ):
        self.lock = lock
        self.config = config
        self.identity = identity or f"{os.getpid()}-{uuid.uuid4().hex[:8]}"
        self.clock = clock or Clock()
        self.on_started_leading = on_started_leading
        self.on_deposed = on_deposed
        self.is_leader = False
        self._stop = threading.Event()
        self._renew_thread: Optional[threading.Thread] = None

    # -- acquisition ----------------------------------------------------------
    def _try_acquire(self) -> bool:
        now = self.clock.now()
        current = self.lock.get()
        if current is not None and current.holder != self.identity:
            expired = now - current.renew_time > self.config.lease_duration_sec
            if not expired:
                return False
            # takeover of an expired lease: CAS on the stale holder
            return self.lock.create_or_update(
                LeaderRecord(self.identity, now, now), current.holder
            )
        expected = self.identity if current is not None else None
        return self.lock.create_or_update(
            LeaderRecord(self.identity, now, now), expected
        )

    def _renew_loop(self) -> None:
        """Renew every retry period; transient CAS failures are retried until the
        renew deadline expires (client-go semantics). Deposition is immediate only
        when another holder demonstrably owns the lease."""
        from escalator_tpu.chaos import CHAOS

        last_renew = self.clock.now()
        while not self._stop.wait(self.config.retry_period_sec):
            now = self.clock.now()
            try:
                # chaos: lease-loss-mid-tick — renewals fail while the tick
                # loop keeps running; after the renew deadline the elector
                # must depose (and the CLI's watcher crash-to-restart)
                CHAOS.inject("lease_renew")
                ok = self.lock.create_or_update(
                    LeaderRecord(self.identity, now, now), self.identity
                )
            except Exception:
                ok = False
            if ok:
                last_renew = now
                continue
            current = None
            try:
                current = self.lock.get()
            except Exception:
                pass
            usurped = current is not None and current.holder != self.identity
            if usurped or now - last_renew > self.config.renew_deadline_sec:
                self.is_leader = False
                if self.on_deposed is not None:
                    self.on_deposed()
                return

    def run(self, blocking_acquire_timeout: Optional[float] = None) -> bool:
        """Block until leadership (or timeout). On success starts the background
        renew loop and returns True."""
        deadline = (
            self.clock.now() + blocking_acquire_timeout
            if blocking_acquire_timeout is not None
            else None
        )
        while not self._stop.is_set():
            try:
                acquired = self._try_acquire()
            except Exception as e:
                # transient lock-backend failure (e.g. apiserver blip during a
                # rolling restart) must not crash a standby — treat as
                # not-acquired and retry next period
                import logging

                logging.getLogger("escalator_tpu.k8s.election").warning(
                    "lease acquisition attempt failed transiently: %s", e
                )
                acquired = False
            if acquired:
                self.is_leader = True
                if self.on_started_leading is not None:
                    self.on_started_leading()
                self._renew_thread = threading.Thread(
                    target=self._renew_loop, daemon=True
                )
                self._renew_thread.start()
                return True
            if deadline is not None and self.clock.now() >= deadline:
                return False
            self.clock.sleep(self.config.retry_period_sec)
        return False

    def stop(self) -> None:
        self._stop.set()
