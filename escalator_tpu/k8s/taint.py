"""Taint mechanics — the durable state channel of the autoscaler.

Mirror of /root/reference/pkg/k8s/taint.go: the taint *value* is the unix timestamp of
tainting, which is how grace-period progress survives controller restarts (the only
persistent state besides the leader lease — SURVEY.md §5 checkpoint/resume). Add and
delete re-GET the node before updating to avoid conflicts, like the reference."""

from __future__ import annotations


from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.client import KubernetesClient
from escalator_tpu.utils.clock import Clock

_default_clock = Clock()


def add_to_be_removed_taint(
    node: k8s.Node,
    client: KubernetesClient,
    taint_effect: str = "",
    clock: Clock = _default_clock,
) -> k8s.Node:
    """Add the autoscaler taint with value=now-unix (reference: taint.go:36-76)."""
    updated = client.get_node(node.name)
    if updated is None:
        raise RuntimeError(f"failed to get node {node.name}")

    for taint in updated.taints:
        if taint.key == k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY:
            return updated  # already tainted; don't re-add

    effect = taint_effect if taint_effect else k8s.TaintEffect.NO_SCHEDULE.value
    updated.taints.append(
        k8s.Taint(
            key=k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY,
            value=str(int(clock.now())),
            effect=effect,
        )
    )
    return client.update_node(updated)


def delete_to_be_removed_taint(
    node: k8s.Node, client: KubernetesClient
) -> k8s.Node:
    """Remove the autoscaler taint if present (reference: taint.go:105-130).
    Swap-remove like the reference (order not preserved)."""
    updated = client.get_node(node.name)
    if updated is None:
        raise RuntimeError(f"failed to get node {node.name}")

    for i, taint in enumerate(updated.taints):
        if taint.key == k8s.TO_BE_REMOVED_BY_AUTOSCALER_KEY:
            updated.taints[i] = updated.taints[-1]
            updated.taints.pop()
            return client.update_node(updated)
    return updated


def delete_nodes(nodes, client: KubernetesClient) -> None:
    """Reference: pkg/k8s/node.go:12-26."""
    for node in nodes:
        client.delete_node(node.name)
