"""Kubernetes object model (framework-local, no client-go / kubernetes package needed).

This is the data model the controller shell and the decision kernels share. It is a
deliberately small, typed mirror of the slices of the k8s API the reference consumes:

- pod resource-request semantics (reference: /root/reference/pkg/k8s/scheduler/types.go:72-89):
  sum of container requests, elementwise max against each init container, plus overhead.
- pod classification (reference: /root/reference/pkg/k8s/util.go:11-24): daemonset by
  owner-reference kind, static by `kubernetes.io/config.source=file` annotation.
- node taint scheme (reference: /root/reference/pkg/k8s/taint.go:15-32): key
  `atlassian.com/escalator`, value = tainting unix timestamp, effect NoSchedule default.

CPU is carried in milli-cores (int), memory in bytes (int) — the same canonical units the
reference's `resource.Quantity` usage boils down to (pkg/k8s/resource/quantity.go:7-17:
memory = BinarySI bytes, cpu = DecimalSI milli). `MilliValue()` of a memory quantity is
bytes*1000; where the reference's float64 math uses milli values we multiply by 1000 at
that call-site so rounding matches bit-for-bit.
"""

from __future__ import annotations

import dataclasses
import enum
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# Taint key the autoscaler uses to mark nodes for removal
# (reference: pkg/k8s/taint.go:29-32).
TO_BE_REMOVED_BY_AUTOSCALER_KEY = "atlassian.com/escalator"

# Annotation marking a node as never-delete (reference: pkg/controller/scale_down.go:15-20).
NODE_ESCALATOR_IGNORE_ANNOTATION = "atlassian.com/no-delete"

# Annotation marking a static (file-sourced) pod (reference: pkg/k8s/util.go:21-24).
STATIC_POD_ANNOTATION = "kubernetes.io/config.source"


class TaintEffect(str, enum.Enum):
    NO_SCHEDULE = "NoSchedule"
    NO_EXECUTE = "NoExecute"
    PREFER_NO_SCHEDULE = "PreferNoSchedule"


#: Valid taint effects (reference: pkg/k8s/taint.go:23-27).
TAINT_EFFECT_TYPES = {e.value for e in TaintEffect}


@dataclass
class Taint:
    key: str
    value: str = ""
    effect: str = TaintEffect.NO_SCHEDULE.value


@dataclass
class ResourceRequests:
    """Per-container resource requests. cpu in milli-cores, memory in bytes."""

    cpu_milli: int = 0
    mem_bytes: int = 0


class NodeSelectorOperator(str, enum.Enum):
    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"


@dataclass
class NodeSelectorRequirement:
    key: str
    operator: str = NodeSelectorOperator.IN.value
    values: Tuple[str, ...] = ()


@dataclass
class NodeSelectorTerm:
    match_expressions: Tuple[NodeSelectorRequirement, ...] = ()


@dataclass
class Affinity:
    """Only the slices of affinity the reference inspects
    (pkg/controller/node_group.go:206-275)."""

    node_affinity_required_terms: Optional[Tuple[NodeSelectorTerm, ...]] = None
    has_node_affinity: bool = False
    has_pod_affinity: bool = False
    has_pod_anti_affinity: bool = False


@dataclass
class Pod:
    name: str
    namespace: str = "default"
    node_name: str = ""  # "" = pending / unscheduled
    containers: List[ResourceRequests] = field(default_factory=list)
    init_containers: List[ResourceRequests] = field(default_factory=list)
    overhead: Optional[ResourceRequests] = None
    node_selector: Dict[str, str] = field(default_factory=dict)
    affinity: Optional[Affinity] = None
    owner_kind: str = ""  # e.g. "DaemonSet", "ReplicaSet"
    annotations: Dict[str, str] = field(default_factory=dict)
    # k8s phase; informer cache excludes Succeeded/Failed (pkg/k8s/cache.go:17)
    phase: str = "Running"


@dataclass
class Node:
    name: str
    creation_time_ns: int = 0  # unix nanoseconds
    cpu_allocatable_milli: int = 0
    mem_allocatable_bytes: int = 0
    labels: Dict[str, str] = field(default_factory=dict)
    annotations: Dict[str, str] = field(default_factory=dict)
    taints: List[Taint] = field(default_factory=list)
    unschedulable: bool = False  # cordoned
    provider_id: str = ""

    def copy(self) -> "Node":
        n = dataclasses.replace(self)
        n.labels = dict(self.labels)
        n.annotations = dict(self.annotations)
        n.taints = [dataclasses.replace(t) for t in self.taints]
        return n


@dataclass
class Event:
    """A k8s Event the autoscaler broadcasts on its actions — the analog of the
    reference's event broadcaster (/root/reference/cmd/main.go:166-170, which
    records election and scaling activity into the cluster's event stream).
    Field names follow core/v1 Event."""

    reason: str                 # machine-readable, e.g. "ScaleUpCloudProvider"
    message: str
    type: str = "Normal"        # "Normal" | "Warning"
    involved_kind: str = "NodeGroup"
    involved_name: str = ""
    namespace: str = "default"
    source: str = "escalator-tpu"
    timestamp_sec: int = 0      # event time, unix seconds
    count: int = 1


# ---------------------------------------------------------------------------
# Pod classification (reference: pkg/k8s/util.go:11-24)
# ---------------------------------------------------------------------------


def pod_is_daemonset(pod: Pod) -> bool:
    return pod.owner_kind == "DaemonSet"


def pod_is_static(pod: Pod) -> bool:
    return pod.annotations.get(STATIC_POD_ANNOTATION) == "file"


# ---------------------------------------------------------------------------
# Pod resource-request semantics (reference: pkg/k8s/scheduler/types.go:72-89)
# ---------------------------------------------------------------------------


def compute_pod_resource_request(pod: Pod) -> ResourceRequests:
    """Sum container requests, take elementwise max vs each init container, add overhead."""
    cpu = 0
    mem = 0
    for c in pod.containers:
        cpu += c.cpu_milli
        mem += c.mem_bytes
    for ic in pod.init_containers:
        cpu = max(cpu, ic.cpu_milli)
        mem = max(mem, ic.mem_bytes)
    if pod.overhead is not None:
        cpu += pod.overhead.cpu_milli
        mem += pod.overhead.mem_bytes
    return ResourceRequests(cpu_milli=cpu, mem_bytes=mem)


def calculate_pods_requests_total(pods: List[Pod]) -> Tuple[int, int]:
    """Total (mem_bytes, cpu_milli) requested across pods
    (reference: pkg/k8s/util.go:27-38)."""
    mem = 0
    cpu = 0
    for pod in pods:
        req = compute_pod_resource_request(pod)
        mem += req.mem_bytes
        cpu += req.cpu_milli
    return mem, cpu


def calculate_nodes_capacity_total(nodes: List[Node]) -> Tuple[int, int]:
    """Total allocatable (mem_bytes, cpu_milli) across nodes
    (reference: pkg/k8s/util.go:41-51)."""
    mem = 0
    cpu = 0
    for node in nodes:
        mem += node.mem_allocatable_bytes
        cpu += node.cpu_allocatable_milli
    return mem, cpu


# ---------------------------------------------------------------------------
# Taint inspection — pure parts (reference: pkg/k8s/taint.go:78-101)
# ---------------------------------------------------------------------------


def get_to_be_removed_taint(node: Node) -> Optional[Taint]:
    for taint in node.taints:
        if taint.key == TO_BE_REMOVED_BY_AUTOSCALER_KEY:
            return taint
    return None


def get_to_be_removed_time(node: Node) -> Optional[int]:
    """Unix seconds the node was tainted, or None. Raises ValueError on a
    malformed timestamp value (reference returns an error there,
    pkg/k8s/taint.go:91-101)."""
    taint = get_to_be_removed_taint(node)
    if taint is None:
        return None
    return int(taint.value)


# ---------------------------------------------------------------------------
# Node→pods map (reference: pkg/k8s/node_state.go:10-65)
# ---------------------------------------------------------------------------


def create_node_name_to_info_map(
    pods: List[Pod], nodes: List[Node]
) -> Dict[str, Tuple[Optional[Node], List[Pod]]]:
    """Buckets pods by spec.nodeName, attaches nodes, drops entries with no node."""
    info: Dict[str, Tuple[Optional[Node], List[Pod]]] = {}
    for pod in pods:
        entry = info.setdefault(pod.node_name, (None, []))
        entry[1].append(pod)
    for node in nodes:
        existing = info.get(node.name)
        if existing is None:
            info[node.name] = (node, [])
        else:
            info[node.name] = (node, existing[1])
    return {k: v for k, v in info.items() if v[0] is not None}


def node_pods_remaining(
    node: Node, info_map: Dict[str, Tuple[Optional[Node], List[Pod]]]
) -> Tuple[int, bool]:
    """Count of non-daemonset pods on the node; ok=False when the node is not
    in the map (reference: pkg/k8s/node_state.go:48-65)."""
    entry = info_map.get(node.name)
    if entry is None:
        return 0, False
    return sum(1 for p in entry[1] if not pod_is_daemonset(p)), True


def node_empty(node: Node, info_map: Dict[str, Tuple[Optional[Node], List[Pod]]]) -> bool:
    remaining, ok = node_pods_remaining(node, info_map)
    return ok and remaining == 0
