// Incremental cluster state store — the native host-side data path.
//
// SURVEY.md §7 calls out the host<->device path as a hard part: packing 100k pods
// from Python objects every tick is O(cluster) Python-loop work. This store keeps
// the kernel's structure-of-arrays resident in C++ and applies watch-style deltas
// (upsert/delete pod/node) in O(1) each; Python views the buffers zero-copy via
// numpy and hands them straight to jax.device_put. The reference has no equivalent
// component (its per-tick cost is the same O(cluster) Go loops at
// /root/reference/pkg/k8s/util.go:27-51, rebuilt every tick).
//
// Concurrency: single-writer (the ingest thread); readers must not overlap writes
// (the Python wrapper snapshots under its own lock). Slots are freelist-reused;
// `valid` masks dead lanes, so buffers never compact and views stay stable.
//
// C ABI only — consumed via ctypes (no pybind11 in this image).

#include <cstdint>
#include <cstring>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

struct PodColumns {
  std::vector<int32_t> group;
  std::vector<int64_t> cpu_milli;
  std::vector<int64_t> mem_bytes;
  std::vector<int32_t> node;
  std::vector<uint8_t> valid;

  // reserve() up to max first so later resize() within max NEVER reallocates —
  // exported buffer pointers (and the numpy views over them) stay stable for the
  // store's lifetime. Reserved-but-unused pages cost only virtual address space.
  void reserve_max(size_t max) {
    group.reserve(max);
    cpu_milli.reserve(max);
    mem_bytes.reserve(max);
    node.reserve(max);
    valid.reserve(max);
  }

  void resize(size_t n) {
    group.resize(n, 0);
    cpu_milli.resize(n, 0);
    mem_bytes.resize(n, 0);
    node.resize(n, -1);
    valid.resize(n, 0);
  }
};

struct NodeColumns {
  std::vector<int32_t> group;
  std::vector<int64_t> cpu_milli;
  std::vector<int64_t> mem_bytes;
  std::vector<int64_t> creation_ns;
  std::vector<uint8_t> tainted;
  std::vector<uint8_t> cordoned;
  std::vector<uint8_t> no_delete;
  std::vector<int64_t> taint_time_sec;
  std::vector<uint8_t> valid;

  void reserve_max(size_t max) {
    group.reserve(max);
    cpu_milli.reserve(max);
    mem_bytes.reserve(max);
    creation_ns.reserve(max);
    tainted.reserve(max);
    cordoned.reserve(max);
    no_delete.reserve(max);
    taint_time_sec.reserve(max);
    valid.reserve(max);
  }

  void resize(size_t n) {
    group.resize(n, 0);
    cpu_milli.resize(n, 0);
    mem_bytes.resize(n, 0);
    creation_ns.resize(n, 0);
    tainted.resize(n, 0);
    cordoned.resize(n, 0);
    no_delete.resize(n, 0);
    // matches escalator_tpu.core.arrays.NO_TAINT_TIME
    taint_time_sec.resize(n, INT64_C(-4611686018427387904));
    valid.resize(n, 0);
  }
};

struct Registry {
  std::unordered_map<std::string, int64_t> index;
  std::vector<int64_t> free_slots;
  int64_t capacity = 0;
  int64_t high_water = 0;  // one past the highest slot ever used

  // returns slot or -1 when full and key is new
  int64_t acquire(const std::string& key) {
    auto it = index.find(key);
    if (it != index.end()) return it->second;
    int64_t slot;
    if (!free_slots.empty()) {
      slot = free_slots.back();
      free_slots.pop_back();
    } else if (high_water < capacity) {
      slot = high_water++;
    } else {
      return -1;
    }
    index.emplace(key, slot);
    return slot;
  }

  int64_t release(const std::string& key) {
    auto it = index.find(key);
    if (it == index.end()) return -1;
    int64_t slot = it->second;
    index.erase(it);
    free_slots.push_back(slot);
    return slot;
  }

  int64_t lookup(const std::string& key) const {
    auto it = index.find(key);
    return it == index.end() ? -1 : it->second;
  }
};

// Per-tick dirty-slot set. Slots touched since the last drain, deduplicated via
// a per-slot epoch stamp (O(1) mark, no clearing pass between ticks). This is
// what makes the host->device path O(changes): the Python side drains the slot
// list, gathers just those lanes from the column views, and scatter-updates the
// device-resident arrays (ops/device_state.py).
struct DirtySet {
  std::vector<int64_t> slots;
  std::vector<uint64_t> epoch_of;
  uint64_t epoch = 1;

  void init(size_t max) { epoch_of.assign(max, 0); }

  void mark(int64_t slot) {
    if (epoch_of[static_cast<size_t>(slot)] != epoch) {
      epoch_of[static_cast<size_t>(slot)] = epoch;
      slots.push_back(slot);
    }
  }

  int64_t count() const { return static_cast<int64_t>(slots.size()); }

  int64_t drain(int64_t* out) {
    int64_t n = static_cast<int64_t>(slots.size());
    if (out != nullptr && n > 0) {
      std::memcpy(out, slots.data(), static_cast<size_t>(n) * sizeof(int64_t));
    }
    slots.clear();
    ++epoch;
    return n;
  }
};

}  // namespace

struct StateStore {
  PodColumns pods;
  NodeColumns nodes;
  Registry pod_reg;
  Registry node_reg;
  DirtySet pod_dirty;
  DirtySet node_dirty;
  int64_t max_pods = 0;
  int64_t max_nodes = 0;
};

extern "C" {

// max_* bound the store's lifetime growth; all columns reserve to max up front so
// exported pointers never move (grow beyond max fails instead of reallocating).
StateStore* ess_new(int64_t pod_capacity, int64_t node_capacity,
                    int64_t max_pods, int64_t max_nodes) {
  if (pod_capacity > max_pods || node_capacity > max_nodes) return nullptr;
  auto* s = new StateStore();
  s->max_pods = max_pods;
  s->max_nodes = max_nodes;
  s->pods.reserve_max(static_cast<size_t>(max_pods));
  s->nodes.reserve_max(static_cast<size_t>(max_nodes));
  s->pod_dirty.init(static_cast<size_t>(max_pods));
  s->node_dirty.init(static_cast<size_t>(max_nodes));
  s->pods.resize(static_cast<size_t>(pod_capacity));
  s->nodes.resize(static_cast<size_t>(node_capacity));
  s->pod_reg.capacity = pod_capacity;
  s->node_reg.capacity = node_capacity;
  return s;
}

void ess_free(StateStore* s) { delete s; }

int64_t ess_pod_capacity(StateStore* s) { return s->pod_reg.capacity; }
int64_t ess_node_capacity(StateStore* s) { return s->node_reg.capacity; }
int64_t ess_pod_count(StateStore* s) {
  return static_cast<int64_t>(s->pod_reg.index.size());
}
int64_t ess_node_count(StateStore* s) {
  return static_cast<int64_t>(s->node_reg.index.size());
}

// Grow capacity within the reserved maxima. Pointers stay valid (reserve_max
// guarantees no reallocation), but previously-created views don't see the new
// lanes — the Python wrapper bumps a generation counter and re-views.
// Returns 0 on success, -1 when the requested capacity exceeds the lifetime max.
int32_t ess_grow(StateStore* s, int64_t pod_capacity, int64_t node_capacity) {
  if (pod_capacity > s->max_pods || node_capacity > s->max_nodes) return -1;
  if (pod_capacity > s->pod_reg.capacity) {
    s->pods.resize(static_cast<size_t>(pod_capacity));
    s->pod_reg.capacity = pod_capacity;
  }
  if (node_capacity > s->node_reg.capacity) {
    s->nodes.resize(static_cast<size_t>(node_capacity));
    s->node_reg.capacity = node_capacity;
  }
  return 0;
}

int64_t ess_upsert_pod(StateStore* s, const char* uid, int32_t group,
                       int64_t cpu_milli, int64_t mem_bytes, int32_t node_slot) {
  int64_t slot = s->pod_reg.acquire(uid);
  if (slot < 0) return -1;
  s->pods.group[slot] = group;
  s->pods.cpu_milli[slot] = cpu_milli;
  s->pods.mem_bytes[slot] = mem_bytes;
  s->pods.node[slot] = node_slot;
  s->pods.valid[slot] = 1;
  s->pod_dirty.mark(slot);
  return slot;
}

int64_t ess_delete_pod(StateStore* s, const char* uid) {
  int64_t slot = s->pod_reg.release(uid);
  if (slot < 0) return -1;
  s->pods.valid[slot] = 0;
  s->pods.cpu_milli[slot] = 0;
  s->pods.mem_bytes[slot] = 0;
  s->pods.node[slot] = -1;
  s->pod_dirty.mark(slot);
  return slot;
}

int64_t ess_upsert_node(StateStore* s, const char* name, int32_t group,
                        int64_t cpu_milli, int64_t mem_bytes,
                        int64_t creation_ns, uint8_t tainted, uint8_t cordoned,
                        uint8_t no_delete, int64_t taint_time_sec) {
  int64_t slot = s->node_reg.acquire(name);
  if (slot < 0) return -1;
  s->nodes.group[slot] = group;
  s->nodes.cpu_milli[slot] = cpu_milli;
  s->nodes.mem_bytes[slot] = mem_bytes;
  s->nodes.creation_ns[slot] = creation_ns;
  s->nodes.tainted[slot] = tainted;
  s->nodes.cordoned[slot] = cordoned;
  s->nodes.no_delete[slot] = no_delete;
  s->nodes.taint_time_sec[slot] = taint_time_sec;
  s->nodes.valid[slot] = 1;
  s->node_dirty.mark(slot);
  return slot;
}

int64_t ess_delete_node(StateStore* s, const char* name) {
  int64_t slot = s->node_reg.release(name);
  if (slot < 0) return -1;
  s->nodes.valid[slot] = 0;
  s->node_dirty.mark(slot);
  return slot;
}

int64_t ess_node_slot(StateStore* s, const char* name) {
  return s->node_reg.lookup(name);
}

int64_t ess_pod_slot(StateStore* s, const char* uid) {
  return s->pod_reg.lookup(uid);
}

// Batched ingest, packed keys: one ctypes crossing per watch-delta batch,
// with the keys in ONE NUL-delimited buffer rather than a char* array — the
// ctypes marshaling of a per-string pointer array measured ~0.7 ms per 1000
// keys on the bench rig (more than the store work itself) vs ~0.15 ms for a
// single joined bytes object. Returns the number of entries applied; stops
// early (returning i) when a new key hits capacity, so the caller can grow
// and resume after skipping i keys in the buffer. The Python wrapper
// validates that keys contain no NUL (framing would desynchronize).
int64_t ess_upsert_pods_packed(StateStore* s, const char* uid_buf,
                               const int32_t* group, const int64_t* cpu_milli,
                               const int64_t* mem_bytes,
                               const int32_t* node_slot, int64_t n) {
  const char* p = uid_buf;
  for (int64_t i = 0; i < n; ++i) {
    size_t len = std::strlen(p);  // one scan: shared by the key and the advance
    int64_t slot = s->pod_reg.acquire(std::string(p, len));
    if (slot < 0) return i;
    s->pods.group[slot] = group[i];
    s->pods.cpu_milli[slot] = cpu_milli[i];
    s->pods.mem_bytes[slot] = mem_bytes[i];
    s->pods.node[slot] = node_slot[i];
    s->pods.valid[slot] = 1;
    s->pod_dirty.mark(slot);
    p += len + 1;
  }
  return n;
}

int64_t ess_upsert_nodes_packed(StateStore* s, const char* name_buf,
                                const int32_t* group, const int64_t* cpu_milli,
                                const int64_t* mem_bytes,
                                const int64_t* creation_ns,
                                const uint8_t* tainted, const uint8_t* cordoned,
                                const uint8_t* no_delete,
                                const int64_t* taint_time_sec, int64_t n) {
  const char* p = name_buf;
  for (int64_t i = 0; i < n; ++i) {
    size_t len = std::strlen(p);
    int64_t slot = s->node_reg.acquire(std::string(p, len));
    if (slot < 0) return i;
    s->nodes.group[slot] = group[i];
    s->nodes.cpu_milli[slot] = cpu_milli[i];
    s->nodes.mem_bytes[slot] = mem_bytes[i];
    s->nodes.creation_ns[slot] = creation_ns[i];
    s->nodes.tainted[slot] = tainted[i];
    s->nodes.cordoned[slot] = cordoned[i];
    s->nodes.no_delete[slot] = no_delete[i];
    s->nodes.taint_time_sec[slot] = taint_time_sec[i];
    s->nodes.valid[slot] = 1;
    s->node_dirty.mark(slot);
    p += len + 1;
  }
  return n;
}

// Dirty-slot tracking: count + drain (copies the deduplicated slot list into
// `out`, which must have room for the count, then resets for the next tick).
int64_t ess_pod_dirty_count(StateStore* s) { return s->pod_dirty.count(); }
int64_t ess_node_dirty_count(StateStore* s) { return s->node_dirty.count(); }
int64_t ess_drain_pod_dirty(StateStore* s, int64_t* out) {
  return s->pod_dirty.drain(out);
}
int64_t ess_drain_node_dirty(StateStore* s, int64_t* out) {
  return s->node_dirty.drain(out);
}

// Packed dirty drain (round 12): drain the deduplicated dirty-slot list AND
// gather each slot's column values into caller-provided buffers in the SAME
// crossing — the scatter-ready (idx, values) delta batch, padded to `bucket`
// lanes. Before this, a tick paid one crossing for the drain plus ~14 numpy
// fancy-indexing gathers in Python (ops/device_state._gather_padded); now the
// whole "diff/pack" of a steady tick is one C call. Pad lanes [n, bucket)
// point at the `scratch` lane and carry the scratch lane's invariant values
// (valid=0, node=-1, taint_time=NO_TAINT_TIME, zeros elsewhere) — exactly
// the _gather_padded contract, so duplicate-index scatter stays
// deterministic and the jit sees the same shapes/values either way.
// Returns the number of real (drained) lanes, or -1 when the dirty count
// exceeds `bucket` (caller bug: the wrapper sizes the bucket from the count
// under the store lock). The dirty set is NOT drained on -1.
int64_t ess_drain_pod_dirty_packed(StateStore* s, int32_t* out_idx,
                                   int32_t* group, int64_t* cpu_milli,
                                   int64_t* mem_bytes, int32_t* node,
                                   uint8_t* valid, int64_t bucket,
                                   int32_t scratch) {
  int64_t n = s->pod_dirty.count();
  if (n > bucket) return -1;
  const std::vector<int64_t>& slots = s->pod_dirty.slots;
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = slots[static_cast<size_t>(i)];
    out_idx[i] = static_cast<int32_t>(slot);
    group[i] = s->pods.group[slot];
    cpu_milli[i] = s->pods.cpu_milli[slot];
    mem_bytes[i] = s->pods.mem_bytes[slot];
    node[i] = s->pods.node[slot];
    valid[i] = s->pods.valid[slot];
  }
  for (int64_t i = n; i < bucket; ++i) {
    out_idx[i] = scratch;
    group[i] = 0;
    cpu_milli[i] = 0;
    mem_bytes[i] = 0;
    node[i] = -1;
    valid[i] = 0;
  }
  s->pod_dirty.drain(nullptr);
  return n;
}

int64_t ess_drain_node_dirty_packed(StateStore* s, int32_t* out_idx,
                                    int32_t* group, int64_t* cpu_milli,
                                    int64_t* mem_bytes, int64_t* creation_ns,
                                    uint8_t* tainted, uint8_t* cordoned,
                                    uint8_t* no_delete, int64_t* taint_time_sec,
                                    uint8_t* valid, int64_t bucket,
                                    int32_t scratch) {
  int64_t n = s->node_dirty.count();
  if (n > bucket) return -1;
  const std::vector<int64_t>& slots = s->node_dirty.slots;
  for (int64_t i = 0; i < n; ++i) {
    int64_t slot = slots[static_cast<size_t>(i)];
    out_idx[i] = static_cast<int32_t>(slot);
    group[i] = s->nodes.group[slot];
    cpu_milli[i] = s->nodes.cpu_milli[slot];
    mem_bytes[i] = s->nodes.mem_bytes[slot];
    creation_ns[i] = s->nodes.creation_ns[slot];
    tainted[i] = s->nodes.tainted[slot];
    cordoned[i] = s->nodes.cordoned[slot];
    no_delete[i] = s->nodes.no_delete[slot];
    taint_time_sec[i] = s->nodes.taint_time_sec[slot];
    valid[i] = s->nodes.valid[slot];
  }
  for (int64_t i = n; i < bucket; ++i) {
    out_idx[i] = scratch;
    group[i] = 0;
    cpu_milli[i] = 0;
    mem_bytes[i] = 0;
    creation_ns[i] = 0;
    tainted[i] = 0;
    cordoned[i] = 0;
    no_delete[i] = 0;
    taint_time_sec[i] = INT64_C(-4611686018427387904);
    valid[i] = 0;
  }
  s->node_dirty.drain(nullptr);
  return n;
}

// Buffer pointer exports, one per column. Field ids keep the ABI append-only.
void* ess_pod_buffer(StateStore* s, int32_t field) {
  switch (field) {
    case 0: return s->pods.group.data();
    case 1: return s->pods.cpu_milli.data();
    case 2: return s->pods.mem_bytes.data();
    case 3: return s->pods.node.data();
    case 4: return s->pods.valid.data();
    default: return nullptr;
  }
}

void* ess_node_buffer(StateStore* s, int32_t field) {
  switch (field) {
    case 0: return s->nodes.group.data();
    case 1: return s->nodes.cpu_milli.data();
    case 2: return s->nodes.mem_bytes.data();
    case 3: return s->nodes.creation_ns.data();
    case 4: return s->nodes.tainted.data();
    case 5: return s->nodes.cordoned.data();
    case 6: return s->nodes.no_delete.data();
    case 7: return s->nodes.taint_time_sec.data();
    case 8: return s->nodes.valid.data();
    default: return nullptr;
  }
}

}  // extern "C"
