"""ctypes wrapper over the C++ incremental state store (statestore.cpp).

Builds the shared library on first use (g++ available in this image; no pybind11
needed — C ABI + ctypes + zero-copy numpy views). Falls back gracefully: callers
check ``available()`` and use the pure-Python packer otherwise.

The store holds the kernel's pod/node columns; ``views()`` returns numpy arrays
aliasing the C++ buffers (no copy). Concurrency: the C++ side is single-writer;
``NativeStateStore.lock`` (an RLock) is the shared contract — every mutating
wrapper method acquires it, and readers that need a consistent multi-array
snapshot (the native backend's view->gather->scatter phase) hold it across the
whole read. The threaded soak test (tests/test_concurrency_soak.py) is the
``go test -race`` analog exercising this.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, Optional

import numpy as np

log = logging.getLogger("escalator_tpu.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "statestore.cpp")
_LIB = os.path.join(_HERE, "libessstate.so")

_build_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_failed = False
_build_failed_reason: Optional[str] = None

#: must match NO_TAINT_TIME in escalator_tpu.core.arrays
NO_TAINT_TIME = -(2**62)

_MIN_DELTA_BUCKET = 64


def delta_bucket(n: int) -> int:
    """Power-of-two delta-batch bucket (min 64) — THE padding policy shared
    by the stores' packed dirty drain and ``ops.device_state``'s host-side
    gather, so both paths hit the same compiled scatter shapes. Lives here
    (not in device_state) because the stores must stay importable without
    jax."""
    return max(_MIN_DELTA_BUCKET, 1 << (max(n, 1) - 1).bit_length())

_POD_FIELDS = [
    ("group", np.int32), ("cpu_milli", np.int64), ("mem_bytes", np.int64),
    ("node", np.int32), ("valid", np.uint8),
]
_NODE_FIELDS = [
    ("group", np.int32), ("cpu_milli", np.int64), ("mem_bytes", np.int64),
    ("creation_ns", np.int64), ("tainted", np.uint8), ("cordoned", np.uint8),
    ("no_delete", np.uint8), ("taint_time_sec", np.int64), ("valid", np.uint8),
]


def _note_build_failure(what: str, err: Exception, stderr: str = "") -> None:
    """Record WHY the native store is unavailable and say so ONCE at WARN —
    including the decision the process is taking (the pure-numpy fallback
    store), so a silently-degraded deployment is visible in the first page
    of logs instead of only as a latency anomaly. ``unavailable_reason()``
    exposes the same text to callers (capability-skipping tests, the
    backend's flight-record annotation)."""
    global _build_failed, _build_failed_reason
    _build_failed = True
    reason = f"{what}: {err}"
    if stderr:
        reason += f" | {stderr.strip()[:2000]}"
    _build_failed_reason = reason
    log.warning(
        "native statestore unavailable (%s); event-driven ingestion will "
        "use the pure-numpy fallback store (same semantics, host diff/pack "
        "runs in vectorized numpy instead of one C crossing)", reason)


def _build() -> Optional[ctypes.CDLL]:
    global _lib
    with _build_lock:
        if _lib is not None:
            return _lib
        if _build_failed:
            return None
        if not os.path.exists(_LIB) or (
            os.path.getmtime(_SRC) > os.path.getmtime(_LIB)
        ):
            cmd = [
                "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
                "-o", _LIB, _SRC,
            ]
            try:
                subprocess.run(cmd, check=True, capture_output=True, text=True)
            except (subprocess.CalledProcessError, OSError) as e:
                _note_build_failure(
                    "compile failed", e, getattr(e, "stderr", "") or "")
                return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            _note_build_failure("load failed", e)
            return None
        lib.ess_new.restype = ctypes.c_void_p
        lib.ess_new.argtypes = [
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ]
        lib.ess_free.argtypes = [ctypes.c_void_p]
        for fn in ("ess_pod_capacity", "ess_node_capacity", "ess_pod_count",
                   "ess_node_count"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.ess_grow.restype = ctypes.c_int32
        lib.ess_grow.argtypes = [ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64]
        lib.ess_upsert_pod.restype = ctypes.c_int64
        lib.ess_upsert_pod.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32,
        ]
        lib.ess_delete_pod.restype = ctypes.c_int64
        lib.ess_delete_pod.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ess_upsert_node.restype = ctypes.c_int64
        lib.ess_upsert_node.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int32, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_uint8, ctypes.c_uint8,
            ctypes.c_uint8, ctypes.c_int64,
        ]
        lib.ess_delete_node.restype = ctypes.c_int64
        lib.ess_delete_node.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ess_node_slot.restype = ctypes.c_int64
        lib.ess_node_slot.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ess_pod_slot.restype = ctypes.c_int64
        lib.ess_pod_slot.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.ess_pod_buffer.restype = ctypes.c_void_p
        lib.ess_pod_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        lib.ess_node_buffer.restype = ctypes.c_void_p
        lib.ess_node_buffer.argtypes = [ctypes.c_void_p, ctypes.c_int32]
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64ptr = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        # batch ingest, packed keys: one NUL-delimited bytes buffer — the
        # per-string c_char_p array marshal costs more than the store work
        lib.ess_upsert_pods_packed.restype = ctypes.c_int64
        lib.ess_upsert_pods_packed.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, i32p, i64ptr, i64ptr, i32p,
            ctypes.c_int64,
        ]
        lib.ess_upsert_nodes_packed.restype = ctypes.c_int64
        lib.ess_upsert_nodes_packed.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, i32p, i64ptr, i64ptr, i64ptr,
            u8p, u8p, u8p, i64ptr, ctypes.c_int64,
        ]
        for fn in ("ess_pod_dirty_count", "ess_node_dirty_count"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        for fn in ("ess_drain_pod_dirty", "ess_drain_node_dirty"):
            getattr(lib, fn).restype = ctypes.c_int64
            getattr(lib, fn).argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64)
            ]
        # packed dirty drain (round 12): drain + gather + pad in ONE crossing
        lib.ess_drain_pod_dirty_packed.restype = ctypes.c_int64
        lib.ess_drain_pod_dirty_packed.argtypes = [
            ctypes.c_void_p, i32p, i32p, i64ptr, i64ptr, i32p, u8p,
            ctypes.c_int64, ctypes.c_int32,
        ]
        lib.ess_drain_node_dirty_packed.restype = ctypes.c_int64
        lib.ess_drain_node_dirty_packed.argtypes = [
            ctypes.c_void_p, i32p, i32p, i64ptr, i64ptr, i64ptr, u8p, u8p,
            u8p, i64ptr, u8p, ctypes.c_int64, ctypes.c_int32,
        ]
        _lib = lib
        return lib


def available() -> bool:
    return _build() is not None


def unavailable_reason() -> Optional[str]:
    """Why :func:`available` is False (compiler error tail, load error) —
    None while the native store is (or may still prove) available. Probes
    the build on first call, same as ``available()``."""
    _build()
    return _build_failed_reason


class NativeStateStore:
    """Incremental SoA cluster state with zero-copy numpy views.

    Buffer pointers are stable for the store's lifetime (the C++ side reserves
    ``max_*`` capacity up front, so growth never reallocates). Growth DOES mean
    previously-created views are too short to see new lanes — check ``generation``
    and re-view when it changed. Views keep the store alive (they hold a reference),
    so dropping the store while views exist is safe.
    """

    def __init__(self, pod_capacity: int = 1 << 17, node_capacity: int = 1 << 15,
                 max_pods: int = 1 << 21, max_nodes: int = 1 << 18):
        lib = _build()
        if lib is None:
            raise RuntimeError("native statestore unavailable (build failed)")
        self._lib = lib
        self._ptr = lib.ess_new(pod_capacity, node_capacity, max_pods, max_nodes)
        if not self._ptr:
            raise MemoryError("ess_new failed (capacity > max?)")
        self.generation = 0
        # The C++ side is single-writer, readers-must-not-overlap-writes
        # (statestore.cpp header). This lock is that contract made concrete:
        # the ingest path (WatchBridge.apply) holds it per event, and the
        # backend holds it across its read phase (view -> gather -> scatter),
        # so a watch thread can never tear a tick's snapshot. RLock because
        # the batch upserts call grow() internally.
        self.lock = threading.RLock()

    def __del__(self):
        ptr = getattr(self, "_ptr", None)
        if ptr:
            self._lib.ess_free(ptr)
            self._ptr = None

    # -- capacities ----------------------------------------------------------
    @property
    def pod_capacity(self) -> int:
        return self._lib.ess_pod_capacity(self._ptr)

    @property
    def node_capacity(self) -> int:
        return self._lib.ess_node_capacity(self._ptr)

    @property
    def pod_count(self) -> int:
        return self._lib.ess_pod_count(self._ptr)

    @property
    def node_count(self) -> int:
        return self._lib.ess_node_count(self._ptr)

    def grow(self, pod_capacity: int, node_capacity: int) -> None:
        if self._lib.ess_grow(self._ptr, pod_capacity, node_capacity) != 0:
            raise MemoryError(
                f"grow({pod_capacity}, {node_capacity}) exceeds the store's"
                " lifetime max capacity"
            )
        self.generation += 1

    def _ensure_pod_capacity(self) -> None:
        if self.pod_count >= self.pod_capacity:
            self.grow(self.pod_capacity * 2, self.node_capacity)

    def _ensure_node_capacity(self) -> None:
        if self.node_count >= self.node_capacity:
            self.grow(self.pod_capacity, self.node_capacity * 2)

    # -- deltas --------------------------------------------------------------
    def upsert_pod(self, uid: str, group: int, cpu_milli: int, mem_bytes: int,
                   node_slot: int = -1) -> int:
        with self.lock:
            self._ensure_pod_capacity()
            slot = self._lib.ess_upsert_pod(
                self._ptr, uid.encode(), group, cpu_milli, mem_bytes, node_slot
            )
        if slot < 0:
            raise MemoryError("pod capacity exhausted")
        return slot

    def delete_pod(self, uid: str) -> int:
        with self.lock:
            return self._lib.ess_delete_pod(self._ptr, uid.encode())

    def upsert_node(self, name: str, group: int, cpu_milli: int, mem_bytes: int,
                    creation_ns: int = 0, tainted: bool = False,
                    cordoned: bool = False, no_delete: bool = False,
                    taint_time_sec: int = NO_TAINT_TIME) -> int:
        with self.lock:
            self._ensure_node_capacity()
            slot = self._lib.ess_upsert_node(
                self._ptr, name.encode(), group, cpu_milli, mem_bytes, creation_ns,
                int(tainted), int(cordoned), int(no_delete), taint_time_sec,
            )
        if slot < 0:
            raise MemoryError("node capacity exhausted")
        return slot

    def delete_node(self, name: str) -> int:
        with self.lock:
            return self._lib.ess_delete_node(self._ptr, name.encode())

    def upsert_pods_batch(self, uids, group, cpu_milli, mem_bytes,
                          node_slot=None) -> None:
        """Apply a batch of pod upserts in one native call (one ctypes crossing
        per tick's watch deltas instead of one per event).

        Keys cross the boundary as ONE NUL-delimited bytes buffer: marshaling
        a per-string ``c_char_p`` array measured ~0.7 ms per 1000 keys on the
        bench rig — more than the store work — vs ~0.15 ms for a single
        joined ``bytes``. A key containing NUL (impossible for k8s
        names/uids) raises ValueError — framing depends on it."""
        n = len(uids)
        if n == 0:
            return
        group = np.ascontiguousarray(group, np.int32)
        cpu_milli = np.ascontiguousarray(cpu_milli, np.int64)
        mem_bytes = np.ascontiguousarray(mem_bytes, np.int64)
        if node_slot is None:
            node_slot = np.full(n, -1, np.int32)
        node_slot = np.ascontiguousarray(node_slot, np.int32)
        for name, arr in (("group", group), ("cpu_milli", cpu_milli),
                          ("mem_bytes", mem_bytes), ("node_slot", node_slot)):
            if len(arr) != n:
                raise ValueError(f"{name} has length {len(arr)}, expected {n}")
        joined = "\0".join(uids)
        # one C-speed scan guards the framing: an embedded NUL in any key
        # would desynchronize the packed buffer (OOB walk on the C++ side)
        if joined.count("\0") != n - 1:
            raise ValueError("pod uid contains NUL")
        buf = (joined + "\0").encode()
        done = 0
        with self.lock:
            while done < n:
                applied = self._lib.ess_upsert_pods_packed(
                    self._ptr,
                    buf,
                    group[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    cpu_milli[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    mem_bytes[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                    node_slot[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    n - done,
                )
                done += applied
                if done < n:
                    # grow-and-resume (rare): skip the applied keys in the buffer
                    buf = ("\0".join(uids[done:]) + "\0").encode()
                    self.grow(self.pod_capacity * 2, self.node_capacity)

    def upsert_nodes_batch(self, names, group, cpu_milli, mem_bytes,
                           creation_ns=None, tainted=None, cordoned=None,
                           no_delete=None, taint_time_sec=None) -> None:
        n = len(names)
        if n == 0:
            return
        group = np.ascontiguousarray(group, np.int32)
        cpu_milli = np.ascontiguousarray(cpu_milli, np.int64)
        mem_bytes = np.ascontiguousarray(mem_bytes, np.int64)
        creation_ns = np.ascontiguousarray(
            creation_ns if creation_ns is not None else np.zeros(n), np.int64
        )
        u8 = lambda v: np.ascontiguousarray(
            v if v is not None else np.zeros(n), np.uint8
        )
        tainted, cordoned, no_delete = u8(tainted), u8(cordoned), u8(no_delete)
        taint_time_sec = np.ascontiguousarray(
            taint_time_sec
            if taint_time_sec is not None
            else np.full(n, NO_TAINT_TIME),
            np.int64,
        )
        for name, arr in (("group", group), ("cpu_milli", cpu_milli),
                          ("mem_bytes", mem_bytes), ("creation_ns", creation_ns),
                          ("tainted", tainted), ("cordoned", cordoned),
                          ("no_delete", no_delete),
                          ("taint_time_sec", taint_time_sec)):
            if len(arr) != n:
                raise ValueError(f"{name} has length {len(arr)}, expected {n}")
        joined = "\0".join(names)  # NUL guard: see upsert_pods_batch
        if joined.count("\0") != n - 1:
            raise ValueError("node name contains NUL")
        buf = (joined + "\0").encode()
        i64p = ctypes.POINTER(ctypes.c_int64)
        done = 0
        with self.lock:
            while done < n:
                applied = self._lib.ess_upsert_nodes_packed(
                    self._ptr,
                    buf,
                    group[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                    cpu_milli[done:].ctypes.data_as(i64p),
                    mem_bytes[done:].ctypes.data_as(i64p),
                    creation_ns[done:].ctypes.data_as(i64p),
                    tainted[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    cordoned[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    no_delete[done:].ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    taint_time_sec[done:].ctypes.data_as(i64p),
                    n - done,
                )
                done += applied
                if done < n:
                    buf = ("\0".join(names[done:]) + "\0").encode()
                    self.grow(self.pod_capacity, self.node_capacity * 2)

    def node_slot(self, name: str) -> int:
        return self._lib.ess_node_slot(self._ptr, name.encode())

    # -- dirty tracking ------------------------------------------------------
    @property
    def pod_dirty_count(self) -> int:
        return self._lib.ess_pod_dirty_count(self._ptr)

    @property
    def node_dirty_count(self) -> int:
        return self._lib.ess_node_dirty_count(self._ptr)

    def drain_dirty(self):
        """(pod_slots, node_slots) touched since the last drain, as int64 arrays.

        Deduplicated on the C++ side; draining resets the sets for the next tick.
        Feed these to ``ops.device_state.DeviceClusterCache.apply_dirty`` for the
        O(changes) host->device path.
        """
        i64p = ctypes.POINTER(ctypes.c_int64)

        def _drain(count, drain_fn):
            out = np.empty(max(count, 1), np.int64)
            n = drain_fn(self._ptr, out.ctypes.data_as(i64p))
            return out[:n]

        with self.lock:
            return (
                _drain(self.pod_dirty_count, self._lib.ess_drain_pod_dirty),
                _drain(self.node_dirty_count, self._lib.ess_drain_node_dirty),
            )

    def drain_dirty_packed(self):
        """Drain the dirty slots as a scatter-ready PACKED delta batch:
        ``(pod_idx, pod_vals, node_idx, node_vals)`` — int32 index vectors
        plus Pod/NodeArrays value batches, padded to the shared power-of-two
        bucket (:func:`delta_bucket`) with the scratch-lane convention of
        ``ops.device_state._gather_padded`` (pad idx = capacity, pad values =
        the never-valid scratch constants). One C crossing replaces the
        drain call plus ~14 numpy fancy-indexing gathers; the result feeds
        ``DeviceClusterCache.apply_gathered`` / ``IncrementalDecider.
        apply_gathered`` directly and is bit-identical to the
        drain+gather path (test-locked)."""
        from escalator_tpu.core.arrays import NodeArrays, PodArrays

        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        as32 = lambda a: a.ctypes.data_as(i32p)      # noqa: E731
        as64 = lambda a: a.ctypes.data_as(i64p)      # noqa: E731
        asu8 = lambda a: a.ctypes.data_as(u8p)       # noqa: E731
        with self.lock:
            pb = delta_bucket(self.pod_dirty_count)
            nb = delta_bucket(self.node_dirty_count)
            pidx = np.empty(pb, np.int32)
            pvals = PodArrays(
                group=np.empty(pb, np.int32),
                cpu_milli=np.empty(pb, np.int64),
                mem_bytes=np.empty(pb, np.int64),
                node=np.empty(pb, np.int32),
                valid=np.empty(pb, np.bool_),
            )
            n = self._lib.ess_drain_pod_dirty_packed(
                self._ptr, as32(pidx), as32(pvals.group), as64(pvals.cpu_milli),
                as64(pvals.mem_bytes), as32(pvals.node), asu8(pvals.valid),
                pb, self.pod_capacity,
            )
            if n < 0:  # pragma: no cover - bucket sized under the same lock
                raise RuntimeError("packed pod drain bucket undersized")
            nidx = np.empty(nb, np.int32)
            nvals = NodeArrays(
                group=np.empty(nb, np.int32),
                cpu_milli=np.empty(nb, np.int64),
                mem_bytes=np.empty(nb, np.int64),
                creation_ns=np.empty(nb, np.int64),
                tainted=np.empty(nb, np.bool_),
                cordoned=np.empty(nb, np.bool_),
                no_delete=np.empty(nb, np.bool_),
                taint_time_sec=np.empty(nb, np.int64),
                valid=np.empty(nb, np.bool_),
            )
            n = self._lib.ess_drain_node_dirty_packed(
                self._ptr, as32(nidx), as32(nvals.group), as64(nvals.cpu_milli),
                as64(nvals.mem_bytes), as64(nvals.creation_ns),
                asu8(nvals.tainted), asu8(nvals.cordoned),
                asu8(nvals.no_delete), as64(nvals.taint_time_sec),
                asu8(nvals.valid), nb, self.node_capacity,
            )
            if n < 0:  # pragma: no cover
                raise RuntimeError("packed node drain bucket undersized")
        return pidx, pvals, nidx, nvals

    def pod_slot(self, uid: str) -> int:
        return self._lib.ess_pod_slot(self._ptr, uid.encode())

    # -- views ---------------------------------------------------------------
    def _view(self, getter, field_id: int, dtype, count: int) -> np.ndarray:
        ptr = getter(self._ptr, field_id)
        buf = (ctypes.c_char * (count * np.dtype(dtype).itemsize)).from_address(ptr)
        # the ctypes buffer becomes the array's base; pinning the store on it keeps
        # the C++ allocation alive as long as any view exists
        buf._escalator_store = self
        return np.frombuffer(buf, dtype=dtype, count=count)

    def pod_views(self) -> Dict[str, np.ndarray]:
        n = self.pod_capacity
        return {
            name: self._view(self._lib.ess_pod_buffer, i, dt, n)
            for i, (name, dt) in enumerate(_POD_FIELDS)
        }

    def node_views(self) -> Dict[str, np.ndarray]:
        n = self.node_capacity
        return {
            name: self._view(self._lib.ess_node_buffer, i, dt, n)
            for i, (name, dt) in enumerate(_NODE_FIELDS)
        }

    def as_pod_node_arrays(self):
        """(PodArrays, NodeArrays) viewing the live buffers zero-copy. bool columns
        are reinterpreted views of the uint8 buffers."""
        from escalator_tpu.core.arrays import NodeArrays, PodArrays

        pv = self.pod_views()
        nv = self.node_views()
        pods = PodArrays(
            group=pv["group"],
            cpu_milli=pv["cpu_milli"],
            mem_bytes=pv["mem_bytes"],
            node=pv["node"],
            valid=pv["valid"].view(bool),
        )
        nodes = NodeArrays(
            group=nv["group"],
            cpu_milli=nv["cpu_milli"],
            mem_bytes=nv["mem_bytes"],
            creation_ns=nv["creation_ns"],
            tainted=nv["tainted"].view(bool),
            cordoned=nv["cordoned"].view(bool),
            no_delete=nv["no_delete"].view(bool),
            taint_time_sec=nv["taint_time_sec"],
            valid=nv["valid"].view(bool),
        )
        return pods, nodes


def make_state_store(pod_capacity: int = 1 << 17, node_capacity: int = 1 << 15,
                     max_pods: int = 1 << 21, max_nodes: int = 1 << 18,
                     kind: str = "auto"):
    """The streaming-ingestion store, wherever the process runs: the C++
    :class:`NativeStateStore` when the toolchain produced a library, else
    the API-identical :class:`~escalator_tpu.native.pystore.PyStateStore`
    (preallocated vectorized numpy — same slot/dirty/packed-drain
    semantics, test-locked bit parity). ``kind`` forces one ("native" /
    "numpy") for tests and benches that price both. The fallback decision
    is logged once at WARN by the build probe with the compiler error."""
    if kind not in ("auto", "native", "numpy"):
        raise ValueError(f"unknown state-store kind {kind!r}")
    if kind in ("auto", "native") and available():
        return NativeStateStore(pod_capacity=pod_capacity,
                                node_capacity=node_capacity,
                                max_pods=max_pods, max_nodes=max_nodes)
    if kind == "native":
        raise RuntimeError(
            f"native statestore unavailable ({unavailable_reason()})")
    from escalator_tpu.native.pystore import PyStateStore

    return PyStateStore(pod_capacity=pod_capacity,
                        node_capacity=node_capacity,
                        max_pods=max_pods, max_nodes=max_nodes)


def store_kind(store) -> str:
    """"native" | "numpy" — the flight-record annotation for which store
    backs an event-driven backend."""
    return "native" if isinstance(store, NativeStateStore) else "numpy"
