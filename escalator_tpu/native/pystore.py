"""Pure-numpy state store: the toolchain-free twin of statestore.cpp.

The streaming-ingestion tentpole (round 12) makes the watch-delta store the
PRIMARY per-tick feed, so it can no longer be optional on a host without a
C++ toolchain. This module is the API-identical fallback
``statestore.make_state_store`` returns when the native build is
unavailable: the same slot registry / freelist semantics, the same
epoch-stamped deduplicated dirty sets, the same zero-copy column views and
the same packed dirty drain — implemented over PREALLOCATED numpy columns
(allocated once at the lifetime maxima, exactly like the C++ side's
``reserve_max``, so views stay stable across growth) with fully vectorized
batch paths. Key→slot resolution is a hash-map walk in both stores; every
column write, dirty mark and drain gather here is a numpy bulk operation.

Bit parity with the native store is test-locked (tests/test_event_ingest_
parity.py drives both through identical mutation sequences and compares
columns, dirty order and packed-drain batches bitwise).
"""

from __future__ import annotations

import threading
from typing import Dict, List

import numpy as np

from escalator_tpu.native.statestore import (
    NO_TAINT_TIME,
    _NODE_FIELDS,
    _POD_FIELDS,
    delta_bucket,
)

_POD_DEFAULTS = {"node": -1}
_NODE_DEFAULTS = {"taint_time_sec": NO_TAINT_TIME}


class _Registry:
    """key -> slot with freelist reuse (statestore.cpp Registry semantics:
    freelist LIFO first, then high-water growth)."""

    __slots__ = ("index", "free", "capacity", "high_water")

    def __init__(self, capacity: int):
        self.index: Dict[str, int] = {}
        self.free: List[int] = []
        self.capacity = int(capacity)
        self.high_water = 0

    def acquire(self, key: str) -> int:
        slot = self.index.get(key)
        if slot is not None:
            return slot
        if self.free:
            slot = self.free.pop()
        elif self.high_water < self.capacity:
            slot = self.high_water
            self.high_water += 1
        else:
            return -1
        self.index[key] = slot
        return slot

    def release(self, key: str) -> int:
        slot = self.index.pop(key, None)
        if slot is None:
            return -1
        self.free.append(slot)
        return slot


class _DirtySet:
    """Insertion-ordered deduplicated dirty slots via per-slot epoch stamps
    (statestore.cpp DirtySet): O(1)/vectorized mark, no clearing pass."""

    __slots__ = ("epoch_of", "epoch", "chunks", "count")

    def __init__(self, max_slots: int):
        self.epoch_of = np.zeros(max_slots, np.uint64)
        self.epoch = np.uint64(1)
        self.chunks: List[np.ndarray] = []
        self.count = 0

    def mark(self, slots: np.ndarray) -> None:
        """Mark a batch (vectorized). Within-batch duplicates keep their
        FIRST occurrence's position, as the C++ per-event loop does."""
        if slots.size == 0:
            return
        if slots.size > 1:
            # first-occurrence order: unique returns sorted values with the
            # index of each value's first appearance
            _, first = np.unique(slots, return_index=True)
            slots = slots[np.sort(first)]
        fresh = slots[self.epoch_of[slots] != self.epoch]
        if fresh.size:
            self.epoch_of[fresh] = self.epoch
            self.chunks.append(fresh.astype(np.int64, copy=False))
            self.count += int(fresh.size)

    def drain(self) -> np.ndarray:
        out = (np.concatenate(self.chunks) if self.chunks
               else np.empty(0, np.int64))
        self.chunks = []
        self.count = 0
        self.epoch += np.uint64(1)
        return out


class PyStateStore:
    """Numpy twin of :class:`~escalator_tpu.native.statestore.
    NativeStateStore` — same public surface, same concurrency contract
    (``lock`` is the single-writer agreement the WatchBridge and the
    backends share), same generation counter on growth."""

    def __init__(self, pod_capacity: int = 1 << 17, node_capacity: int = 1 << 15,
                 max_pods: int = 1 << 21, max_nodes: int = 1 << 18):
        if pod_capacity > max_pods or node_capacity > max_nodes:
            raise MemoryError("ess_new failed (capacity > max?)")
        self._max_pods = int(max_pods)
        self._max_nodes = int(max_nodes)
        # preallocate at the lifetime maxima (the numpy analog of the C++
        # reserve_max): growth only moves the logical capacity, so views
        # (slices of these buffers) never relocate
        self._pod_cols = {
            name: np.full(self._max_pods, _POD_DEFAULTS.get(name, 0), dt)
            for name, dt in _POD_FIELDS
        }
        self._node_cols = {
            name: np.full(self._max_nodes, _NODE_DEFAULTS.get(name, 0), dt)
            for name, dt in _NODE_FIELDS
        }
        self._pod_reg = _Registry(pod_capacity)
        self._node_reg = _Registry(node_capacity)
        self._pod_dirty = _DirtySet(self._max_pods)
        self._node_dirty = _DirtySet(self._max_nodes)
        self.generation = 0
        self.lock = threading.RLock()

    # -- capacities ----------------------------------------------------------
    @property
    def pod_capacity(self) -> int:
        return self._pod_reg.capacity

    @property
    def node_capacity(self) -> int:
        return self._node_reg.capacity

    @property
    def pod_count(self) -> int:
        return len(self._pod_reg.index)

    @property
    def node_count(self) -> int:
        return len(self._node_reg.index)

    def grow(self, pod_capacity: int, node_capacity: int) -> None:
        if pod_capacity > self._max_pods or node_capacity > self._max_nodes:
            raise MemoryError(
                f"grow({pod_capacity}, {node_capacity}) exceeds the store's"
                " lifetime max capacity"
            )
        self._pod_reg.capacity = max(self._pod_reg.capacity, int(pod_capacity))
        self._node_reg.capacity = max(self._node_reg.capacity,
                                      int(node_capacity))
        self.generation += 1

    def _ensure_pod_capacity(self) -> None:
        if self.pod_count >= self.pod_capacity:
            self.grow(self.pod_capacity * 2, self.node_capacity)

    def _ensure_node_capacity(self) -> None:
        if self.node_count >= self.node_capacity:
            self.grow(self.pod_capacity, self.node_capacity * 2)

    # -- single-object deltas ------------------------------------------------
    def upsert_pod(self, uid: str, group: int, cpu_milli: int, mem_bytes: int,
                   node_slot: int = -1) -> int:
        with self.lock:
            self._ensure_pod_capacity()
            slot = self._pod_reg.acquire(uid)
            if slot < 0:
                raise MemoryError("pod capacity exhausted")
            c = self._pod_cols
            c["group"][slot] = group
            c["cpu_milli"][slot] = cpu_milli
            c["mem_bytes"][slot] = mem_bytes
            c["node"][slot] = node_slot
            c["valid"][slot] = 1
            self._pod_dirty.mark(np.array([slot]))
            return slot

    def delete_pod(self, uid: str) -> int:
        with self.lock:
            slot = self._pod_reg.release(uid)
            if slot < 0:
                return -1
            c = self._pod_cols
            c["valid"][slot] = 0
            c["cpu_milli"][slot] = 0
            c["mem_bytes"][slot] = 0
            c["node"][slot] = -1
            self._pod_dirty.mark(np.array([slot]))
            return slot

    def upsert_node(self, name: str, group: int, cpu_milli: int, mem_bytes: int,
                    creation_ns: int = 0, tainted: bool = False,
                    cordoned: bool = False, no_delete: bool = False,
                    taint_time_sec: int = NO_TAINT_TIME) -> int:
        with self.lock:
            self._ensure_node_capacity()
            slot = self._node_reg.acquire(name)
            if slot < 0:
                raise MemoryError("node capacity exhausted")
            c = self._node_cols
            c["group"][slot] = group
            c["cpu_milli"][slot] = cpu_milli
            c["mem_bytes"][slot] = mem_bytes
            c["creation_ns"][slot] = creation_ns
            c["tainted"][slot] = int(tainted)
            c["cordoned"][slot] = int(cordoned)
            c["no_delete"][slot] = int(no_delete)
            c["taint_time_sec"][slot] = taint_time_sec
            c["valid"][slot] = 1
            self._node_dirty.mark(np.array([slot]))
            return slot

    def delete_node(self, name: str) -> int:
        with self.lock:
            slot = self._node_reg.release(name)
            if slot < 0:
                return -1
            self._node_cols["valid"][slot] = 0
            self._node_dirty.mark(np.array([slot]))
            return slot

    def node_slot(self, name: str) -> int:
        slot = self._node_reg.index.get(name)
        return -1 if slot is None else slot

    def pod_slot(self, uid: str) -> int:
        slot = self._pod_reg.index.get(uid)
        return -1 if slot is None else slot

    # -- batch deltas --------------------------------------------------------
    def _acquire_batch(self, reg, keys, ensure) -> np.ndarray:
        slots = np.empty(len(keys), np.int64)
        acquire = reg.acquire
        for i, k in enumerate(keys):
            s = acquire(k)
            if s < 0:
                ensure()   # grow (raises past the lifetime max)
                s = acquire(k)
            slots[i] = s
        return slots

    def upsert_pods_batch(self, uids, group, cpu_milli, mem_bytes,
                          node_slot=None) -> None:
        n = len(uids)
        if n == 0:
            return
        if node_slot is None:
            node_slot = np.full(n, -1, np.int32)
        cols = {
            "group": np.asarray(group), "cpu_milli": np.asarray(cpu_milli),
            "mem_bytes": np.asarray(mem_bytes), "node": np.asarray(node_slot),
        }
        for name, arr in cols.items():
            if len(arr) != n:
                raise ValueError(f"{name} has length {len(arr)}, expected {n}")
        with self.lock:
            slots = self._acquire_batch(
                self._pod_reg, uids, self._ensure_pod_capacity)
            # numpy integer-array assignment applies in order: a duplicated
            # uid's LAST row wins, matching the C++ per-row loop
            for name, arr in cols.items():
                self._pod_cols[name][slots] = arr
            self._pod_cols["valid"][slots] = 1
            self._pod_dirty.mark(slots)

    def upsert_nodes_batch(self, names, group, cpu_milli, mem_bytes,
                           creation_ns=None, tainted=None, cordoned=None,
                           no_delete=None, taint_time_sec=None) -> None:
        n = len(names)
        if n == 0:
            return
        fill = lambda v, d: np.asarray(  # noqa: E731
            v if v is not None else np.full(n, d))
        cols = {
            "group": np.asarray(group), "cpu_milli": np.asarray(cpu_milli),
            "mem_bytes": np.asarray(mem_bytes),
            "creation_ns": fill(creation_ns, 0),
            "tainted": fill(tainted, 0), "cordoned": fill(cordoned, 0),
            "no_delete": fill(no_delete, 0),
            "taint_time_sec": fill(taint_time_sec, NO_TAINT_TIME),
        }
        for name, arr in cols.items():
            if len(arr) != n:
                raise ValueError(f"{name} has length {len(arr)}, expected {n}")
        with self.lock:
            slots = self._acquire_batch(
                self._node_reg, names, self._ensure_node_capacity)
            for name, arr in cols.items():
                self._node_cols[name][slots] = arr
            self._node_cols["valid"][slots] = 1
            self._node_dirty.mark(slots)

    # -- dirty tracking ------------------------------------------------------
    @property
    def pod_dirty_count(self) -> int:
        return self._pod_dirty.count

    @property
    def node_dirty_count(self) -> int:
        return self._node_dirty.count

    def drain_dirty(self):
        with self.lock:
            return self._pod_dirty.drain(), self._node_dirty.drain()

    def drain_dirty_packed(self):
        """Packed delta batch, bit-identical to
        :meth:`NativeStateStore.drain_dirty_packed` for the same state: one
        vectorized gather per column into bucket-padded buffers with the
        scratch-lane pad convention."""
        from escalator_tpu.core.arrays import NodeArrays, PodArrays

        def packed(dirty, cols, fields, defaults, scratch, cls):
            slots = dirty.drain()
            bucket = delta_bucket(slots.size)
            idx = np.full(bucket, scratch, np.int32)
            idx[:slots.size] = slots
            vals = {}
            for name, dt in fields:
                v = np.full(bucket, defaults.get(name, 0), dt)
                if slots.size:
                    v[:slots.size] = cols[name][slots]
                # flag columns cross as bool, as the live views do
                vals[name] = v.view(bool) if dt == np.uint8 else v
            return idx, cls(**vals)

        with self.lock:
            pidx, pvals = packed(
                self._pod_dirty, self._pod_cols, _POD_FIELDS, _POD_DEFAULTS,
                self.pod_capacity, PodArrays)
            nidx, nvals = packed(
                self._node_dirty, self._node_cols, _NODE_FIELDS,
                _NODE_DEFAULTS, self.node_capacity, NodeArrays)
        return pidx, pvals, nidx, nvals

    # -- views ---------------------------------------------------------------
    def pod_views(self) -> Dict[str, np.ndarray]:
        n = self.pod_capacity
        return {name: col[:n] for name, col in self._pod_cols.items()}

    def node_views(self) -> Dict[str, np.ndarray]:
        n = self.node_capacity
        return {name: col[:n] for name, col in self._node_cols.items()}

    def as_pod_node_arrays(self):
        """(PodArrays, NodeArrays) viewing the live buffers zero-copy —
        same contract as the native store (bool columns are views of the
        uint8 buffers)."""
        from escalator_tpu.core.arrays import NodeArrays, PodArrays

        pv = self.pod_views()
        nv = self.node_views()
        pods = PodArrays(
            group=pv["group"], cpu_milli=pv["cpu_milli"],
            mem_bytes=pv["mem_bytes"], node=pv["node"],
            valid=pv["valid"].view(bool),
        )
        nodes = NodeArrays(
            group=nv["group"], cpu_milli=nv["cpu_milli"],
            mem_bytes=nv["mem_bytes"], creation_ns=nv["creation_ns"],
            tainted=nv["tainted"].view(bool),
            cordoned=nv["cordoned"].view(bool),
            no_delete=nv["no_delete"].view(bool),
            taint_time_sec=nv["taint_time_sec"],
            valid=nv["valid"].view(bool),
        )
        return pods, nodes
