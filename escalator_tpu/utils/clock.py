"""Injectable clock — the framework's equivalent of the reference's mockable clock
(github.com/stephanos/clock, used at /root/reference/pkg/controller/scale_down.go:11)
so multi-tick and grace-period tests never sleep."""

from __future__ import annotations

import time as _time


class Clock:
    """Real time. Subclass/replace for tests."""

    def now(self) -> float:
        """Unix seconds (float)."""
        return _time.time()

    def sleep(self, seconds: float) -> None:
        _time.sleep(seconds)


class MockClock(Clock):
    """Deterministic, manually-advanced clock."""

    def __init__(self, start: float = 1_700_000_000.0):
        self._now = start

    def now(self) -> float:
        return self._now

    def sleep(self, seconds: float) -> None:
        self._now += seconds

    def advance(self, seconds: float) -> None:
        self._now += seconds
