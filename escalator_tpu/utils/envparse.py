"""Strict env-knob parsing: the ``parse_refresh_every`` discipline, shared.

The observability watchdogs (tail, memory) read a family of env knobs —
``ESCALATOR_TPU_TAIL_CAPTURE/TAIL_MIN_TICKS/TAIL_DUMP_INTERVAL_SEC`` and the
``ESCALATOR_TPU_MEMORY_*`` set — on the tick path. Before round 17 they ran
bare ``int(raw)``/``float(raw)`` with a silent fall-to-default on anything
else, so ``TAIL_MIN_TICKS=-5`` or ``MEMORY_SAMPLE_EVERY=0`` were accepted
without a word (the memory sampler silently clamped 0 to 1; a negative
min-ticks armed the watchdog on the very first tick). These parsers are the
shared strict core: they REJECT 0/negative/non-numeric values with a clear
:class:`ValueError` naming the knob, and support ``"off"`` only where the
knob documents it. Tick-path callers catch the error, WARN once per distinct
raw value (their config caches memoize on the raw strings) and run the
default — a typo must be loud, but it must never crash a tick.

``ops.device_state.parse_refresh_every`` predates this module and keeps its
own spelling (it is the fail-FAST form: backend construction raises); these
are the fail-SOFT siblings for knobs parsed after startup.
"""

from __future__ import annotations

from typing import Optional

__all__ = ["OFF_SPELLINGS", "parse_env_int", "parse_env_float"]

#: the documented disable spellings (``allow_off`` knobs only)
OFF_SPELLINGS = ("off", "false", "no", "none")


def _reject(source: str, value, want: str) -> ValueError:
    return ValueError(f"{source} must be {want}, got {value!r}")


def parse_env_int(value: Optional[str], source: str, *,
                  allow_off: bool = False,
                  minimum: int = 1) -> Optional[int]:
    """Strict integer knob: ``None``/blank returns None (caller applies its
    default), ``"off"`` returns 0 where ``allow_off`` (the knob's documented
    disable), anything else must parse as an int >= ``minimum`` or this
    raises ValueError naming the knob."""
    if value is None or not value.strip():
        return None
    text = value.strip().lower()
    if allow_off and text in OFF_SPELLINGS:
        return 0
    want = (f"an integer >= {minimum}"
            + (" or 'off'" if allow_off else ""))
    try:
        parsed = int(text)
    except ValueError:
        raise _reject(source, value, want) from None
    if parsed < minimum:
        raise _reject(source, value, want)
    return parsed


def parse_env_float(value: Optional[str], source: str, *,
                    allow_off: bool = False,
                    allow_zero: bool = False,
                    zero_is_off: bool = False) -> Optional[float]:
    """Strict float knob: ``None``/blank returns None (caller default),
    ``"off"`` (plus ``"0"`` when ``zero_is_off`` — the TAIL_CAPTURE
    contract) returns 0.0 where ``allow_off``. Anything else must parse as
    a float > 0 (>= 0 when ``allow_zero``) or this raises ValueError."""
    if value is None or not value.strip():
        return None
    text = value.strip().lower()
    if allow_off and text in OFF_SPELLINGS:
        return 0.0
    want = ("a number > 0" if not allow_zero else "a number >= 0")
    if allow_off:
        want += " or 'off'"
    try:
        parsed = float(text)
    except ValueError:
        raise _reject(source, value, want) from None
    if allow_off and zero_is_off and parsed == 0.0:
        return 0.0
    if parsed < 0 or (parsed == 0 and not allow_zero):
        raise _reject(source, value, want)
    return parsed
