"""Crash-consistent file writes: THE one copy of the tmp + flush + fsync +
atomic-rename recipe (round 11). Three durability-bearing writers share it —
the device-state snapshot (`ops/snapshot.py`), the flight recorder's
incident dumps, and the election lease (`k8s/election.py`) — so a fix to
the recipe (the directory fsync, tmp cleanup on failure) lands everywhere
at once instead of drifting per copy. Stdlib only: the observability layer
imports this and must stay jax-free and cheap.
"""

from __future__ import annotations

import os
import tempfile
from typing import Callable, IO


def atomic_write(path: str, write_fn: Callable[[IO], None],
                 mode: str = "wb") -> str:
    """Write ``path`` via a same-directory temp file: ``write_fn(f)`` fills
    it, then flush + fsync + atomic ``os.replace``. A crash (or SIGKILL, or
    power cut) at any instant leaves either the previous file or the new
    one — never a torn or zero-length artifact — and the temp file is
    unlinked on any write failure. The rename is followed by a best-effort
    directory fsync so it is durable, not just atomic (best-effort because
    a failure there still leaves a VALID file — at worst the previous one
    resurrects after a crash). Returns ``path``."""
    out_dir = os.path.dirname(os.path.abspath(path)) or "."
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".tmp.",
                               dir=out_dir)
    # mkstemp creates 0600; the pre-round-11 writers used plain open() and
    # produced umask-based modes (typically 0644). Restore that: a standby,
    # sidecar exporter, or artifact collector under a different uid must
    # keep reading the lease / dumps / snapshots after this refactor.
    cur_umask = os.umask(0)
    os.umask(cur_umask)
    try:
        os.fchmod(fd, 0o666 & ~cur_umask)
    except OSError:
        pass
    try:
        with os.fdopen(fd, mode) as f:
            write_fn(f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    try:
        dfd = os.open(out_dir, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass
    return path
