"""Profiling hooks around the device step.

The reference has no tracing beyond a per-run wall-time debug log
(/root/reference/pkg/controller/controller.go:448-449); SURVEY.md §5 calls for real
tracing in the rebuild. Two facilities:

- ``trace_ticks(dir, n)`` — capture the first ``n`` controller ticks as an XLA
  profiler trace (TensorBoard-loadable) via ``jax.profiler``.
- ``start_profiler_server(port)`` — live profiling endpoint for
  ``tensorboard --logdir`` remote capture.

Both are no-ops when unset, and degrade to warnings if the profiler is unavailable
on the platform.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Iterator, Optional

log = logging.getLogger("escalator_tpu.tracing")


class TickTracer:
    """Captures the first ``max_ticks`` ticks into an XLA profiler trace."""

    def __init__(self, trace_dir: Optional[str] = None, max_ticks: int = 5):
        self.trace_dir = trace_dir
        self.max_ticks = max_ticks
        self._remaining = max_ticks if trace_dir else 0
        self._active = False

    @contextlib.contextmanager
    def tick(self) -> Iterator[None]:
        if self._remaining <= 0:
            yield
            return
        try:
            import jax

            if not self._active:
                jax.profiler.start_trace(self.trace_dir)
                self._active = True
                log.info(
                    "profiler trace started -> %s (%d ticks)",
                    self.trace_dir, self._remaining,
                )
        except Exception as e:  # pragma: no cover - platform-dependent
            log.warning("could not start profiler trace: %s", e)
            self._remaining = 0
            yield
            return
        try:
            with jax.profiler.TraceAnnotation("escalator_tick"):
                yield
        finally:
            self._remaining -= 1
            if self._remaining <= 0:
                self.close()

    def close(self) -> None:
        """Flush an in-flight trace. Called automatically after max_ticks; call on
        shutdown (the CLI does) so --once runs and interrupts don't lose it."""
        if not self._active:
            return
        try:
            import jax

            jax.profiler.stop_trace()
            log.info("profiler trace written to %s", self.trace_dir)
        except Exception as e:  # pragma: no cover
            log.warning("could not stop profiler trace: %s", e)
        self._active = False
        self._remaining = 0


def start_profiler_server(port: int) -> None:
    """Expose the live-profiling gRPC endpoint (no-op on failure)."""
    try:
        import jax

        jax.profiler.start_server(port)
        log.info("jax profiler server on port %d", port)
    except Exception as e:  # pragma: no cover - platform-dependent
        log.warning("could not start profiler server: %s", e)
