"""Typed provider errors (reference: /root/reference/pkg/cloudprovider/types.go:7-15).
NodeNotInNodeGroup is FATAL to the controller run — it aborts the tick loop
(reference: pkg/controller/controller.go:386-393,435-443)."""

from __future__ import annotations


class NodeNotInNodeGroupError(Exception):
    def __init__(self, node_name: str, provider_id: str, node_group: str):
        self.node_name = node_name
        self.provider_id = provider_id
        self.node_group = node_group
        super().__init__(
            f"node {node_name} ({provider_id}) does not belong in node group"
            f" {node_group}"
        )
