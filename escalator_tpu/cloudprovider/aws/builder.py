"""AWS provider builder (reference: /root/reference/pkg/cloudprovider/aws/builder.go).
Requires an AWS SDK (boto3), which is not part of this image — the full ASG/fleet
implementation lives in aws.py and activates when an SDK (or injected fake) is
available."""

from __future__ import annotations


from escalator_tpu.cloudprovider import interface as cp


class AWSBuilder(cp.Builder):
    def __init__(self, node_groups, region: str = "", assume_role_arn: str = ""):
        self.node_groups = node_groups
        self.region = region
        self.assume_role_arn = assume_role_arn

    def build(self) -> cp.CloudProvider:
        from escalator_tpu.cloudprovider.aws.aws import AWSCloudProvider, make_clients

        autoscaling, ec2 = make_clients(self.region, self.assume_role_arn)
        provider = AWSCloudProvider(autoscaling, ec2)
        provider.register_node_groups(
            *[
                cp.NodeGroupConfig(
                    name=ng.name,
                    group_id=ng.cloud_provider_group_name,
                    aws=cp.AWSNodeGroupConfig(
                        launch_template_id=ng.aws.launch_template_id,
                        launch_template_version=ng.aws.launch_template_version,
                        fleet_instance_ready_timeout_sec=(
                            ng.aws.fleet_instance_ready_timeout_duration()
                        ),
                        lifecycle=ng.aws.lifecycle,
                        instance_type_overrides=tuple(
                            ng.aws.instance_type_overrides
                        ),
                        resource_tagging=ng.aws.resource_tagging,
                    ),
                )
                for ng in self.node_groups
            ]
        )
        return provider
