"""AWS (ASG/EC2) cloud provider — port of
/root/reference/pkg/cloudprovider/aws/aws.go to the boto3 dict API.

Clients are injected (``AWSCloudProvider(autoscaling, ec2)``), so the same code runs
against real boto3 clients (``make_clients``) or the dict-level fakes in
``escalator_tpu.testsupport.aws`` — the reference tests the same way with its
SDK-interface mocks (pkg/test/aws.go:12-96).

Capabilities mirrored 1:1:
- providerID codec ``aws:///<az>/<instance-id>`` (aws.go:39-45)
- RegisterNodeGroups = DescribeAutoScalingGroups + cache + optional ASG tagging
  (aws.go:76-117, 593-624); Refresh re-describes (aws.go:120-127)
- GetInstance via DescribeInstances for the registration-lag metric (aws.go:136-162)
- scale-up strategies: SetDesiredCapacity, or one-shot CreateFleet when a launch
  template is configured (aws.go:237, 350-362, 366-397): instant fleet,
  on-demand/spot lifecycle, min-target=all-or-nothing, subnet x instance-type
  override matrix from the ASG's VPCZoneIdentifier (aws.go:488-590)
- fleet instances polled at 1 Hz until running or timeout, attached in batches of 20,
  orphans terminated in batches of 1000 with a 3-strikes circuit breaker
  (aws.go:399-485, 627-656)
- scale-down TerminateInstanceInAutoScalingGroup with desired-capacity decrement and
  min-size guards (aws.go:268-305)
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional

from escalator_tpu.cloudprovider import interface as cp
from escalator_tpu.cloudprovider.errors import NodeNotInNodeGroupError
from escalator_tpu.k8s import types as k8s
from escalator_tpu.utils.clock import Clock

log = logging.getLogger("escalator_tpu.cloudprovider.aws")

PROVIDER_NAME = "aws"
LIFECYCLE_ON_DEMAND = "on-demand"
LIFECYCLE_SPOT = "spot"
#: AttachInstances API limit (aws.go:27-28)
ATTACH_BATCH_SIZE = 20
#: TerminateInstances API limit (aws.go:35-36)
TERMINATE_BATCH_SIZE = 1000
#: consecutive fleet-orphan-cleanup failures before hard exit (aws.go:33-34)
MAX_TERMINATE_INSTANCES_TRIES = 3
TAG_KEY = "k8s.io/atlassian-escalator/enabled"
TAG_VALUE = "true"


def instance_to_provider_id(instance: Dict) -> str:
    return f"aws:///{instance['AvailabilityZone']}/{instance['InstanceId']}"


def provider_id_to_instance_id(provider_id: str) -> str:
    return provider_id.split("/")[4]


class FleetProvisioningFailure(RuntimeError):
    """Raised after MAX_TERMINATE_INSTANCES_TRIES consecutive CreateFleet failures —
    the reference log.Fatal's here (aws.go:650-655); we raise so the embedding
    process decides (the CLI exits)."""


class AWSInstance(cp.Instance):
    def __init__(self, instance_id: str, launch_time: float):
        self._id = instance_id
        self._launch_time = launch_time

    def instantiation_time(self) -> float:
        return self._launch_time

    def id(self) -> str:
        return self._id


class AWSNodeGroup(cp.NodeGroup):
    def __init__(self, config: cp.NodeGroupConfig, asg: Dict,
                 provider: "AWSCloudProvider"):
        self._id = config.group_id
        self._name = config.name
        self.asg = asg
        self.provider = provider
        self.config = config
        self.terminate_instances_tries = 0

    def __str__(self) -> str:
        return str(self.asg)

    def id(self) -> str:
        return self._id

    def name(self) -> str:
        return self._name

    def min_size(self) -> int:
        return int(self.asg["MinSize"])

    def max_size(self) -> int:
        return int(self.asg["MaxSize"])

    def target_size(self) -> int:
        return int(self.asg["DesiredCapacity"])

    def size(self) -> int:
        return len(self.asg.get("Instances", []))

    def can_scale_in_one_shot(self) -> bool:
        return bool(self.config.aws.launch_template_id)

    def increase_size(self, delta: int) -> None:
        if delta <= 0:
            raise ValueError("size increase must be positive")
        if self.target_size() + delta > self.max_size():
            raise RuntimeError("increasing size will breach maximum node size")
        if self.can_scale_in_one_shot():
            log.info("[asg %s] scaling with CreateFleet strategy", self._id)
            self._set_desired_size_one_shot(delta)
        else:
            log.info("[asg %s] scaling with SetDesiredCapacity strategy", self._id)
            self._set_desired_size(self.target_size() + delta)

    def delete_nodes(self, *nodes: k8s.Node) -> None:
        if self.target_size() <= self.min_size():
            raise RuntimeError("min sized reached, nodes will not be deleted")
        if self.target_size() - len(nodes) < self.min_size():
            raise RuntimeError("terminating nodes will breach minimum node size")
        for node in nodes:
            if not self.belongs(node):
                raise NodeNotInNodeGroupError(
                    node.name, node.provider_id, self._id
                )
            instance_id = None
            for instance in self.asg.get("Instances", []):
                if node.provider_id == instance_to_provider_id(instance):
                    instance_id = instance["InstanceId"]
                    break
            self.provider.service.terminate_instance_in_auto_scaling_group(
                InstanceId=instance_id,
                ShouldDecrementDesiredCapacity=True,
            )

    def belongs(self, node: k8s.Node) -> bool:
        return node.provider_id in self.nodes()

    def decrease_target_size(self, delta: int) -> None:
        if delta >= 0:
            raise ValueError("size decrease delta must be negative")
        if self.target_size() + delta < self.min_size():
            raise RuntimeError("decreasing target size will breach minimum node size")
        self._set_desired_size(self.target_size() + delta)

    def nodes(self) -> List[str]:
        return [
            instance_to_provider_id(i) for i in self.asg.get("Instances", [])
        ]

    # -- scaling internals ----------------------------------------------------
    def _set_desired_size(self, new_size: int) -> None:
        self.provider.service.set_desired_capacity(
            AutoScalingGroupName=self._id,
            DesiredCapacity=new_size,
            HonorCooldown=False,
        )

    def _set_desired_size_one_shot(self, add_count: int) -> None:
        fleet_input = create_fleet_input(self, add_count)
        fleet = self.provider.ec2_service.create_fleet(**fleet_input)
        instances: List[str] = []
        for i in fleet.get("Instances", []):
            instances.extend(i.get("InstanceIds", []))
        # errors may accompany a fully-successful instant fleet; only fatal when no
        # instances came back (aws.go:377-386)
        if not instances and fleet.get("Errors"):
            raise RuntimeError(fleet["Errors"][0]["ErrorMessage"])
        self._attach_instances_to_asg(instances)

    def _attach_instances_to_asg(self, instances: List[str]) -> None:
        clock = self.provider.clock
        deadline = clock.now() + self.config.aws.fleet_instance_ready_timeout_sec
        while not self._all_instances_ready(instances):
            if clock.now() >= deadline:
                log.info(
                    "reached instance ready deadline but not all instances ready"
                )
                self._terminate_orphaned_instances(instances)
                raise RuntimeError("Not all instances could be started")
            clock.sleep(1.0)

        remaining = list(instances)
        while remaining:
            batch, remaining = (
                remaining[:ATTACH_BATCH_SIZE],
                remaining[ATTACH_BATCH_SIZE:],
            )
            try:
                self.provider.service.attach_instances(
                    AutoScalingGroupName=self._id, InstanceIds=batch
                )
            except Exception:
                log.error("failed AttachInstances call")
                self._terminate_orphaned_instances(batch + remaining)
                raise
        self.terminate_instances_tries = 0

    def _all_instances_ready(self, ids: List[str]) -> bool:
        try:
            resp = self.provider.ec2_service.describe_instance_status(
                InstanceIds=ids, IncludeAllInstances=True
            )
        except Exception:
            return False
        statuses = resp.get("InstanceStatuses", [])
        if not statuses:
            return False
        return all(
            s.get("InstanceState", {}).get("Name") == "running" for s in statuses
        )

    def _terminate_orphaned_instances(self, instances: List[str]) -> None:
        if instances:
            log.info(
                "[asg %s] terminating %d instance(s) that could not be attached",
                self._id, len(instances),
            )
            for i in range(0, len(instances), TERMINATE_BATCH_SIZE):
                batch = instances[i : i + TERMINATE_BATCH_SIZE]
                try:
                    self.provider.ec2_service.terminate_instances(InstanceIds=batch)
                except Exception as e:
                    log.warning("failed to terminate instances %s", e)
            self.terminate_instances_tries += 1
            if self.terminate_instances_tries >= MAX_TERMINATE_INSTANCES_TRIES:
                raise FleetProvisioningFailure(
                    "reached maximum number of consecutive failures"
                    f" ({MAX_TERMINATE_INSTANCES_TRIES}) provisioning nodes with"
                    " CreateFleet"
                )


def create_fleet_input(n: AWSNodeGroup, add_count: int) -> Dict:
    """Reference: aws.go:488-545."""
    lifecycle = n.config.aws.lifecycle or LIFECYCLE_ON_DEMAND
    overrides = create_template_overrides(n)
    fleet_input: Dict = {
        "Type": "instant",
        "TerminateInstancesWithExpiration": False,
        "TargetCapacitySpecification": {
            "TotalTargetCapacity": add_count,
            "DefaultTargetCapacityType": lifecycle,
        },
        "LaunchTemplateConfigs": [
            {
                "LaunchTemplateSpecification": {
                    "LaunchTemplateId": n.config.aws.launch_template_id,
                    "Version": n.config.aws.launch_template_version,
                },
                "Overrides": overrides,
            }
        ],
    }
    options = {"MinTargetCapacity": add_count, "SingleInstanceType": True}
    if lifecycle == LIFECYCLE_ON_DEMAND:
        fleet_input["OnDemandOptions"] = options
    else:
        fleet_input["SpotOptions"] = options
    if n.config.aws.resource_tagging:
        fleet_input["TagSpecifications"] = [
            {
                "ResourceType": "fleet",
                "Tags": [{"Key": TAG_KEY, "Value": TAG_VALUE}],
            }
        ]
    return fleet_input


def create_template_overrides(n: AWSNodeGroup) -> List[Dict]:
    """Subnet x instance-type override matrix from the ASG's VPCZoneIdentifier
    (reference: aws.go:548-590)."""
    resp = n.provider.service.describe_auto_scaling_groups(
        AutoScalingGroupNames=[n.id()]
    )
    groups = resp.get("AutoScalingGroups", [])
    if not groups:
        raise RuntimeError(
            "failed to get an ASG from DescribeAutoscalingGroups response"
        )
    vpc_zone_identifier = groups[0].get("VPCZoneIdentifier", "")
    if not vpc_zone_identifier:
        raise RuntimeError(
            "failed to get any subnetIDs from DescribeAutoscalingGroups response"
        )
    subnet_ids = vpc_zone_identifier.split(",")
    instance_types = list(n.config.aws.instance_type_overrides)
    if instance_types:
        return [
            {"SubnetId": s, "InstanceType": t}
            for s in subnet_ids
            for t in instance_types
        ]
    return [{"SubnetId": s} for s in subnet_ids]


class AWSCloudProvider(cp.CloudProvider):
    def __init__(self, autoscaling_client, ec2_client, clock: Optional[Clock] = None):
        self.service = autoscaling_client
        self.ec2_service = ec2_client
        self.clock = clock or Clock()
        self._node_groups: Dict[str, AWSNodeGroup] = {}
        self._configs: List[cp.NodeGroupConfig] = []

    def name(self) -> str:
        return PROVIDER_NAME

    def node_groups(self) -> List[cp.NodeGroup]:
        return list(self._node_groups.values())

    def get_node_group(self, group_id: str) -> Optional[AWSNodeGroup]:
        return self._node_groups.get(group_id)

    def register_node_groups(self, *configs: cp.NodeGroupConfig) -> None:
        """Reference: aws.go:76-117."""
        if configs:
            self._configs = list(configs)
        ids = [c.group_id for c in self._configs]
        resp = self.service.describe_auto_scaling_groups(
            AutoScalingGroupNames=ids
        )
        found = {g["AutoScalingGroupName"]: g for g in resp.get("AutoScalingGroups", [])}
        for config in self._configs:
            asg = found.get(config.group_id)
            if asg is None:
                raise RuntimeError(
                    f"autoscaling group {config.group_id} not found on AWS"
                )
            existing = self._node_groups.get(config.group_id)
            if existing is not None:
                existing.asg = asg
            else:
                self._node_groups[config.group_id] = AWSNodeGroup(
                    config, asg, self
                )
            self._add_asg_tags(config, asg)

    def refresh(self) -> None:
        """Reference: aws.go:120-127."""
        self.register_node_groups()

    def get_instance(self, node: k8s.Node) -> AWSInstance:
        """Reference: aws.go:136-162."""
        instance_id = provider_id_to_instance_id(node.provider_id)
        resp = self.ec2_service.describe_instances(InstanceIds=[instance_id])
        for reservation in resp.get("Reservations", []):
            for instance in reservation.get("Instances", []):
                if instance.get("InstanceId") == instance_id:
                    launch = instance.get("LaunchTime", 0.0)
                    if hasattr(launch, "timestamp"):
                        launch = launch.timestamp()
                    return AWSInstance(instance_id, float(launch))
        raise RuntimeError(f"instance {instance_id} not found")

    def _add_asg_tags(self, config: cp.NodeGroupConfig, asg: Dict) -> None:
        """Reference: aws.go:593-624."""
        if not config.aws.resource_tagging:
            return
        for tag in asg.get("Tags", []):
            if tag.get("Key") == TAG_KEY:
                return
        name = asg["AutoScalingGroupName"]
        try:
            self.service.create_or_update_tags(
                Tags=[
                    {
                        "Key": TAG_KEY,
                        "PropagateAtLaunch": True,
                        "ResourceId": name,
                        "ResourceType": "auto-scaling-group",
                        "Value": TAG_VALUE,
                    }
                ]
            )
        except Exception as e:
            log.error("failed to create auto scaling tag for ASG %s: %s", name, e)


def make_clients(region: str = "", assume_role_arn: str = ""):
    """Real boto3 clients, with optional STS assume-role
    (reference: builder.go:24-64). Gated: boto3 is not part of this image."""
    try:
        import boto3
    except ImportError as e:  # pragma: no cover
        raise RuntimeError(
            "AWS provider requires boto3, which is not available in this"
            " environment; use the sim provider or inject fake clients"
        ) from e
    session_kwargs = {"region_name": region} if region else {}
    session = boto3.Session(**session_kwargs)
    if assume_role_arn:  # pragma: no cover - needs real AWS
        sts = session.client("sts")
        creds = sts.assume_role(
            RoleArn=assume_role_arn, RoleSessionName="escalator-tpu"
        )["Credentials"]
        session = boto3.Session(
            aws_access_key_id=creds["AccessKeyId"],
            aws_secret_access_key=creds["SecretAccessKey"],
            aws_session_token=creds["SessionToken"],
            **session_kwargs,
        )
    return session.client("autoscaling"), session.client("ec2")
