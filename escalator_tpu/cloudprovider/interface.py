"""Cloud provider SPI — mirror of the reference's provider abstraction
(/root/reference/pkg/cloudprovider/interface.go:12-121), re-typed for this framework's
object model. Implementations: in-memory mock (testsupport), AWS (gated on SDK
availability), and any future provider."""

from __future__ import annotations

import abc
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from escalator_tpu.k8s import types as k8s


class Instance(abc.ABC):
    """Cloud instance info (reference: interface.go:34-41)."""

    @abc.abstractmethod
    def instantiation_time(self) -> float:
        """Unix seconds the resource was instantiated."""

    @abc.abstractmethod
    def id(self) -> str:
        ...


class NodeGroup(abc.ABC):
    """A controllable set of homogeneous nodes (reference: interface.go:43-92)."""

    @abc.abstractmethod
    def id(self) -> str:
        ...

    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def min_size(self) -> int:
        ...

    @abc.abstractmethod
    def max_size(self) -> int:
        ...

    @abc.abstractmethod
    def target_size(self) -> int:
        ...

    @abc.abstractmethod
    def size(self) -> int:
        ...

    @abc.abstractmethod
    def increase_size(self, delta: int) -> None:
        ...

    @abc.abstractmethod
    def belongs(self, node: k8s.Node) -> bool:
        ...

    @abc.abstractmethod
    def delete_nodes(self, *nodes: k8s.Node) -> None:
        ...

    @abc.abstractmethod
    def decrease_target_size(self, delta: int) -> None:
        ...

    @abc.abstractmethod
    def nodes(self) -> List[str]:
        """Provider IDs of member nodes."""


class CloudProvider(abc.ABC):
    """Reference: interface.go:12-32."""

    @abc.abstractmethod
    def name(self) -> str:
        ...

    @abc.abstractmethod
    def node_groups(self) -> List[NodeGroup]:
        ...

    @abc.abstractmethod
    def get_node_group(self, group_id: str) -> Optional[NodeGroup]:
        ...

    @abc.abstractmethod
    def register_node_groups(self, *configs: "NodeGroupConfig") -> None:
        ...

    @abc.abstractmethod
    def refresh(self) -> None:
        """Called before every main loop tick."""

    @abc.abstractmethod
    def get_instance(self, node: k8s.Node) -> Instance:
        ...


class Builder(abc.ABC):
    """Reference: interface.go:94-97."""

    @abc.abstractmethod
    def build(self) -> CloudProvider:
        ...


@dataclass
class AWSNodeGroupConfig:
    """Reference: interface.go:112-121."""

    launch_template_id: str = ""
    launch_template_version: str = ""
    fleet_instance_ready_timeout_sec: float = 60.0
    lifecycle: str = ""
    instance_type_overrides: Tuple[str, ...] = ()
    resource_tagging: bool = False


@dataclass
class NodeGroupConfig:
    """Reference: interface.go:105-110."""

    name: str
    group_id: str
    aws: AWSNodeGroupConfig = field(default_factory=AWSNodeGroupConfig)


@dataclass
class BuildOpts:
    """Reference: interface.go:99-103."""

    provider_id: str = ""
    node_group_configs: List[NodeGroupConfig] = field(default_factory=list)
