"""Fleet simulation harness: multi-tick what-if runs with a synthetic cloud.

Drives the REAL controller (executors, locks, taints, reaper — everything) against
the in-memory cluster with a virtual clock and a cloud that fulfills provider
target changes after a configurable latency. This is the framework's
shadow-testing / capacity-planning tool: replay a workload timeline and read the
scaling behavior off the emitted per-tick records, without touching a cluster.

The reference has only single-tick dry-mode; multi-tick simulation is one of the
capabilities the dense decision core makes cheap (SURVEY.md §7 step 6).

Workload timeline YAML::

    events:
      - at_tick: 0
        add_pods: {count: 200, cpu_milli: 500, mem_bytes: 1000000000,
                   node_selector: {customer: buildeng}}
      - at_tick: 10
        finish_pods: {count: 150}     # oldest running pods complete

Usage::

    python -m escalator_tpu.sim --nodegroups ng.yaml --sim-state state.yaml \
        --ticks 30 --tick-interval 60 --node-ready-ticks 3 [--workload wl.yaml] \
        [--backend auto]

Emits one JSON line per tick: deltas, provider targets, node/pod counts, util.
"""

from __future__ import annotations

import argparse
import itertools
import json
import logging
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import yaml

from escalator_tpu.cli import load_sim_state, setup_node_groups
from escalator_tpu.controller import controller as ctl
from escalator_tpu.controller.backend import make_backend
from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.cache import EventfulClient
from escalator_tpu.testsupport.builders import NodeOpts, build_test_node
from escalator_tpu.testsupport.cloud_provider import MockBuilder, MockCloudProvider, MockNodeGroup
from escalator_tpu.utils.clock import MockClock

log = logging.getLogger("escalator_tpu.sim")

_uid = itertools.count()


@dataclass
class SyntheticCloud:
    """Brings provider target-size changes to life as registered nodes after a
    latency of ``node_ready_ticks`` ticks (models boot + registration lag)."""

    client: EventfulClient
    provider: MockCloudProvider
    group_labels: Dict[str, Dict[str, str]]  # provider group id -> node labels
    group_capacity: Dict[str, Dict[str, int]]  # id -> {cpu_milli, mem_bytes}
    node_ready_ticks: int = 2
    clock: Optional[MockClock] = None
    _pending: List = field(default_factory=list)  # (ready_at_tick, group_id)
    _tick: int = 0

    def observe(self) -> None:
        """Queue newly requested capacity (target > live+pending)."""
        for ng in self.provider.node_groups():
            gid = ng.id()
            live = sum(
                1 for n in self.client.list_nodes()
                if all(
                    n.labels.get(k) == v
                    for k, v in self.group_labels[gid].items()
                )
            )
            pending = sum(1 for _, g in self._pending if g == gid)
            missing = ng.target_size() - live - pending
            for _ in range(max(0, missing)):
                self._pending.append((self._tick + self.node_ready_ticks, gid))

    def deliver(self) -> None:
        ready = [(t, g) for t, g in self._pending if t <= self._tick]
        self._pending = [(t, g) for t, g in self._pending if t > self._tick]
        for _, gid in ready:
            cap = self.group_capacity[gid]
            node = build_test_node(NodeOpts(
                name=f"sim-node-{next(_uid)}",
                cpu=cap["cpu_milli"], mem=cap["mem_bytes"],
                creation_time_ns=int((self.clock.now() if self.clock else 0) * 1e9),
            ))
            node.labels = dict(self.group_labels[gid])
            self.client.add_node(node)

    def advance(self) -> None:
        self._tick += 1
        self.observe()
        self.deliver()


def apply_workload_event(client: EventfulClient, event: dict) -> None:
    add = event.get("add_pods")
    if add:
        for _ in range(int(add["count"])):
            client.add_pod(k8s.Pod(
                name=f"sim-pod-{next(_uid)}",
                containers=[k8s.ResourceRequests(
                    cpu_milli=int(add.get("cpu_milli", 0)),
                    mem_bytes=int(add.get("mem_bytes", 0)),
                )],
                node_selector=dict(add.get("node_selector", {})),
                node_name=add.get("node_name", ""),
            ))
    finish = event.get("finish_pods")
    if finish:
        count = int(finish["count"])
        for pod in client.list_pods()[:count]:
            client.remove_pod(pod)


def run_simulation(
    node_groups,
    client: EventfulClient,
    ticks: int,
    tick_interval_sec: float,
    node_ready_ticks: int,
    workload_events: Optional[List[dict]] = None,
    backend=None,
    sweep_candidates: int = 0,
) -> List[dict]:
    clock = MockClock()
    provider = MockCloudProvider()
    group_labels = {}
    group_capacity = {}
    for ng in node_groups:
        nodes = [
            n for n in client.list_nodes()
            if n.labels.get(ng.label_key) == ng.label_value
        ]
        cap = {
            "cpu_milli": nodes[0].cpu_allocatable_milli if nodes else 4000,
            "mem_bytes": nodes[0].mem_allocatable_bytes if nodes else 16 * 10**9,
        }
        gid = ng.cloud_provider_group_name
        group_labels[gid] = {ng.label_key: ng.label_value}
        group_capacity[gid] = cap
        provider.register_node_group(MockNodeGroup(
            gid, ng.name, min_size=ng.min_nodes,
            max_size=max(ng.max_nodes, len(nodes)), target_size=len(nodes),
        ))

    cloud = SyntheticCloud(
        client=client, provider=provider, group_labels=group_labels,
        group_capacity=group_capacity, node_ready_ticks=node_ready_ticks,
        clock=clock,
    )
    controller = ctl.Controller(ctl.Opts(
        client=client, node_groups=node_groups,
        cloud_provider_builder=MockBuilder(provider),
        backend=backend, clock=clock,
    ))

    by_tick: Dict[int, List[dict]] = {}
    for ev in workload_events or []:
        by_tick.setdefault(int(ev.get("at_tick", 0)), []).append(ev)

    timeline = []
    for tick in range(ticks):
        for ev in by_tick.get(tick, []):
            apply_workload_event(client, ev)
        controller.run_once()
        cloud.advance()

        nodes = client.list_nodes()
        record = {
            "tick": tick,
            "time": clock.now(),
            "pods": len(client.list_pods()),
            "nodes": len(nodes),
            "tainted": sum(
                1 for n in nodes if k8s.get_to_be_removed_taint(n) is not None
            ),
            "deltas": {
                name: st.scale_delta
                for name, st in controller.node_groups.items()
            },
            "provider_targets": {
                ng.name(): ng.target_size() for ng in provider.node_groups()
            },
        }
        timeline.append(record)
        clock.advance(tick_interval_sec)

    if sweep_candidates and timeline:
        # capacity-planning summary off the final state: for each group, the
        # minimal node delta whose post-delta utilisation clears the scale-up
        # threshold (ops/simulate — no reference equivalent)
        from escalator_tpu.core.arrays import pack_cluster
        from escalator_tpu.jaxconfig import ensure_responsive_accelerator
        from escalator_tpu.ops.simulate import sweep_deltas_jit

        # the sweep dispatches jax even when the tick backend was golden; a
        # wedged transport must degrade it to XLA-CPU, not hang every caller
        # of this library function (the guard no-ops for already-initialized
        # or cpu-pinned processes — jaxconfig fast paths)
        ensure_responsive_accelerator()

        gi, names = [], []
        for ng in node_groups:
            st = controller.node_groups[ng.name]
            gi.append((
                st.pod_lister.list(), st.node_lister.list(),
                st.opts.to_group_config(), st.kernel_state,
            ))
            names.append(ng.name)
        sweep = sweep_deltas_jit(
            pack_cluster(gi), num_candidates=sweep_candidates
        )
        timeline[-1]["sweep_min_feasible_delta"] = {
            name: int(sweep.min_feasible_delta[i])
            for i, name in enumerate(names)
        }
    return timeline


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(prog="escalator-tpu-sim")
    p.add_argument("--nodegroups", required=True)
    p.add_argument("--sim-state", required=True)
    p.add_argument("--workload", default="")
    p.add_argument("--ticks", type=int, default=30)
    p.add_argument("--tick-interval", type=float, default=60.0)
    p.add_argument("--node-ready-ticks", type=int, default=2)
    p.add_argument("--backend", default="golden",
                   choices=["auto", "jax", "sharded-jax", "grid-jax",
                            "podaxis-jax", "golden"])
    p.add_argument("--sweep-deltas", type=int, default=0,
                   help="after the run, report each group's minimal feasible"
                        " scale-up delta over this many candidates")
    p.add_argument("--loglevel", default="warn")
    args = p.parse_args(argv)
    logging.basicConfig(level=getattr(logging, args.loglevel.upper(), 30))

    node_groups = setup_node_groups(args.nodegroups)
    client = load_sim_state(args.sim_state)
    events = []
    if args.workload:
        with open(args.workload) as f:
            events = (yaml.safe_load(f) or {}).get("events", [])

    timeline = run_simulation(
        node_groups, client, args.ticks, args.tick_interval,
        args.node_ready_ticks, events, make_backend(args.backend),
        sweep_candidates=args.sweep_deltas,
    )
    for record in timeline:
        print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
