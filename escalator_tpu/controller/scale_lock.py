"""Scale lock: cooldown/hysteresis after asking the provider for nodes — mirror of
/root/reference/pkg/controller/scale_lock.go. Time-based: locked while
now - lock_time < minimum_lock_duration (= scale_up_cool_down_period), then
auto-unlocks on the next locked() check."""

from __future__ import annotations

from escalator_tpu.metrics import metrics
from escalator_tpu.utils.clock import Clock


class ScaleLock:
    def __init__(self, clock: Clock, minimum_lock_duration_sec: float,
                 nodegroup: str = ""):
        self._clock = clock
        self.minimum_lock_duration_sec = minimum_lock_duration_sec
        self.nodegroup = nodegroup
        self.is_locked = False
        self.requested_nodes = 0
        self.lock_time = -float("inf")

    def locked(self) -> bool:
        """Reference: scale_lock.go:22-29."""
        if self._clock.now() - self.lock_time < self.minimum_lock_duration_sec:
            metrics.node_group_scale_lock_check_was_locked.labels(
                self.nodegroup
            ).inc()
            return True
        self.unlock()
        return self.is_locked

    def lock(self, nodes: int) -> None:
        """Reference: scale_lock.go:32-42."""
        metrics.node_group_scale_lock.labels(self.nodegroup).inc()
        self.is_locked = True
        self.requested_nodes = nodes
        self.lock_time = self._clock.now()

    def unlock(self) -> None:
        """Reference: scale_lock.go:45-56. No-op when not locked."""
        if self.is_locked:
            duration = self._clock.now() - self.lock_time
            self.is_locked = False
            self.requested_nodes = 0
            metrics.node_group_scale_lock_duration.labels(self.nodegroup).observe(
                duration
            )
            metrics.node_group_scale_lock.labels(self.nodegroup).set(0.0)

    def time_until_minimum_unlock(self) -> float:
        """Reference: scale_lock.go:59-61."""
        return (self.lock_time + self.minimum_lock_duration_sec) - self._clock.now()

    def __str__(self) -> str:
        return (
            f"lock({self.locked()}): there are {self.requested_nodes} upcoming nodes"
            f" requested, {self.time_until_minimum_unlock():.0f}s before min cooldown."
        )
