"""Nodegroup configuration, validation, and pod/node filters — mirror of
/root/reference/pkg/controller/node_group.go.

Two deliberate fixes over the reference (CHANGELOG-worthy divergences, see SURVEY.md
§5 "known drift"):

1. The reference's ``HardDeleteGracePeriod`` yaml tag is mistakenly
   ``soft_delete_grace_period`` (node_group.go:40), silently dropping
   ``hard_delete_grace_period`` in YAML configs. Here the tag is correct.
2. The documented-but-phantom ``scale_up_cool_down_timeout`` option
   (docs/configuration/nodegroup.md:143-157 vs no code) is not replicated; only the
   real ``scale_up_cool_down_period`` exists.
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import IO, List, Sequence, Union

import yaml

from escalator_tpu.core import semantics
from escalator_tpu.k8s import types as k8s

# Nodegroup name handling pods with no selector (reference: node_group.go:15-16).
DEFAULT_NODE_GROUP = "default"


def parse_duration(s: str) -> float:
    """Parse a Go-style duration string ("300ms", "1.5h", "2h45m", "10s") to seconds.
    Returns 0.0 on parse failure, like the reference's lazy parsers
    (node_group.go:139-175 return 0 on error)."""
    if not s:
        return 0.0
    units = {"ns": 1e-9, "us": 1e-6, "µs": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0,
             "h": 3600.0}
    total = 0.0
    num = ""
    unit = ""
    any_part = False

    def flush() -> bool:
        nonlocal total, num, unit, any_part
        if not num or unit not in units:
            return False
        total += float(num) * units[unit]
        num, unit = "", ""
        any_part = True
        return True

    i = 0
    negative = False
    if s and s[0] in "+-":
        negative = s[0] == "-"
        i = 1
    while i < len(s):
        c = s[i]
        if c.isdigit() or c == ".":
            if unit:
                if not flush():
                    return 0.0
            num += c
        else:
            unit += c
        i += 1
    if not flush():
        return 0.0
    return -total if negative else total


@dataclass
class AWSNodeGroupOptions:
    """Reference: node_group.go:54-66."""

    launch_template_id: str = ""
    launch_template_version: str = ""
    fleet_instance_ready_timeout: str = ""
    lifecycle: str = ""
    instance_type_overrides: List[str] = field(default_factory=list)
    resource_tagging: bool = False

    def fleet_instance_ready_timeout_duration(self) -> float:
        """Defaults to 1 minute (reference: node_group.go:183-195)."""
        if not self.fleet_instance_ready_timeout:
            return 60.0
        return parse_duration(self.fleet_instance_ready_timeout)


@dataclass
class NodeGroupOptions:
    """Reference: node_group.go:20-52. Field names match the reference's yaml tags."""

    name: str = ""
    label_key: str = ""
    label_value: str = ""
    cloud_provider_group_name: str = ""
    min_nodes: int = 0
    max_nodes: int = 0
    dry_mode: bool = False
    taint_upper_capacity_threshold_percent: int = 0
    taint_lower_capacity_threshold_percent: int = 0
    scale_up_threshold_percent: int = 0
    slow_node_removal_rate: int = 0
    fast_node_removal_rate: int = 0
    soft_delete_grace_period: str = ""
    hard_delete_grace_period: str = ""
    scale_up_cool_down_period: str = ""
    taint_effect: str = ""
    #: scale-down victim ordering: "" / "oldest_first" (reference behavior) or
    #: "emptiest_first" (fewest non-daemonset pods first, ties oldest-first)
    scale_down_selection: str = ""
    #: replace the average-based scale-up delta with FFD bin-packing: the delta
    #: becomes "template nodes the pod overflow actually needs" — correct on
    #: heterogeneous nodes where the whole-group average is wrong (lifts the
    #: reference's documented single-instance-type assumption,
    #: docs/calculations.md:8)
    packing_aware: bool = False
    #: cap on virtual new nodes the packing pass may propose per tick
    packing_budget: int = 128
    aws: AWSNodeGroupOptions = field(default_factory=AWSNodeGroupOptions)

    def soft_delete_grace_period_duration(self) -> float:
        return parse_duration(self.soft_delete_grace_period)

    def hard_delete_grace_period_duration(self) -> float:
        return parse_duration(self.hard_delete_grace_period)

    def scale_up_cool_down_period_duration(self) -> float:
        return parse_duration(self.scale_up_cool_down_period)

    def auto_discover_min_max_node_options(self) -> bool:
        """min=max=0 => discover from the cloud provider
        (reference: node_group.go:177-180)."""
        return self.min_nodes == 0 and self.max_nodes == 0

    def to_group_config(self) -> semantics.GroupConfig:
        """Dense-kernel view of this config."""
        return semantics.GroupConfig(
            min_nodes=self.min_nodes,
            max_nodes=self.max_nodes,
            taint_lower_percent=self.taint_lower_capacity_threshold_percent,
            taint_upper_percent=self.taint_upper_capacity_threshold_percent,
            scale_up_percent=self.scale_up_threshold_percent,
            slow_removal_rate=self.slow_node_removal_rate,
            fast_removal_rate=self.fast_node_removal_rate,
            soft_delete_grace_sec=int(self.soft_delete_grace_period_duration()),
            hard_delete_grace_sec=int(self.hard_delete_grace_period_duration()),
            scale_down_selection=self.scale_down_selection or "oldest_first",
            packing_aware=self.packing_aware,
            packing_budget=self.packing_budget,
        )


def unmarshal_node_group_options(
    stream: Union[str, bytes, IO]
) -> List[NodeGroupOptions]:
    """Decode the ``node_groups:`` YAML/JSON document
    (reference: node_group.go:68-77; YAML is a JSON superset, so one parser)."""
    if isinstance(stream, (str, bytes)):
        stream = io.StringIO(
            stream.decode() if isinstance(stream, bytes) else stream
        )
    doc = yaml.safe_load(stream) or {}
    out: List[NodeGroupOptions] = []
    for entry in doc.get("node_groups", []) or []:
        aws_raw = entry.pop("aws", None) or {}
        known = {f for f in NodeGroupOptions.__dataclass_fields__ if f != "aws"}
        opts = NodeGroupOptions(
            **{key: value for key, value in entry.items() if key in known}
        )
        aws_known = set(AWSNodeGroupOptions.__dataclass_fields__)
        opts.aws = AWSNodeGroupOptions(
            **{key: value for key, value in aws_raw.items() if key in aws_known}
        )
        out.append(opts)
    return out


#: AWS lifecycle constants (reference: pkg/cloudprovider/aws/aws.go:24-26).
LIFECYCLE_ON_DEMAND = "on-demand"
LIFECYCLE_SPOT = "spot"


def _valid_aws_lifecycle(lifecycle: str) -> bool:
    return lifecycle in ("", LIFECYCLE_ON_DEMAND, LIFECYCLE_SPOT)


def _valid_taint_effect(effect: str) -> bool:
    return effect == "" or effect in k8s.TAINT_EFFECT_TYPES


def validate_node_group(ng: NodeGroupOptions) -> List[str]:
    """All the reference's validation checks (node_group.go:80-126). Returns a list
    of problems; empty means valid."""
    problems: List[str] = []

    def check(cond: bool, msg: str) -> None:
        if not cond:
            problems.append(msg)

    check(len(ng.name) > 0, "name cannot be empty")
    check(len(ng.label_key) > 0, "label_key cannot be empty")
    check(len(ng.label_value) > 0, "label_value cannot be empty")
    check(
        len(ng.cloud_provider_group_name) > 0,
        "cloud_provider_group_name cannot be empty",
    )

    check(
        ng.taint_upper_capacity_threshold_percent > 0,
        "taint_upper_capacity_threshold_percent must be larger than 0",
    )
    check(
        ng.taint_lower_capacity_threshold_percent > 0,
        "taint_lower_capacity_threshold_percent must be larger than 0",
    )
    check(
        ng.scale_up_threshold_percent > 0,
        "scale_up_threshold_percent must be larger than 0",
    )
    check(
        ng.taint_lower_capacity_threshold_percent
        < ng.taint_upper_capacity_threshold_percent,
        "taint_lower_capacity_threshold_percent must be less than "
        "taint_upper_capacity_threshold_percent",
    )
    check(
        ng.taint_upper_capacity_threshold_percent < ng.scale_up_threshold_percent,
        "taint_upper_capacity_threshold_percent must be less than "
        "scale_up_threshold_percent",
    )

    if not ng.auto_discover_min_max_node_options():
        check(ng.min_nodes < ng.max_nodes, "min_nodes must be less than max_nodes")
        check(ng.max_nodes > 0, "max_nodes must be larger than 0")
        check(ng.min_nodes >= 0, "min_nodes must be not less than 0")

    check(
        ng.slow_node_removal_rate <= ng.fast_node_removal_rate,
        "slow_node_removal_rate must be less than fast_node_removal_rate",
    )

    check(len(ng.soft_delete_grace_period) > 0,
          "soft_delete_grace_period must not be empty")
    check(len(ng.hard_delete_grace_period) > 0,
          "hard_delete_grace_period must not be empty")
    check(
        ng.soft_delete_grace_period_duration() > 0,
        "soft_delete_grace_period failed to parse into a duration",
    )
    check(
        ng.hard_delete_grace_period_duration() > 0,
        "hard_delete_grace_period failed to parse into a duration",
    )
    check(
        ng.soft_delete_grace_period_duration()
        < ng.hard_delete_grace_period_duration(),
        "soft_delete_grace_period must be less than hard_delete_grace_period",
    )

    check(len(ng.scale_up_cool_down_period) > 0,
          "scale_up_cool_down_period must not be empty")
    check(
        ng.scale_up_cool_down_period_duration() > 0,
        "scale_up_cool_down_period failed to parse into a duration",
    )

    check(_valid_taint_effect(ng.taint_effect),
          "taint_effect must be valid kubernetes taint")
    check(
        ng.scale_down_selection in ("", "oldest_first", "emptiest_first"),
        "scale_down_selection must be 'oldest_first' or 'emptiest_first'",
    )
    check(
        isinstance(ng.packing_budget, int) and 0 < ng.packing_budget <= 4096,
        "packing_budget must be in (0, 4096]",
    )
    check(
        _valid_aws_lifecycle(ng.aws.lifecycle),
        f"aws.lifecycle must be '{LIFECYCLE_ON_DEMAND}' or '{LIFECYCLE_SPOT}' "
        "if provided",
    )
    return problems


# ---------------------------------------------------------------------------
# Pod / node filters (reference: node_group.go:206-287)
# ---------------------------------------------------------------------------


def _node_selector_terms(pod: k8s.Pod) -> Sequence[k8s.NodeSelectorTerm]:
    if pod.affinity is not None and pod.affinity.node_affinity_required_terms:
        return pod.affinity.node_affinity_required_terms
    return ()


def new_pod_affinity_filter_func(label_key: str, label_value: str):
    """Non-daemonset pods that select this nodegroup via nodeSelector or a
    required node-affinity `In` expression (reference: node_group.go:218-253)."""

    def f(pod: k8s.Pod) -> bool:
        if k8s.pod_is_daemonset(pod):
            return False
        if pod.node_selector.get(label_key) == label_value:
            return True
        for term in _node_selector_terms(pod):
            for expr in term.match_expressions:
                if expr.key != label_key:
                    continue
                if expr.operator == k8s.NodeSelectorOperator.IN.value:
                    if label_value in expr.values:
                        return True
        return False

    return f


def new_pod_default_filter_func():
    """Pods for the `default` nodegroup: non-daemonset, non-static, no selector and
    no affinity of any kind (reference: node_group.go:256-275)."""

    def f(pod: k8s.Pod) -> bool:
        if k8s.pod_is_daemonset(pod):
            return False
        if k8s.pod_is_static(pod):
            return False
        if pod.node_selector:
            return False
        a = pod.affinity
        return a is None or (
            not a.has_node_affinity
            and not a.has_pod_affinity
            and not a.has_pod_anti_affinity
        )

    return f


def new_node_label_filter_func(label_key: str, label_value: str):
    """Reference: node_group.go:278-287."""

    def f(node: k8s.Node) -> bool:
        return node.labels.get(label_key) == label_value

    return f
