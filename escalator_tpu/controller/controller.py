"""The controller: tick loop + scale executors around the batched decision backend.

Mirror of /root/reference/pkg/controller/controller.go, scale_up.go, scale_down.go —
with one architectural change: instead of computing each nodegroup's decision inline
and serially (controller.go:416-445), ``run_once`` reads every group's listers, hands
the whole batch to a ``ComputeBackend`` (one device program for all groups), then
executes side effects per group. Nodegroups are disjoint by label selector, so
batching the pure decision phase is semantically equivalent to the reference's serial
loop; all cross-tick state (scale locks, cached capacity, dry-mode taint trackers —
controller.go:28-44) stays host-side in ``NodeGroupState``.
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from escalator_tpu import observability as obs
from escalator_tpu.cloudprovider import interface as cp
from escalator_tpu.cloudprovider.errors import NodeNotInNodeGroupError
from escalator_tpu.controller import node_group as ngmod
from escalator_tpu.controller.backend import ComputeBackend, GroupDecision, make_backend
from escalator_tpu.controller.scale_lock import ScaleLock
from escalator_tpu.core import semantics
from escalator_tpu.k8s import taint as taintlib
from escalator_tpu.k8s import types as k8s
from escalator_tpu.k8s.client import KubernetesClient
from escalator_tpu.k8s.listers import NodeLister, PodLister
from escalator_tpu.metrics import metrics
from escalator_tpu.utils.clock import Clock
from escalator_tpu.utils.tracing import TickTracer

log = logging.getLogger("escalator_tpu.controller")


@dataclass
class NodeGroupState:
    """Everything about one nodegroup (reference: controller.go:28-44)."""

    opts: ngmod.NodeGroupOptions
    pod_lister: PodLister
    node_lister: NodeLister
    scale_lock: ScaleLock
    # dry-mode in-memory taint tracking (controller.go:34-35)
    taint_tracker: List[str] = field(default_factory=list)
    scale_delta: int = 0
    last_scale_out: float = 0.0
    # cached instance capacity + lock view for the kernel
    kernel_state: semantics.GroupState = field(default_factory=semantics.GroupState)


@dataclass
class Opts:
    """Reference: controller.go:46-53."""

    client: KubernetesClient
    node_groups: List[ngmod.NodeGroupOptions]
    cloud_provider_builder: cp.Builder
    scan_interval_sec: float = 60.0
    dry_mode: bool = False
    backend: Optional[ComputeBackend] = None
    clock: Clock = field(default_factory=Clock)
    tracer: TickTracer = field(default_factory=TickTracer)


@dataclass
class _ScaleOpts:
    """Reference: controller.go:55-63."""

    nodes: List[k8s.Node]
    tainted_nodes: List[k8s.Node]
    untainted_nodes: List[k8s.Node]
    node_group: NodeGroupState
    nodes_delta: int = 0
    group_decision: Optional[GroupDecision] = None


def _build_listers(
    client: KubernetesClient, opts: ngmod.NodeGroupOptions
) -> Tuple[PodLister, NodeLister]:
    """Reference: node_group.go:290-303 — the `default` group uses the
    selector-less pod filter."""
    if opts.name == ngmod.DEFAULT_NODE_GROUP:
        pod_filter = ngmod.new_pod_default_filter_func()
    else:
        pod_filter = ngmod.new_pod_affinity_filter_func(opts.label_key, opts.label_value)
    node_filter = ngmod.new_node_label_filter_func(opts.label_key, opts.label_value)
    return PodLister(client, pod_filter), NodeLister(client, node_filter)


class Controller:
    """Reference: controller.go:19-117."""

    def __init__(self, opts: Opts, stop_event: Optional[threading.Event] = None):
        self.opts = opts
        self.client = opts.client
        self.clock = opts.clock
        self.stop_event = stop_event or threading.Event()
        #: clock time of the last completed tick, for /readyz freshness
        self.last_tick_completed_sec: Optional[float] = None
        self.backend = opts.backend or make_backend("auto")
        self.cloud_provider = opts.cloud_provider_builder.build()

        self.node_groups: Dict[str, NodeGroupState] = {}
        for ng_opts in opts.node_groups:
            cloud_ng = self.cloud_provider.get_node_group(
                ng_opts.cloud_provider_group_name
            )
            if cloud_ng is None:
                raise RuntimeError(
                    f'could not find node group "{ng_opts.cloud_provider_group_name}"'
                    " on cloud provider"
                )
            if ng_opts.auto_discover_min_max_node_options():
                ng_opts.min_nodes = cloud_ng.min_size()
                ng_opts.max_nodes = cloud_ng.max_size()
            pods, nodes = _build_listers(self.client, ng_opts)
            self.node_groups[ng_opts.name] = NodeGroupState(
                opts=ng_opts,
                pod_lister=pods,
                node_lister=nodes,
                scale_lock=ScaleLock(
                    self.clock,
                    ng_opts.scale_up_cool_down_period_duration(),
                    ng_opts.name,
                ),
            )

    # ------------------------------------------------------------------ dry mode
    def _dry_mode(self, state: NodeGroupState) -> bool:
        """Reference: controller.go:114-117."""
        return self.opts.dry_mode or state.opts.dry_mode

    # ------------------------------------------------------------------ events
    def _event(self, state: NodeGroupState, reason: str, message: str,
               type_: str = "Normal") -> None:
        """Broadcast a k8s Event for a scaling action (reference analog:
        cmd/main.go:166-170). Best-effort — a failing event sink must never
        break the control loop; dry mode records nothing (shadow runs leave no
        trace in the cluster, controller.go:126-138's contract)."""
        if self._dry_mode(state):
            return
        create = getattr(self.client, "create_event", None)
        if create is None:
            return
        try:
            create(k8s.Event(
                reason=reason,
                message=message,
                type=type_,
                involved_kind="NodeGroup",
                involved_name=state.opts.name,
                timestamp_sec=int(self.clock.now()),
            ))
        except Exception as e:  # pragma: no cover - sink failures are non-fatal
            log.warning("[%s] failed to record event %s: %s",
                        state.opts.name, reason, e)

    # ------------------------------------------------------------------ tick
    def run_once(self) -> None:
        """One tick over all nodegroups (reference: controller.go:400-451).

        The whole tick is one flight-recorder timeline (root span ``tick``):
        the controller's own phases (provider_refresh / group_scan / decide /
        act) plus whatever device phases the backend nests under ``decide``
        — so a dump reads as a single end-to-end per-tick trace."""
        from escalator_tpu.chaos import CHAOS

        with self.opts.tracer.tick(), obs.span("tick"):
            obs.annotate(backend=self.backend.name)
            # chaos: a wedged tick (site sleeps per its armed delay) — the
            # watchdog's crash-to-restart + flight dump is the remediation
            # under test; disarmed this is one attribute read
            CHAOS.should_fire("tick_wedge")
            self._run_once_inner()

    def _run_once_inner(self) -> None:
        start = self.clock.now()

        # Provider refresh with stale-credential retries (controller.go:403-414).
        with obs.span("provider_refresh"):
            try:
                self.cloud_provider.refresh()
            except Exception as first_err:
                err: Optional[Exception] = first_err
                for i in range(2):
                    log.warning(
                        "cloud provider failed to refresh; re-fetching"
                        " credentials (try %d): %s", i + 1, err,
                    )
                    self.clock.sleep(5)
                    self.cloud_provider = (
                        self.opts.cloud_provider_builder.build())
                    try:
                        self.cloud_provider.refresh()
                        err = None
                        break
                    except Exception as e:  # noqa: PERF203
                        err = e
                if err is not None:
                    # the retry loop already logged each failure; the implicit
                    # first_err context adds nothing (err may BE first_err)
                    raise err from None

        # Phase 1: per-group provider checks + lister reads (object level).
        with obs.span("group_scan"):
            batch = self._scan_groups()

        # Phase 2: one batched decision for all groups. The backend opens its
        # own named span under this one, so the flight record nests e.g.
        # tick/decide/native-jax/delta_decide.
        now_sec = int(self.clock.now())
        group_inputs = [
            (pods, nodes, st.opts.to_group_config(), st.kernel_state)
            for (_, st, pods, nodes) in batch
        ]
        with obs.span("decide"):
            decisions = self.backend.decide(
                group_inputs,
                now_sec,
                dry_mode_flags=[self._dry_mode(st) for (_, st, _, _) in batch],
                taint_trackers=[st.taint_tracker for (_, st, _, _) in batch],
            )
        # host/device overlap (round 10): an overlapped backend annotated the
        # timeline with the host work it hid under the in-flight decide; the
        # estimate lands root-level on the tick record (flight recorder) and
        # here on the per-backend Prometheus histogram
        tl = obs.current_timeline()
        saved_ms = (tl.meta.get("overlap_saved_ms")
                    if tl is not None else None)
        if saved_ms is not None:
            metrics.tick_overlap_saved.labels(self.backend.name).observe(
                float(saved_ms) / 1e3)

        # Phase 3: per-group side effects.
        with obs.span("act"):
            for (name, state, pods, nodes), gd in zip(
                    batch, decisions, strict=True):
                delta = self._act_on_decision(name, state, pods, nodes, gd)
                metrics.node_group_scale_delta.labels(name).set(delta)
                state.scale_delta = delta

        metrics.run_count.inc()
        self.last_tick_completed_sec = self.clock.now()
        log.debug("scaling took a total of %.3fs", self.clock.now() - start)

    def _scan_groups(
        self,
    ) -> List[Tuple[str, NodeGroupState, List[k8s.Pod], List[k8s.Node]]]:
        """Tick phase 1: provider size checks + lister reads per group."""
        batch: List[Tuple[str, NodeGroupState, List[k8s.Pod], List[k8s.Node]]] = []
        for ng_opts in self.opts.node_groups:
            state = self.node_groups[ng_opts.name]
            cloud_ng = self.cloud_provider.get_node_group(
                ng_opts.cloud_provider_group_name
            )
            if cloud_ng is None:
                raise RuntimeError("could not find node group")
            if ng_opts.auto_discover_min_max_node_options():
                state.opts.min_nodes = cloud_ng.min_size()
                state.opts.max_nodes = cloud_ng.max_size()
            metrics.cloud_provider_min_size.labels(
                self.cloud_provider.name(), cloud_ng.id(), ng_opts.name
            ).set(cloud_ng.min_size())
            metrics.cloud_provider_max_size.labels(
                self.cloud_provider.name(), cloud_ng.id(), ng_opts.name
            ).set(cloud_ng.max_size())
            metrics.cloud_provider_target_size.labels(
                self.cloud_provider.name(), cloud_ng.id(), ng_opts.name
            ).set(cloud_ng.target_size())
            metrics.cloud_provider_size.labels(
                self.cloud_provider.name(), cloud_ng.id(), ng_opts.name
            ).set(cloud_ng.size())

            if self.backend.needs_objects:
                try:
                    pods = state.pod_lister.list()
                    nodes = state.node_lister.list()
                except Exception as e:
                    log.error(
                        "failed to list pods/nodes for %s: %s", ng_opts.name, e
                    )
                    metrics.node_group_scale_delta.labels(ng_opts.name).set(0)
                    state.scale_delta = 0
                    continue
            else:
                # event-driven backend sources cluster state itself (O(changes)
                # ingestion instead of an O(cluster) walk per tick)
                pods, nodes = [], []
            # sync the kernel's view of the scale lock
            state.kernel_state.locked = state.scale_lock.locked()
            state.kernel_state.requested_nodes = state.scale_lock.requested_nodes
            batch.append((ng_opts.name, state, pods, nodes))
        return batch

    def run_forever(self, run_immediately: bool = False) -> None:
        """Reference: controller.go:455-480."""
        if run_immediately:
            self.run_once()
        while not self.stop_event.wait(self.opts.scan_interval_sec):
            self.run_once()

    # ------------------------------------------------------------------ decision
    def _act_on_decision(
        self,
        nodegroup: str,
        state: NodeGroupState,
        pods: List[k8s.Pod],
        nodes: List[k8s.Node],
        gd: GroupDecision,
    ) -> int:
        """Everything scaleNodeGroup does after the math
        (reference: controller.go:213-396). Returns the per-group delta the
        reference would return."""
        d = gd.decision
        # membership comes from the decision's ordered selections (identical sets
        # to filterNodes' partitions; ordering already applied by the backend)
        untainted = gd.scale_down_order
        tainted = gd.untaint_order

        metrics.node_group_nodes.labels(nodegroup).set(d.num_nodes)
        metrics.node_group_nodes_cordoned.labels(nodegroup).set(d.num_cordoned)
        metrics.node_group_nodes_untainted.labels(nodegroup).set(d.num_untainted)
        metrics.node_group_nodes_tainted.labels(nodegroup).set(d.num_tainted)
        metrics.node_group_pods.labels(nodegroup).set(d.num_pods)

        if d.status == semantics.DecisionStatus.NOOP_EMPTY:
            return 0
        if d.status == semantics.DecisionStatus.ERR_BELOW_MIN:
            log.warning(
                "[%s] node count %d less than minimum %d",
                nodegroup, d.num_nodes, state.opts.min_nodes,
            )
            return 0
        if d.status == semantics.DecisionStatus.ERR_ABOVE_MAX:
            log.warning(
                "[%s] node count %d larger than maximum %d",
                nodegroup, d.num_nodes, state.opts.max_nodes,
            )
            return 0

        metrics.node_group_cpu_request.labels(nodegroup).set(d.cpu_request_milli)
        metrics.node_group_cpu_capacity.labels(nodegroup).set(d.cpu_capacity_milli)
        metrics.node_group_mem_request.labels(nodegroup).set(d.mem_request_bytes)
        metrics.node_group_mem_capacity.labels(nodegroup).set(d.mem_capacity_bytes)

        scale_opts = _ScaleOpts(
            nodes=nodes,
            tainted_nodes=tainted,
            untainted_nodes=untainted,
            node_group=state,
            group_decision=gd,
        )

        if d.status == semantics.DecisionStatus.FORCED_MIN_SCALE_UP:
            log.warning("[%s] less untainted nodes than the minimum", nodegroup)
            scale_opts.nodes_delta = d.nodes_delta
            try:
                return self.scale_up(scale_opts)
            except NodeNotInNodeGroupError:
                raise
            except Exception as e:
                log.error("[%s] %s", nodegroup, e)
                return 0

        if d.status == semantics.DecisionStatus.ERR_DIV_ZERO:
            log.error("[%s] cannot divide by zero in percent calculation", nodegroup)
            return 0

        # percent metrics; scale-from-zero sentinel reported as 0
        # (controller.go:308-315)
        if d.cpu_percent == semantics.MAX_FLOAT64 or \
                d.mem_percent == semantics.MAX_FLOAT64:
            metrics.node_group_cpu_percent.labels(nodegroup).set(0)
            metrics.node_group_mem_percent.labels(nodegroup).set(0)
        else:
            metrics.node_group_cpu_percent.labels(nodegroup).set(d.cpu_percent)
            metrics.node_group_mem_percent.labels(nodegroup).set(d.mem_percent)

        if d.status == semantics.DecisionStatus.LOCKED:
            log.info("[%s] waiting for scale to finish", nodegroup)
            return state.scale_lock.requested_nodes

        self._calculate_new_node_metrics(
            nodegroup, state,
            nodes if nodes else untainted + tainted + gd.cordoned_nodes,
        )

        if d.status == semantics.DecisionStatus.ERR_NEG_DELTA:
            log.error("[%s] negative scale up delta", nodegroup)
            return 0

        nodes_delta = d.nodes_delta

        try:
            if nodes_delta < 0:
                scale_opts.nodes_delta = -nodes_delta
                self.scale_down(scale_opts)
            elif nodes_delta > 0:
                scale_opts.nodes_delta = nodes_delta
                self.scale_up(scale_opts)
                state.last_scale_out = self.clock.now()
            else:
                removed = self.try_remove_tainted_nodes(scale_opts)
                log.info("[%s] reaper: deleted %d empty nodes", nodegroup, -removed)
        except NodeNotInNodeGroupError:
            raise
        except Exception as e:
            log.error("[%s] %s", nodegroup, e)

        return nodes_delta

    def _calculate_new_node_metrics(
        self, nodegroup: str, state: NodeGroupState, nodes: List[k8s.Node]
    ) -> None:
        """Node registration lag histogram (reference: controller.go:157-189)."""
        if state.scale_delta <= 0:
            return
        count_new = 0
        for node in nodes:
            reg_time = node.creation_time_ns / 1e9
            if reg_time > state.last_scale_out:
                try:
                    instance = self.cloud_provider.get_instance(node)
                except Exception:
                    log.error(
                        "unable to get instance %s for registration lag",
                        node.provider_id,
                    )
                    continue
                lag = reg_time - instance.instantiation_time()
                metrics.node_group_node_registration_lag.labels(nodegroup).observe(
                    lag
                )
                count_new += 1
        if count_new != state.scale_delta:
            log.warning(
                "[%s] expected new nodes: %d actual: %d",
                nodegroup, state.scale_delta, count_new,
            )

    # ------------------------------------------------------------------ scale up
    def scale_up(self, opts: _ScaleOpts) -> int:
        """Untaint first, then grow the provider group
        (reference: scale_up.go:14-45)."""
        untainted = self._scale_up_untaint(opts)
        remaining = opts.nodes_delta - untainted
        if remaining > 0:
            added = self._scale_up_cloud_provider(opts, remaining)
            opts.node_group.scale_lock.lock(added)
            return untainted + added
        return untainted

    def _scale_up_cloud_provider(self, opts: _ScaleOpts, delta: int) -> int:
        """Reference: scale_up.go:48-95."""
        state = opts.node_group
        cloud_ng = self.cloud_provider.get_node_group(
            state.opts.cloud_provider_group_name
        )
        if cloud_ng is None:
            raise RuntimeError(
                "cloud provider node group does not exist:"
                f" {state.opts.cloud_provider_group_name}"
            )
        nodes_to_add = semantics.calculate_nodes_to_add(
            delta, cloud_ng.target_size(), cloud_ng.max_size()
        )
        if nodes_to_add <= 0:
            raise RuntimeError(
                "refusing to scale up beyond the maximum size of the autoscaling"
                f" group (TargetSize: {cloud_ng.target_size()};"
                f" MaxNodes: {state.opts.max_nodes}). Taking no action"
            )
        dry = self._dry_mode(state)
        log.info(
            "[%s] increasing cloud provider node group by %d (drymode=%s)",
            state.opts.name, nodes_to_add, dry,
        )
        if not dry:
            cloud_ng.increase_size(nodes_to_add)
            self._event(
                state, "ScaleUpCloudProvider",
                f"increased cloud provider node group {cloud_ng.id()} by"
                f" {nodes_to_add}",
            )
        return nodes_to_add

    def _scale_up_untaint(self, opts: _ScaleOpts) -> int:
        """Untaint the newest N tainted nodes (reference: scale_up.go:98-163).
        Uses the backend's precomputed newest-first order."""
        state = opts.node_group
        if not opts.tainted_nodes:
            log.warning("[%s] there are no tainted nodes to untaint", state.opts.name)
            return 0
        metrics.node_group_untaint_event.labels(state.opts.name).inc(
            opts.nodes_delta
        )
        order = (
            opts.group_decision.untaint_order
            if opts.group_decision is not None
            else [
                opts.tainted_nodes[i]
                for i in semantics.nodes_newest_first(opts.tainted_nodes)
            ]
        )
        untainted = 0
        for node in order:
            if untainted >= opts.nodes_delta:
                break
            if not self._dry_mode(state):
                if k8s.get_to_be_removed_taint(node) is None:
                    continue
                try:
                    taintlib.delete_to_be_removed_taint(node, self.client)
                except Exception as e:
                    log.error("failed to untaint %s: %s", node.name, e)
                    continue
                untainted += 1
            else:
                if node.name in state.taint_tracker:
                    state.taint_tracker.remove(node.name)
                    untainted += 1
        log.info("untainted a total of %d nodes", untainted)
        if untainted > 0:
            self._event(
                state, "ScaleUpUntaint",
                f"untainted {untainted} nodes (newest first)",
            )
        return untainted

    # ------------------------------------------------------------------ scale down
    def scale_down(self, opts: _ScaleOpts) -> int:
        """Reap then taint (reference: scale_down.go:23-37)."""
        try:
            removed = self.try_remove_tainted_nodes(opts)
            log.info("reaper: deleted %d empty nodes this round", -removed)
        except NodeNotInNodeGroupError:
            raise
        except Exception as e:
            log.warning("reaping nodes failed: %s", e)
        return self._scale_down_taint(opts)

    def try_remove_tainted_nodes(self, opts: _ScaleOpts) -> int:
        """Delete reap-eligible tainted nodes (reference: scale_down.go:51-136).
        Eligibility was computed in the decision batch (reap_nodes)."""
        state = opts.node_group
        if self._dry_mode(state):
            return 0
        gd = opts.group_decision
        to_delete = list(gd.reap_nodes) if gd is not None else []
        if not to_delete:
            return 0

        pods_remaining = sum(
            gd.node_pods_remaining.get(n.name, 0) for n in to_delete
        )
        cloud_ng = self.cloud_provider.get_node_group(
            state.opts.cloud_provider_group_name
        )
        if cloud_ng is None:
            raise RuntimeError(
                "cloud provider node group does not exist:"
                f" {state.opts.cloud_provider_group_name}"
            )
        cloud_ng.delete_nodes(*to_delete)
        taintlib.delete_nodes(to_delete, self.client)
        log.info("[%s] sent delete request to %d nodes", state.opts.name,
                 len(to_delete))
        metrics.node_group_pods_evicted.labels(state.opts.name).inc(pods_remaining)
        self._event(
            state, "DeleteNodes",
            f"deleted {len(to_delete)} expired tainted nodes"
            f" ({pods_remaining} pods evicted)",
        )
        return -len(to_delete)

    def _scale_down_taint(self, opts: _ScaleOpts) -> int:
        """Taint the oldest N untainted nodes with the min-clamp
        (reference: scale_down.go:138-205)."""
        state = opts.node_group
        try:
            nodes_to_remove = semantics.clamp_scale_down(
                len(opts.untainted_nodes), opts.nodes_delta, state.opts.min_nodes
            )
        except ValueError as exc:
            raise RuntimeError(
                f"the number of nodes ({len(opts.untainted_nodes)}) is less than"
                f" specified minimum of {state.opts.min_nodes}. Taking no action"
            ) from exc
        log.info("[%s] scaling down: tainting %d nodes", state.opts.name,
                 nodes_to_remove)
        metrics.node_group_taint_event.labels(state.opts.name).inc(nodes_to_remove)
        order = (
            opts.group_decision.scale_down_order
            if opts.group_decision is not None
            else [
                opts.untainted_nodes[i]
                for i in semantics.nodes_oldest_first(opts.untainted_nodes)
            ]
        )
        tainted = 0
        for node in order:
            if tainted >= nodes_to_remove:
                break
            if not self._dry_mode(state):
                try:
                    taintlib.add_to_be_removed_taint(
                        node, self.client, state.opts.taint_effect, self.clock
                    )
                except Exception as e:
                    log.error("while tainting %s: %s", node.name, e)
                    continue
                tainted += 1
            else:
                state.taint_tracker.append(node.name)
                tainted += 1
        log.info("[%s] tainted a total of %d nodes", state.opts.name, tainted)
        if tainted > 0:
            self._event(
                state, "ScaleDownTaint",
                f"tainted {tainted} nodes for removal",
            )
        return tainted
