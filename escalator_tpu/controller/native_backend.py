"""Event-driven native backend: O(changes) per tick instead of O(cluster).

``NativeJaxBackend`` subscribes a ``WatchBridge`` to the cluster's event stream at
construction; from then on the kernel's pod/node columns live in the C++ state store
and are always current. ``decide`` therefore needs NO object lists (the controller
skips its lister walk: ``needs_objects = False``) — it assembles the small ``[G]``
group arrays, device-puts the zero-copy column views, and runs the batched kernel.

Cross-tick host state remains in the controller's ``GroupState`` (locks, cached
capacity). Cached capacity is refreshed from the group's lowest-slot live node
(the reference uses the first lister-order node, controller.go:208-211 — both are
"an arbitrary node of the group"; documented divergence under slot reuse).

Dry-mode groups get a per-tick corrected view of the tainted column (the in-memory
taint tracker substitutes for real taints, and cordons are ignored), matching
filterNodes' dry-mode branch (controller.go:126-138) without mutating the store.
"""

from __future__ import annotations

import logging
import time
from typing import Dict, List, Sequence

import numpy as np

from escalator_tpu import observability as obs
from escalator_tpu.controller.backend import (
    ComputeBackend,
    GroupDecision,
    PackingPostPass,
    _annotate_decision,
    _round_up,
)
from escalator_tpu.core import semantics
from escalator_tpu.core.arrays import ClusterArrays, NodeArrays, pack_groups
from escalator_tpu.k8s.cache import EventfulClient, GroupFilters, WatchBridge
from escalator_tpu.metrics import metrics


def _copy_soa(soa):
    """Deep copy of a Pod/NodeArrays whose columns may alias live C++ buffers."""
    return type(soa)(
        **{f: np.array(getattr(soa, f)) for f in soa.__dataclass_fields__}
    )


class NativeJaxBackend(ComputeBackend):
    #: ticks on the XLA fallback before the single Pallas retry (see
    #: _decide_resilient); class-level so tests can shrink the cool-off
    _PALLAS_RETRY_AFTER = 10
    name = "native-jax"
    needs_objects = False

    def __init__(self, client: EventfulClient, groups: Sequence[GroupFilters],
                 pod_capacity: int = 1 << 17, node_capacity: int = 1 << 15,
                 incremental: "bool | None" = None,
                 refresh_every: "int | str | None" = None,
                 overlap: "bool | None" = None,
                 snapshot_dir: "str | None" = None,
                 snapshot_every: "int | None" = None,
                 store_kind: str = "auto",
                 relist_audit_every: "int | str | None" = None,
                 warm_restore: bool = False):
        import os

        from escalator_tpu.native.statestore import make_state_store
        from escalator_tpu.ops import kernel

        self._kernel = kernel
        # round 12: the store is a factory pick — the C++ statestore when the
        # toolchain built it, the API/bit-identical numpy fallback otherwise
        # (statestore.make_state_store logs the degradation once at WARN), so
        # streaming ingestion is the primary feed on every install
        self.store = make_state_store(
            pod_capacity=pod_capacity, node_capacity=node_capacity,
            kind=store_kind,
        )
        self._client = client
        self.bridge = WatchBridge(self.store, groups)
        # NOTE: the watch subscription happens at the END of __init__ — a
        # warm restore (round 18) must seed the store twin from the
        # checkpoint before the first event can land
        # re-list reconciliation audit (round 12): every N ticks, re-list the
        # client world through bridge.resync — the O(cluster) walk demoted to
        # an audit cadence; off by default ("off"/unset/0 via env
        # ESCALATOR_TPU_RELIST_AUDIT_EVERY). Parsing is STRICT (the
        # parse_refresh_every lesson): a typo'd cadence must fail loudly,
        # not silently disable the reconciliation the operator asked for.
        if relist_audit_every is None:
            relist_audit_every = os.environ.get(
                "ESCALATOR_TPU_RELIST_AUDIT_EVERY", "off")
        if isinstance(relist_audit_every, str):
            s = relist_audit_every.strip().lower()
            if s in ("", "off", "0"):
                relist_audit_every = 0
            else:
                from escalator_tpu.ops.device_state import parse_refresh_every

                relist_audit_every = parse_refresh_every(
                    s, "ESCALATOR_TPU_RELIST_AUDIT_EVERY")
        elif relist_audit_every != 0:
            from escalator_tpu.ops.device_state import parse_refresh_every

            relist_audit_every = parse_refresh_every(
                relist_audit_every, "relist_audit_every")
        self._relist_audit_every = int(relist_audit_every)
        self._ticks = 0
        #: packed delta batches pre-drained during a previous tick's device
        #: window (the round-12 overlap extension) — applied BEFORE the
        #: current tick's drain, dropped on rebuild (the full re-upload
        #: supersedes them)
        self._pending_batches: list = []
        # Device-resident cluster cache (ops/device_state.py): built on first
        # decide, scatter-updated with the store's dirty slots per tick.
        self._cache = None
        # Incremental decide (round 8, ops/device_state.IncrementalDecider):
        # persistent per-group aggregates maintained by the scatter's exact
        # deltas + dirty-group-compacted decision math — steady-state decide
        # becomes O(dirty groups + N elementwise) instead of O(cluster).
        # Opt-in (param, else ESCALATOR_TPU_INCREMENTAL_DECIDE=1): the
        # incremental dispatch pair pins the XLA scatter path (its delta
        # batches are exactly the tiny-scatter shape; the Pallas sweep's win
        # is the full-cluster interleaved sweep this mode exists to avoid),
        # so the pallas resilience machinery below stays on the legacy path.
        if incremental is None:
            incremental = os.environ.get(
                "ESCALATOR_TPU_INCREMENTAL_DECIDE", "0"
            ).lower() in ("1", "true", "yes")
        self._incremental = bool(incremental)
        self._refresh_every = refresh_every
        # host/device overlap (round 10): incremental ordered ticks return
        # unfenced and the unpack's first device read absorbs the tail. The
        # legacy (non-incremental) path keeps its fences — its Pallas
        # resilience machinery NEEDS the block inside _decide_resilient so a
        # device failure surfaces where the fallback can catch it.
        from escalator_tpu.controller.backend import _overlap_default

        self._overlap = overlap if overlap is not None else _overlap_default()
        self._inc = None
        # node slots whose device lanes were overridden by last tick's dry-mode
        # view — they must be re-scattered (possibly back to raw) this tick
        self._overridden_slots = np.empty(0, np.int64)
        self._packing = PackingPostPass()
        # sticky impl override after a Pallas failure (see _decide_resilient):
        # a controller that crash-loops on a kernel lowering bug is worse than
        # one that degrades to the bit-identical scatter path and says so.
        # ONE retry is allowed after _PALLAS_RETRY_AFTER ticks — a transient
        # non-Pallas failure (host OOM, one-off transfer error) must not
        # forfeit the measured 1.57x win for the whole process lifetime; a
        # second failure makes the fallback permanent.
        self._impl_fallback: "str | None" = None
        self._pallas_failures = 0
        self._ticks_since_fallback = 0
        self._dispatches_this_tick = 0
        # failover checkpoints (round 11): the incremental decider's state
        # checkpoints to disk on a cadence. Round 18 closes the warm-RESTORE
        # caveat: the checkpoint now carries a slot->key sidecar
        # (``store.keys``, see WatchBridge.slot_key_tables), and because the
        # store assigns slots freelist-then-sequential, ordered upserts on a
        # fresh store replay the snapshot's exact ingestion-ordered layout —
        # so a restarted process can adopt the device state and resync only
        # what changed since (docs/ha.md).
        from escalator_tpu.controller.backend import _snapshot_config

        snapshot_dir, snapshot_every = _snapshot_config(
            snapshot_dir, snapshot_every)
        self._writer = None
        if snapshot_dir and self._incremental:
            from escalator_tpu.ops.snapshot import SnapshotWriter

            self._writer = SnapshotWriter(snapshot_dir, every=snapshot_every)
        # warm restore (round 18, opt-in — attach_event_source passes
        # warm_restore=True when checkpointing is on): seed the store twin +
        # bridge maps + device state from the rolling checkpoint BEFORE
        # subscribing. Any failure cold-starts on a fresh store, exactly
        # today's bootstrap.
        warm = False
        if warm_restore and self._writer is not None:
            warm = self._try_warm_restore(
                pod_capacity, node_capacity, store_kind)
        # cold: list-then-watch replay (the O(cluster) bootstrap). warm: the
        # store already holds the checkpoint world — subscribe without
        # replay, then ONE resync audit reconciles everything that changed
        # while no leader ran into the first tick's delta batch (unchanged
        # objects match their seeded records and stay clean).
        client.subscribe(self.bridge.apply, replay=not warm)
        if warm:
            self.bridge.resync(client)
        obs.jaxmon.install()

    # -- warm restore (round 18) ---------------------------------------------
    def _checkpoint_extra(self) -> Dict[str, np.ndarray]:
        """Slot->key sidecar leaves for the rolling checkpoint: the store
        assigns slots by ingestion order, so the snapshot's layout is only
        reproducible with the key tables that produced it. One msgpack blob
        as a uint8 leaf; ``leaves_to_state`` pulls leaves by name, so repack
        consumers of the same snapshot dir ignore it."""
        import msgpack

        with self.store.lock:
            pod_keys, node_keys = self.bridge.slot_key_tables()
        blob = msgpack.packb({"pod_keys": pod_keys, "node_keys": node_keys})
        return {"store.keys": np.frombuffer(blob, np.uint8)}

    def _note_corrupt_snapshot(self, path: str, err: Exception) -> None:
        import logging

        metrics.snapshot_restores.labels("corrupt").inc()
        dump = obs.dump_on_incident("snapshot-corrupt")
        logging.getLogger("escalator_tpu.native").error(
            "snapshot %s failed validation (%s); cold-starting instead "
            "(flight record: %s)", path, err, dump or "dump failed")

    def _try_warm_restore(self, pod_capacity: int, node_capacity: int,
                          store_kind: str) -> bool:
        """Warm start for the streaming path: adopt the checkpoint's device
        state (exactly the repack backend's ``_try_restore``), then replay
        the snapshot's slot layout into the still-empty store twin from the
        ``store.keys`` sidecar and seed the bridge's record maps, so the
        post-subscribe resync marks only objects that changed while no
        leader ran. Returns True on success; every failure path leaves a
        fresh cold-start store behind."""
        import logging

        import msgpack

        from escalator_tpu.native.statestore import make_state_store
        from escalator_tpu.ops import snapshot as snaplib
        from escalator_tpu.ops.device_state import restore_decider

        log = logging.getLogger("escalator_tpu.native")
        path = self._writer.path
        with obs.span("snapshot_load"):
            try:
                leaves, meta = snaplib.read_snapshot(path)
            except FileNotFoundError:
                return False
            except snaplib.SnapshotCorruptError as e:
                self._note_corrupt_snapshot(path, e)
                return False
        raw = leaves.pop("store.keys", None)
        if raw is None:
            metrics.snapshot_restores.labels("stale").inc()
            log.warning(
                "snapshot %s carries no slot-key sidecar (pre-round-18 "
                "writer): the ingestion-ordered slot layout cannot be "
                "replayed — cold-starting the streaming store instead", path)
            return False
        try:
            keys = msgpack.unpackb(np.asarray(raw).tobytes())
            pod_keys = [str(k) for k in keys["pod_keys"]]
            node_keys = [str(k) for k in keys["node_keys"]]
        except Exception as e:
            self._note_corrupt_snapshot(path, e)
            return False
        # leaf length = capacity + 1 (the scratch lane rides the snapshot)
        cap_p = int(
            np.asarray(leaves.get("cluster.pods.valid", ())).shape[0]) - 1
        cap_n = int(
            np.asarray(leaves.get("cluster.nodes.valid", ())).shape[0]) - 1
        if (0 <= cap_p < self.store.pod_capacity
                or 0 <= cap_n < self.store.node_capacity):
            # round 20: a checkpoint SMALLER than the configured store is a
            # slot remap, not a stale restore — the occupied slots keep
            # their indices and every new lane is a hole, so the
            # ingestion-ordered replay below reproduces the snapshot's
            # layout inside the larger store (the tenant-row adopt's
            # identity-remap contract; docs/ha.md). Shrinking still
            # cold-starts: pad_cluster_leaves refuses it by construction.
            target_p = max(cap_p, self.store.pod_capacity)
            target_n = max(cap_n, self.store.node_capacity)
            leaves = snaplib.pad_cluster_leaves(
                leaves, target_p + 1, target_n + 1)
            meta = dict(meta, pod_capacity=target_p, node_capacity=target_n)
            pod_keys += [""] * max(0, target_p - len(pod_keys))
            node_keys += [""] * max(0, target_n - len(node_keys))
            log.info(
                "snapshot %s capacities (%dP/%dN) padded up to the "
                "configured store (%dP/%dN): warm restore via slot remap",
                path, cap_p, cap_n, target_p, target_n)
        try:
            cache, inc = restore_decider(
                leaves, meta, impl="xla", refresh_every=self._refresh_every,
                on_mismatch="repair", overlap=self._overlap)
        except snaplib.SnapshotCorruptError as e:
            self._note_corrupt_snapshot(path, e)
            return False
        if (cache.pod_capacity < self.store.pod_capacity
                or cache.node_capacity < self.store.node_capacity):
            # unreachable after the pad above unless the snapshot carried
            # no cluster leaves at all — keep the named stale rejection
            metrics.snapshot_restores.labels("stale").inc()
            log.warning(
                "snapshot %s capacities (%dP/%dN) are smaller than the "
                "configured store (%dP/%dN); slot layout cannot be replayed "
                "— cold-starting", path, cache.pod_capacity,
                cache.node_capacity, self.store.pod_capacity,
                self.store.node_capacity)
            return False
        try:
            if (cache.pod_capacity > self.store.pod_capacity
                    or cache.node_capacity > self.store.node_capacity):
                self.store.grow(cache.pod_capacity, cache.node_capacity)
            self._seed_store(cache, pod_keys, node_keys)
        except Exception as e:
            # the store may be half-seeded: rebuild it (and the bridge)
            # fresh so the cold bootstrap starts from a clean slate
            metrics.snapshot_restores.labels("stale").inc()
            log.warning(
                "warm seed from %s failed (%s); cold-starting on a fresh "
                "store", path, e)
            self.store = make_state_store(
                pod_capacity=pod_capacity, node_capacity=node_capacity,
                kind=store_kind)
            self.bridge = WatchBridge(self.store, self.bridge.groups)
            return False
        with self.store.lock:
            self.bridge.seed_from_snapshot(
                pod_keys, node_keys, *cache.host_views)
        self._cache, self._inc = cache, inc
        metrics.snapshot_restores.labels("warm").inc()
        log.info(
            "warm start: restored device state + store twin from %s "
            "(tick %s)", path, meta.get("tick"))
        return True

    def _seed_store(self, cache, pod_keys: List[str],
                    node_keys: List[str]) -> None:
        """Replay the snapshot's slot layout into the empty store: slots
        assign freelist-then-sequential, so upserting slot 0..last IN ORDER
        on a fresh store reproduces any layout — holes get placeholder keys
        (deleted afterwards, returning them to the freelist; DNS-1123 names
        and ``ns/name`` uids cannot collide with them). Slots whose key
        sidecar disagrees with the snapshot's valid column (an event landed
        between the checkpointed tick's drain and the key-table capture)
        seed as holes whose placeholder delete lands AFTER the dirty
        discard — the first tick then scatters the invalidation to the
        device, and the post-restore resync re-adds the object if it is
        still live. Dirty marks from the replay itself are discarded: the
        restored device state already holds every seeded row."""
        hp, hn = cache.host_views
        with self.store.lock:
            dirty_deletes = []   # (delete_fn, slot): run AFTER the discard

            def replay(keys, valid_col, real, hole, delete):
                valid_col = np.asarray(valid_col)
                last = max((s for s, k in enumerate(keys) if k), default=-1)
                if valid_col.any():
                    last = max(last, int(np.nonzero(valid_col)[0].max()))
                clean_holes = []
                for slot in range(last + 1):
                    key = keys[slot]
                    valid = bool(valid_col[slot])
                    if key and valid:
                        got = real(slot, key)
                    else:
                        got = hole(slot)
                        if bool(key) != valid:
                            keys[slot] = ""
                            dirty_deletes.append((delete, slot))
                        else:
                            clean_holes.append(slot)
                    if got != slot:
                        raise RuntimeError(
                            f"slot replay diverged at {slot} (got {got})")
                for slot in clean_holes:
                    delete(f"_warm-hole-{slot}")

            replay(
                node_keys, hn.valid,
                lambda slot, name: self.store.upsert_node(
                    name, int(hn.group[slot]), int(hn.cpu_milli[slot]),
                    int(hn.mem_bytes[slot]),
                    creation_ns=int(hn.creation_ns[slot]),
                    tainted=bool(hn.tainted[slot]),
                    cordoned=bool(hn.cordoned[slot]),
                    no_delete=bool(hn.no_delete[slot]),
                    taint_time_sec=int(hn.taint_time_sec[slot])),
                lambda slot: self.store.upsert_node(
                    f"_warm-hole-{slot}", 0, 0, 0),
                self.store.delete_node)
            replay(
                pod_keys, hp.valid,
                lambda slot, uid: self.store.upsert_pod(
                    uid, int(hp.group[slot]), int(hp.cpu_milli[slot]),
                    int(hp.mem_bytes[slot]), int(hp.node[slot])),
                lambda slot: self.store.upsert_pod(
                    f"_warm-hole-{slot}", 0, 0, 0, -1),
                self.store.delete_pod)
            self.store.drain_dirty()
            for delete, slot in dirty_deletes:
                delete(f"_warm-hole-{slot}")

    def _refresh_cached_capacity(self, group_inputs, nodes: NodeArrays) -> None:
        """First live node per group -> GroupState cached capacity
        (reference: controller.go:208-211)."""
        valid_idx = np.nonzero(nodes.valid)[0]
        if valid_idx.size == 0:
            return
        node_groups = nodes.group[valid_idx]
        uniq, first = np.unique(node_groups, return_index=True)
        first_slot = {int(gid): int(valid_idx[fi]) for gid, fi in zip(uniq, first, strict=True)}
        for gi, (_, _, _config, state) in enumerate(group_inputs):
            slot = first_slot.get(gi)
            if slot is not None:
                state.cached_cpu_milli = int(nodes.cpu_milli[slot])
                state.cached_mem_bytes = int(nodes.mem_bytes[slot])

    def _dry_mode_view(self, nodes: NodeArrays, group_inputs, dry_mode_flags,
                       taint_trackers) -> NodeArrays:
        """Per-tick corrected taint/cordon columns for dry-mode groups."""
        if not dry_mode_flags or not any(dry_mode_flags):
            return nodes
        tainted = np.array(nodes.tainted, copy=True)
        cordoned = np.array(nodes.cordoned, copy=True)
        dry_groups = {gi for gi, f in enumerate(dry_mode_flags) if f}
        in_dry = np.isin(nodes.group, list(dry_groups)) & nodes.valid
        tainted[in_dry] = False
        cordoned[in_dry] = False
        if taint_trackers:
            for gi in dry_groups:
                for name in taint_trackers[gi] or ():
                    slot = self.store.node_slot(name)
                    if slot >= 0:
                        tainted[slot] = True
        return NodeArrays(
            group=nodes.group, cpu_milli=nodes.cpu_milli,
            mem_bytes=nodes.mem_bytes, creation_ns=nodes.creation_ns,
            tainted=tainted, cordoned=cordoned, no_delete=nodes.no_delete,
            taint_time_sec=nodes.taint_time_sec, valid=nodes.valid,
        )

    # -- decide ------------------------------------------------------------------
    def decide(self, group_inputs, now_sec, dry_mode_flags=None,
               taint_trackers=None):
        from escalator_tpu.native.statestore import store_kind

        with obs.span(self.name):
            obs.annotate(backend=self.name,
                         impl="xla" if self._incremental else
                         (self._impl_fallback or "native"),
                         store=store_kind(self.store))
            return self._decide_inner(
                group_inputs, now_sec, dry_mode_flags, taint_trackers)

    def _predrain(self) -> None:
        """Round-12 overlap extension: drain the watch deltas that arrived
        SINCE this tick's event_drain into a pending packed batch, while
        the tick's device program is still in flight (IncrementalDecider
        runs this between its decide dispatch and its first blocking read).
        The next tick applies the pending batch before its own drain —
        tick t+1's event-drain work hides under tick t's device time.
        Host/store state only; never touches device buffers (a donating
        dispatch is in flight)."""
        store = self.store
        if self._cache is None or not hasattr(store, "drain_dirty_packed"):
            return
        with store.lock:
            if store.pod_dirty_count == 0 and store.node_dirty_count == 0:
                return
            # a capacity change since the tick's drain means the batch would
            # target the WRONG scratch lane — leave it for the rebuild path
            if (store.pod_capacity != self._cache.pod_capacity
                    or store.node_capacity != self._cache.node_capacity):
                return
            self._pending_batches.append(store.drain_dirty_packed())

    def _decide_inner(self, group_inputs, now_sec, dry_mode_flags=None,
                      taint_trackers=None):
        import jax

        from escalator_tpu.ops.device_state import DeviceClusterCache

        t0 = time.perf_counter()
        self._ticks += 1
        # Re-list audit cadence (O(cluster), default off): reconcile the
        # store against a full client re-list BEFORE taking the store lock
        # below (resync acquires client-then-store, the same order the
        # event path uses — taking it under our store lock would invert
        # that against a concurrent watch thread). Slots it touches land in
        # this tick's drain like any other event.
        if (self._relist_audit_every
                and self._ticks % self._relist_audit_every == 0):
            with obs.span("relist_audit"):
                stats = self.bridge.resync(self._client)
                obs.annotate(relist_audit=(
                    f"dropped={stats['pods_dropped']}p/"
                    f"{stats['nodes_dropped']}n "
                    f"reapplied={stats['events_reapplied']}"))
        # Hold the store's single-writer lock across the whole host phase
        # (drain/pack -> gather -> snapshot): a concurrent watch thread can
        # then never tear the tick's snapshot or race the dirty-list drain.
        # The long device decide below runs OUTSIDE the lock — ingestion
        # overlaps compute, the -race-analog soak test
        # (tests/test_concurrency_soak.py) exercises exactly this
        # interleaving. Phase taxonomy (round 12): ``event_drain`` is the
        # store's dirty drain + delta-triple gather (ONE native crossing on
        # the packed fast path), ``triple_build`` the remaining [G]/[N]
        # host assembly — together they replace the old ``host_snapshot``
        # composite, so a dump attributes the host tail line by line. Their
        # combined duration is also "how long watch ingestion was stalled".
        dry_any = bool(dry_mode_flags and any(dry_mode_flags))
        with self.store.lock:
            pods, nodes_raw = self.store.as_pod_node_arrays()
            rebuild = (
                self._cache is None
                or self._cache.pod_capacity != self.store.pod_capacity
                or self._cache.node_capacity != self.store.node_capacity
                # incremental state is [G]-shaped: a group-count change that
                # crosses the pad_groups power-of-two boundary (8 -> 9
                # groups) changes the packed groups shape with the store
                # capacities unchanged — the aggregates and persistent
                # columns must rebuild, not broadcast-crash. The legacy path
                # tolerates the swap (groups ride through whole), so the
                # extra rebuild is scoped to incremental mode.
                or (self._incremental and self._cache is not None
                    and int(self._cache.cluster.groups.valid.shape[0])
                    != int(_round_up(len(group_inputs), 8)))
            )
            # Fast path: no dry-mode overrides in play and the store can
            # emit packed delta triples — the steady-state tick. The drain,
            # the per-column gather and the pad all happen inside the store
            # (one ctypes crossing on the native store; vectorized numpy on
            # the fallback), and the dry-mode/override machinery is
            # bypassed because raw columns ARE the decided view.
            fast = (not rebuild and not dry_any
                    and self._overridden_slots.size == 0
                    and hasattr(self.store, "drain_dirty_packed"))
            pending, self._pending_batches = self._pending_batches, []
            with obs.span("triple_build"):
                self._refresh_cached_capacity(group_inputs, nodes_raw)
                nodes = self._dry_mode_view(
                    nodes_raw, group_inputs, dry_mode_flags, taint_trackers
                )
                groups = pack_groups(
                    [(config, state) for _, _, config, state in group_inputs],
                    pad_groups=_round_up(len(group_inputs), 8),
                )
                overridden = (
                    np.nonzero(
                        (nodes.tainted != nodes_raw.tainted)
                        | (nodes.cordoned != nodes_raw.cordoned)
                    )[0].astype(np.int64)
                    if nodes is not nodes_raw
                    else np.empty(0, np.int64)
                )
                # Snapshot the tiny per-node columns _unpack reads after the
                # lock is released (the SoA views alias the live store
                # buffers; result assembly must group by the DECIDED state,
                # not whatever a watch thread wrote since).
                unpack_group = np.array(nodes.group)
                unpack_valid = np.array(nodes.valid)
                unpack_tainted_col = np.array(nodes.tainted)
                unpack_cordoned_col = np.array(nodes.cordoned)
                unpack_cordoned = unpack_valid & unpack_cordoned_col
                unpack_untainted = (
                    unpack_valid & ~unpack_tainted_col & ~unpack_cordoned_col
                )
                # lazy-orders gate (kernel.lazy_orders_decide): tainted
                # presence in the DECIDED snapshot (dry-mode view included) —
                # when no node is tainted and no group scales down, no
                # ordering window is ever read, and the decide skips its
                # dominant [N]-lane sort
                tainted_any = bool(
                    (np.asarray(nodes.valid)
                     & np.asarray(nodes.tainted)).any())
                # Packing-aware groups: gather their pod/bin lanes from the
                # same locked snapshot; the device FFD runs after decide,
                # outside the lock
                packing_rows = self._gather_packing_inputs(
                    group_inputs, pods, nodes)
                if rebuild:
                    # first tick or store growth: copy the full columns under
                    # the lock; the O(cluster) device upload happens AFTER
                    # release so watch ingestion never stalls behind a
                    # transfer/compile. Pre-drained pending batches are
                    # superseded by the full upload (the store columns
                    # already carry their effects) — drop them.
                    pending = []
                    pods_snap = _copy_soa(pods)
                    nodes_snap = _copy_soa(nodes)
            # event_drain owns the WHOLE diff/pack: the dirty drain plus the
            # delta-triple gather — one store crossing on the fast path, the
            # legacy drain + per-column gather (from the dry-mode-corrected
            # views bound just above) otherwise — so the phase means the same
            # work whichever path a tick took
            with obs.span("event_drain"):
                if fast:
                    gathered = self.store.drain_dirty_packed()
                else:
                    pod_dirty, node_dirty = self.store.drain_dirty()
                    if not rebuild:
                        node_dirty = np.unique(np.concatenate(
                            [node_dirty, self._overridden_slots, overridden]))
                        self._cache.set_host(pods, nodes)
                        # lock covers only the host gather (reads the live
                        # views); the device dispatch — and any jit compile
                        # a new delta-bucket size triggers — happens after
                        # release, so watch ingestion never convoys behind a
                        # transfer or compile
                        gathered = self._cache.gather_deltas(
                            pod_dirty, node_dirty)
        with obs.span("scatter", kind="device"):
            if rebuild:
                # outside the lock: upload the snapshot copies. The cache's host
                # views rebind on the next tick's set_host before any gather, so
                # no live-view binding is needed (or safe) here.
                self._cache = DeviceClusterCache(
                    ClusterArrays(groups=groups, pods=pods_snap,
                                  nodes=nodes_snap)
                )
                if self._incremental:
                    from escalator_tpu.ops.device_state import IncrementalDecider

                    # a production controller must not crash-loop on an audit
                    # mismatch: repair (recompute + full dirty) and log loudly
                    self._inc = IncrementalDecider(
                        self._cache, impl="xla",
                        refresh_every=self._refresh_every,
                        on_mismatch="repair", overlap=self._overlap)
                obs.fence(self._cache.cluster)
            elif self._inc is not None:
                # incremental: same scatter batch, but the device program also
                # folds the exact aggregate deltas + dirty marks (one
                # dispatch). NOT fenced, same as the legacy branch below: the
                # scatter->decide dispatch pipelining is the steady-tick
                # optimization, and a fence here would buy phase precision by
                # inserting a host sync the production path never had — the
                # decide span absorbs any scatter tail, keeping the tick
                # total honest while this phase reads as dispatch-only.
                # Pre-drained pending batches (last tick's overlap window)
                # apply FIRST, in drain order — a slot re-touched since
                # lands in the fresh batch and overwrites.
                for batch in pending:
                    self._inc.apply_gathered(batch)
                self._inc.apply_gathered(gathered, groups)
            else:
                # two async dispatches (scatter, then decide) pipeline
                # back-to-back; measured faster than the fused single-program
                # alternative (apply_dirty_and_decide) on the v5e tunnel.
                # NOT fenced: the pipelining IS the optimization — the decide
                # span below absorbs any scatter tail, so the tick total
                # stays honest while this phase reads as dispatch-only.
                for batch in pending:
                    self._cache.apply_gathered(batch)
                self._cache.apply_gathered(gathered, groups)
        self._overridden_slots = overridden
        t1 = time.perf_counter()
        if self._inc is not None:
            # incremental dispatch pair (delta_decide light / incremental
            # ordered) with the same lazy-orders gate semantics; the decider
            # runs _predrain in its dispatch-to-first-read window, so next
            # tick's event drain hides under this tick's device program
            with obs.span("decide", kind="device"):
                out, ordered = self._inc.decide(
                    now_sec, tainted_any,
                    overlap_work=self._predrain if self._overlap else None)
                if not (self._overlap and ordered):
                    obs.fence(out)
            t2 = time.perf_counter()
            metrics.solver_pack_latency.labels(self.name).observe(t1 - t0)
            metrics.solver_decide_latency.labels(self.name).observe(t2 - t1)
            obs.annotate(ordered=bool(ordered))
            with obs.span("unpack"):
                results = self._unpack(
                    out, group_inputs, unpack_group,
                    unpack_cordoned, ordered=ordered,
                    untainted_mask=unpack_untainted,
                    dispatch_end=t2 if self._overlap and ordered else None,
                    pre_synced=self._inc.last_decide_synced)
            _annotate_decision(self.name, out)
            with obs.span("packing_post"):
                if packing_rows:
                    sel = set(PackingPostPass.select(results, group_inputs))
                    self._packing.apply_arrays(
                        results,
                        [row for row in packing_rows if row[0] in sel]
                    )
            if self._writer is not None:
                with obs.span("checkpoint"):
                    self._writer.maybe_checkpoint(
                        self._inc, extra=self._checkpoint_extra)
            return results
        # blocks on the result itself: an async device failure must surface
        # inside the resilient wrapper, not here. The lazy protocol sorts
        # only when an ordering has a consumer; imported from the real kernel
        # module (not self._kernel, which tests stub at the decide_jit seam —
        # the protocol is pure host logic, the stub still intercepts every
        # dispatch inside _decide_resilient)
        from escalator_tpu.ops.kernel import lazy_orders_decide

        # a drain-start tick dispatches twice; the pallas cool-off counter
        # must still advance once per TICK (see _decide_resilient)
        self._dispatches_this_tick = 0

        def dispatch(w):
            with obs.span("decide_ordered" if w else "decide_light",
                          kind="device"):
                return obs.fence(
                    self._decide_resilient(np.int64(now_sec), with_orders=w))

        with obs.span("decide", kind="device"):
            out, ordered = lazy_orders_decide(dispatch, tainted_any)
            obs.fence(out)
        t2 = time.perf_counter()
        metrics.solver_pack_latency.labels(self.name).observe(t1 - t0)
        metrics.solver_decide_latency.labels(self.name).observe(t2 - t1)
        obs.annotate(ordered=bool(ordered))
        _annotate_decision(self.name, out)
        with obs.span("unpack"):
            results = self._unpack(out, group_inputs, unpack_group,
                                   unpack_cordoned, ordered=ordered,
                                   untainted_mask=unpack_untainted)
        with obs.span("packing_post"):
            if packing_rows:
                sel = set(PackingPostPass.select(results, group_inputs))
                self._packing.apply_arrays(
                    results, [row for row in packing_rows if row[0] in sel]
                )
        return results

    def _decide_resilient(self, now_sec, with_orders: bool = True):
        """Run the decide with the native tick's impl selection (pallas on
        TPU — the churned slot-reused layout is where the sorted MXU sweep
        measured 1.57x faster than XLA scatter; ops.kernel.native_tick_impl),
        degrading to the XLA scatter path if the Pallas program fails to
        lower/execute. ONE retry of the native choice happens after
        _PALLAS_RETRY_AFTER fallback ticks (a transient failure must not
        forfeit the win forever); a second failure is sticky for the process.
        Outputs are bit-identical either way (the
        parity suite locks that), so degrading changes latency, never
        decisions — same philosophy as the accelerator probe's CPU pin
        (jaxconfig.ensure_responsive_accelerator). A crash would instead
        restart-loop through the same compile failure every time."""
        import jax

        from escalator_tpu.ops.kernel import native_tick_impl

        native = native_tick_impl(self._cache.device.platform)
        impl = self._impl_fallback or native
        # a lazy-orders drain-start tick calls this twice (light + ordered);
        # the cool-off is documented in TICKS, so only the tick's first
        # dispatch advances it
        self._dispatches_this_tick += 1
        if (
            self._impl_fallback is not None
            and self._pallas_failures == 1
            and native == "pallas"
        ):
            # degraded by a single failure: retry the native choice once
            # after a cool-off (the failure may have been transient — host
            # OOM, one-off transfer error — not the Pallas program itself)
            if self._dispatches_this_tick == 1:
                self._ticks_since_fallback += 1
            if self._ticks_since_fallback >= self._PALLAS_RETRY_AFTER:
                impl = native
        # misconfiguration stays fail-fast (same ValueError every backend
        # raises for a bad ESCALATOR_TPU_KERNEL_IMPL; kernel.py locks this
        # invariant) — only genuine lowering/device failures degrade
        if impl not in ("xla", "pallas"):
            raise ValueError(f"unknown aggregation impl {impl!r}")
        # the flight record carries the impl that actually RAN this tick
        # (the fallback/retry machinery can differ from the construction one)
        obs.annotate(impl=impl)
        try:
            # block HERE: decide_jit dispatches asynchronously, so a device-
            # side Pallas failure surfaces at block_until_ready, and it must
            # surface inside this try for the fallback to catch it
            out = jax.block_until_ready(self._kernel.decide_jit(
                self._cache.cluster, now_sec, impl=impl,
                with_orders=with_orders))
            if impl == native and self._impl_fallback is not None:
                # the retry succeeded: the failure was transient, lift the
                # fallback. _pallas_failures is a LIFETIME count, deliberately
                # not reset: a device that fails intermittently would
                # otherwise oscillate pallas->xla->retry forever, paying a
                # doubled decide on every failing tick — the next failure
                # (the second ever) makes the fallback permanent instead.
                logging.getLogger("escalator_tpu.native").warning(
                    "impl=%r retry succeeded; lifting the xla fallback", impl)
                self._impl_fallback = None
                self._ticks_since_fallback = 0
            return out
        except Exception:
            if impl == "xla":  # nothing further to degrade to
                raise
            self._pallas_failures += 1
            self._ticks_since_fallback = 0
            logging.getLogger("escalator_tpu.native").warning(
                "impl=%r decide failed (failure %d); falling back to "
                "impl='xla' (%s; decisions are bit-identical)", impl,
                self._pallas_failures,
                "one retry after cool-off" if self._pallas_failures == 1
                else "permanently for this process",
                exc_info=True,
            )
            self._impl_fallback = "xla"
            return jax.block_until_ready(self._kernel.decide_jit(
                self._cache.cluster, now_sec, impl="xla",
                with_orders=with_orders))

    def _gather_packing_inputs(self, group_inputs, pods, nodes):
        """[(gi, pod_cpu, pod_mem, bin_cpu, bin_mem, template, budget)] for
        packing-aware groups, copied out of the locked store snapshot (caller
        holds the store lock). Status filtering happens after decide."""
        packing_gis = [
            gi for gi, (_p, _n, config, _s) in enumerate(group_inputs)
            if getattr(config, "packing_aware", False)
        ]
        if not packing_gis:
            return []
        pod_group = np.asarray(pods.group)
        pod_valid = np.asarray(pods.valid)
        node_group = np.asarray(nodes.group)
        untainted = (
            np.asarray(nodes.valid)
            & ~np.asarray(nodes.tainted)
            & ~np.asarray(nodes.cordoned)
        )
        rows = []
        for gi in packing_gis:
            _p, _n, config, state = group_inputs[gi]
            psel = pod_valid & (pod_group == gi)
            nsel = untainted & (node_group == gi)
            rows.append((
                gi,
                np.asarray(pods.cpu_milli)[psel].astype(np.int64),
                np.asarray(pods.mem_bytes)[psel].astype(np.int64),
                np.asarray(nodes.cpu_milli)[nsel].astype(np.int64),
                np.asarray(nodes.mem_bytes)[nsel].astype(np.int64),
                (state.cached_cpu_milli, state.cached_mem_bytes),
                int(config.packing_budget),
            ))
        return rows

    def _unpack(self, out, group_inputs, node_group: np.ndarray,
                cordoned_mask: np.ndarray,
                ordered: bool = True,
                untainted_mask: "np.ndarray | None" = None,
                dispatch_end: "float | None" = None,
                pre_synced: bool = False,
                ) -> List[GroupDecision]:
        """Slot-order-agnostic unpack: node indices resolve through the bridge.

        ordered=False means the decide ran WITHOUT the ordering sort
        (lazy-orders light path): the order fields are placeholders, and by
        the protocol's gate no ORDERING consumer exists — no tainted nodes
        (untaint and reap windows empty) and no negative delta (scale-down
        windows unread). scale_down_order is still populated as UNORDERED
        membership from ``untainted_mask`` (the decided snapshot): the
        controller's registration-lag metric reads the candidate lists as
        plain membership when this backend passes no node objects
        (controller.py:348), and leaving them empty logged a spurious
        "expected new nodes: N actual: 0" after every scale-up (ADVICE r5).
        untaint_order stays empty — the light gate guarantees no tainted
        node exists in the decided snapshot.

        ``dispatch_end`` marks an overlapped tick (round 10): the decide
        came back unfenced at that time. The host-only prep below — slot
        scans over the LOCKED COPIES captured at decide time, no device
        data, no lock — runs first, hidden under the in-flight device
        program; the first device read then absorbs whatever tail remains
        (measured + annotated)."""
        from escalator_tpu.controller.backend import _annotate_overlap

        cordoned_slots = np.nonzero(cordoned_mask)[0]
        membership_slots = (
            np.nonzero(untainted_mask)[0]
            if not ordered and untainted_mask is not None else ()
        )

        sync_start = time.perf_counter()
        status = np.asarray(out.status)        # first device read: blocks
        if dispatch_end is not None:
            _annotate_overlap(dispatch_end, sync_start,
                              time.perf_counter() - sync_start,
                              pre_synced=pre_synced)
        delta = np.asarray(out.nodes_delta)
        cpu_pct = np.asarray(out.cpu_percent)
        mem_pct = np.asarray(out.mem_percent)
        cpu_req = np.asarray(out.cpu_request_milli)
        mem_req = np.asarray(out.mem_request_bytes)
        cpu_cap = np.asarray(out.cpu_capacity_milli)
        mem_cap = np.asarray(out.mem_capacity_bytes)
        n_unt = np.asarray(out.num_untainted)
        n_tnt = np.asarray(out.num_tainted)
        n_crd = np.asarray(out.num_cordoned)
        n_all = np.asarray(out.num_nodes)
        n_pods = np.asarray(out.num_pods)
        down = np.asarray(out.scale_down_order)
        up = np.asarray(out.untaint_order)
        u_off = np.asarray(out.untainted_offsets)
        t_off = np.asarray(out.tainted_offsets)
        reap = np.asarray(out.reap_mask)
        remaining = np.asarray(out.node_pods_remaining)

        # node_group/cordoned_mask are COPIES captured under the store lock at
        # decide time, so grouping reflects the decided state even if a watch
        # thread has since rewritten lanes. Slot->object resolution goes through
        # the bridge under the lock for a mutually-consistent name map; a slot
        # recycled mid-decide resolves to None (or the new object) and is
        # filtered — self-correcting next tick, same TOCTOU the reference has
        # between its lister snapshot and its API writes.
        with self.store.lock:
            node_at = self.bridge.node_at_slot
            reap_slots = np.nonzero(reap)[0]
            reap_by_group: Dict[int, list] = {}
            for slot in reap_slots:
                reap_by_group.setdefault(int(node_group[slot]), []).append(
                    node_at(int(slot))
                )
            cordoned_by_group: Dict[int, list] = {}
            for slot in cordoned_slots:
                cordoned_by_group.setdefault(int(node_group[slot]), []).append(
                    node_at(int(slot))
                )
            membership_by_group: Dict[int, list] = {}
            for slot in membership_slots:
                membership_by_group.setdefault(
                    int(node_group[slot]), []
                ).append((int(slot), node_at(int(slot))))

            results = []
            for gi, (_pods, _nodes, _config, _state) in enumerate(group_inputs):
                decision = semantics.Decision(
                    status=semantics.DecisionStatus(int(status[gi])),
                    nodes_delta=int(delta[gi]),
                    cpu_percent=float(cpu_pct[gi]),
                    mem_percent=float(mem_pct[gi]),
                    cpu_request_milli=int(cpu_req[gi]),
                    mem_request_bytes=int(mem_req[gi]),
                    cpu_capacity_milli=int(cpu_cap[gi]),
                    mem_capacity_bytes=int(mem_cap[gi]),
                    num_untainted=int(n_unt[gi]),
                    num_tainted=int(n_tnt[gi]),
                    num_cordoned=int(n_crd[gi]),
                    num_nodes=int(n_all[gi]),
                    num_pods=int(n_pods[gi]),
                )
                # keep (slot, node) pairs: pods-remaining indexes by the DECIDED
                # slot, never by a post-decide store lookup (a deleted node's
                # node_slot() is -1, which would silently read the last lane)
                down_pairs = [
                    (int(i), node_at(int(i)))
                    for i in down[u_off[gi] : u_off[gi + 1]]
                ] if ordered else membership_by_group.get(gi, [])
                up_pairs = [
                    (int(i), node_at(int(i)))
                    for i in up[t_off[gi] : t_off[gi + 1]]
                ] if ordered else []
                results.append(
                    GroupDecision(
                        decision=decision,
                        scale_down_order=[n for _, n in down_pairs if n is not None],
                        untaint_order=[n for _, n in up_pairs if n is not None],
                        reap_nodes=[
                            n for n in reap_by_group.get(gi, []) if n is not None
                        ],
                        cordoned_nodes=[
                            n for n in cordoned_by_group.get(gi, []) if n is not None
                        ],
                        node_pods_remaining={
                            n.name: int(remaining[slot])
                            for slot, n in down_pairs + up_pairs
                            if n is not None
                        },
                    )
                )
        return results


def group_filters_from_options(node_group_options) -> "list[GroupFilters]":
    """NodeGroupOptions -> the per-group membership filters the event
    bridge resolves with (identical predicates to the listers' — one
    definition, so the event path and the re-list path cannot drift).
    Shared by :func:`make_native_backend` and
    ``IncrementalJaxBackend.attach_event_source``."""
    from escalator_tpu.controller import node_group as ngmod

    filters = []
    for opts in node_group_options:
        if opts.name == ngmod.DEFAULT_NODE_GROUP:
            pod_filter = ngmod.new_pod_default_filter_func()
        else:
            pod_filter = ngmod.new_pod_affinity_filter_func(
                opts.label_key, opts.label_value
            )
        filters.append(
            GroupFilters(
                name=opts.name,
                pod_filter=pod_filter,
                node_filter=ngmod.new_node_label_filter_func(
                    opts.label_key, opts.label_value
                ),
            )
        )
    return filters


def make_native_backend(
    client: EventfulClient,
    node_group_options,
    pod_capacity: int = 1 << 12,
    node_capacity: int = 1 << 10,
    incremental: "bool | None" = None,
    refresh_every: "int | None" = None,
    snapshot_dir: "str | None" = None,
    snapshot_every: "int | None" = None,
    store_kind: str = "auto",
    relist_audit_every: "int | str | None" = None,
) -> NativeJaxBackend:
    """Wire group filters from NodeGroupOptions (same filters the listers use).

    Initial capacities start small — kernel shapes equal store capacity, so a modest
    start keeps the first XLA compile fast; the store doubles (one recompile per
    tier) as the cluster grows toward the 1<<21/1<<18 lifetime maxima."""
    filters = group_filters_from_options(node_group_options)
    return NativeJaxBackend(
        client, filters, pod_capacity=pod_capacity,
        node_capacity=node_capacity, incremental=incremental,
        refresh_every=refresh_every, snapshot_dir=snapshot_dir,
        snapshot_every=snapshot_every, store_kind=store_kind,
        relist_audit_every=relist_audit_every,
    )
