"""Compute backends: where the scale decision actually runs.

The reference computes decisions inline in Go (pkg/controller/controller.go:192-397).
Here the controller depends on the ``ComputeBackend`` interface — the SPI slot
SURVEY.md §2.7 calls the "compute plugin", shaped like a sibling of
``cloudprovider.Builder`` (reference: pkg/cloudprovider/interface.go:95-97):

- ``GoldenBackend``  — pure-Python semantics, dependency-free fallback of last resort
- ``JaxBackend``     — batched device kernel, single program for all groups (TPU when
  present, XLA-CPU otherwise: same traced code, so fallback keeps parity for free)
- ``ShardedJaxBackend`` — nodegroup axis sharded over a device mesh via shard_map
- ``GridJaxBackend``    — 2-D (groups x pods) mesh: tail shards with the groups,
  each block's pod sweep splits further over the mesh columns (parallel.grid)
- ``PodAxisJaxBackend`` — pod axis sharded, for one dominant giant group

All return the same ``GroupDecision`` objects (decision + object-level selections), so
the controller shell is backend-agnostic. ``make_backend("auto")`` picks the best
available. A gRPC remote backend (``escalator_tpu.plugin``) wraps any of these behind
a service boundary for non-Python controllers.
"""

from __future__ import annotations

import abc
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from escalator_tpu import observability as obs
from escalator_tpu.core import semantics
from escalator_tpu.k8s import types as k8s
from escalator_tpu.metrics import metrics

#: One group's inputs: (pods, nodes, config, cross-tick state)
GroupInput = Tuple[
    Sequence[k8s.Pod],
    Sequence[k8s.Node],
    semantics.GroupConfig,
    semantics.GroupState,
]


@dataclass
class GroupDecision:
    """Backend output for one nodegroup, at object level."""

    decision: semantics.Decision
    #: untainted nodes in victim order (per the group's scale_down_selection:
    #: oldest-first by default, emptiest-first when configured)
    scale_down_order: List[k8s.Node] = field(default_factory=list)
    untaint_order: List[k8s.Node] = field(default_factory=list)     # newest-first
    reap_nodes: List[k8s.Node] = field(default_factory=list)
    cordoned_nodes: List[k8s.Node] = field(default_factory=list)
    node_pods_remaining: Dict[str, int] = field(default_factory=dict)


class ComputeBackend(abc.ABC):
    name = "abstract"
    #: False for event-driven backends that source cluster state themselves (the
    #: controller then skips its O(cluster) lister walk and passes empty lists)
    needs_objects = True

    @abc.abstractmethod
    def decide(
        self,
        group_inputs: Sequence[GroupInput],
        now_sec: int,
        dry_mode_flags: Optional[Sequence[bool]] = None,
        taint_trackers: Optional[Sequence[Sequence[str]]] = None,
    ) -> List[GroupDecision]:
        ...


class GoldenBackend(ComputeBackend):
    """Pure-Python reference semantics (escalator_tpu.core.semantics)."""

    name = "golden"

    def decide(self, group_inputs, now_sec, dry_mode_flags=None, taint_trackers=None):
        with obs.span(self.name):
            obs.annotate(backend=self.name, impl="python")
            return self._decide_timed(
                group_inputs, now_sec, dry_mode_flags, taint_trackers)

    def _decide_timed(self, group_inputs, now_sec, dry_mode_flags,
                      taint_trackers):
        # sub-step times accumulate ACROSS the group loop and land as four
        # aggregate phases (a span per group per step would be G*4 phases);
        # everything is host compute, so the phases are fenced by construction
        t_eval = t_filter = t_reap = t_orders = 0.0
        out: List[GroupDecision] = []
        for gi, (pods, nodes, config, state) in enumerate(group_inputs):
            dry = bool(dry_mode_flags[gi]) if dry_mode_flags else False
            tracker = taint_trackers[gi] if taint_trackers else None
            t0 = time.perf_counter()
            decision = semantics.evaluate_node_group(
                pods, nodes, config, state, dry, tracker
            )
            t1 = time.perf_counter()
            untainted, tainted, cordoned = semantics.filter_nodes(nodes, dry, tracker)
            info = k8s.create_node_name_to_info_map(list(pods), list(nodes))
            t2 = time.perf_counter()
            reap_idx = semantics.reap_eligible(
                tainted, info, config.soft_delete_grace_sec,
                config.hard_delete_grace_sec, now_sec,
            )
            t3 = time.perf_counter()
            if config.scale_down_selection == "emptiest_first":
                remaining = [
                    k8s.node_pods_remaining(nd, info)[0] for nd in untainted
                ]
                victim_order = semantics.nodes_emptiest_first(untainted, remaining)
            else:
                victim_order = semantics.nodes_oldest_first(untainted)
            out.append(
                GroupDecision(
                    decision=decision,
                    scale_down_order=[untainted[i] for i in victim_order],
                    untaint_order=[
                        tainted[i] for i in semantics.nodes_newest_first(tainted)
                    ],
                    reap_nodes=[tainted[i] for i in reap_idx],
                    cordoned_nodes=cordoned,
                    node_pods_remaining={
                        name: sum(
                            1 for p in entry[1] if not k8s.pod_is_daemonset(p)
                        )
                        for name, entry in info.items()
                    },
                )
            )
            t4 = time.perf_counter()
            t_eval += t1 - t0
            t_filter += t2 - t1
            t_reap += t3 - t2
            t_orders += t4 - t3
        obs.add_phase("evaluate", t_eval)
        obs.add_phase("filter", t_filter)
        obs.add_phase("reap", t_reap)
        obs.add_phase("orders_assemble", t_orders)
        obs.annotate(digest=_decision_digest_objects(out))
        # the provenance feed rides the same object walk (golden is the
        # dependency-free fallback; flap detection still applies to it)
        from escalator_tpu.observability import provenance

        provenance.stage(
            self.name,
            np.array([int(r.decision.status) for r in out], np.int64),
            np.array([r.decision.nodes_delta for r in out], np.int64))
        return out


def _decision_digest(out) -> str:
    """crc32 over the decision-defining columns (status + delta), as a short
    hex token in every flight-recorder entry: two ticks with equal digests
    decided the same thing, so an operator reading a dump can spot the tick
    where behavior changed without diffing arrays. Device->host copies are
    two [G] arrays — negligible. Round 11: the single implementation lives
    in observability/replay.py, because `debug-replay` asserts a re-executed
    tick against exactly this token — the two may never drift."""
    from escalator_tpu.observability.replay import decision_digest

    return decision_digest(out)


def _annotate_decision(key: str, out) -> None:
    """The per-tick decision bookkeeping every array backend runs where it
    used to annotate just the digest: ONE device->host copy per column
    serves both the flight-record digest AND the provenance feed
    (observability/provenance.py) — the decision history + flap watchdog
    cost the tick nothing beyond the D2H the digest already paid. ``key``
    is the backend's root name, which is also the history key debug-explain
    and the flap journal events report."""
    from escalator_tpu.observability import provenance
    from escalator_tpu.observability.replay import decision_digest_arrays

    status = np.asarray(out.status)
    delta = np.asarray(out.nodes_delta)
    obs.annotate(digest=decision_digest_arrays(status, delta))
    provenance.stage(key, status, delta)


def _decision_digest_objects(results: "List[GroupDecision]") -> str:
    """Object-level digest (golden/grpc post-unpack): same role as
    :func:`_decision_digest`, over the unpadded per-group (status, delta)
    pairs — not comparable across the two forms, stable within one."""
    import zlib

    arr = np.array(
        [(int(r.decision.status), r.decision.nodes_delta) for r in results],
        np.int64,
    )
    return format(zlib.crc32(np.ascontiguousarray(arr).tobytes()), "08x")


def _round_up(n: int, minimum: int = 64) -> int:
    """Next power of two >= n (>= minimum): keeps jit shapes stable as the cluster
    grows/shrinks (no recompilation storms, SURVEY.md §7 raggedness)."""
    size = max(n, minimum)
    return 1 << (size - 1).bit_length()


class PaddedPacker:
    """pack_cluster with high-water-mark power-of-two padding — the shared shape
    stabilization policy for every array-feeding backend (local jit and remote
    plugin alike)."""

    def __init__(self):
        self._pad_pods = 0
        self._pad_nodes = 0
        self._pad_groups = 0

    def seed(self, pad_pods: int, pad_nodes: int, pad_groups: int) -> None:
        """Pre-seed the high-water pads (the snapshot warm-start path: the
        next pack must reproduce the checkpoint's shapes or the resident
        state would be discarded for a pad mismatch). Seeds are floors —
        a bigger live world still grows them as usual."""
        self._pad_pods = max(self._pad_pods, int(pad_pods))
        self._pad_nodes = max(self._pad_nodes, int(pad_nodes))
        self._pad_groups = max(self._pad_groups, int(pad_groups))

    def pack(self, group_inputs, dry_mode_flags=None, taint_trackers=None):
        from escalator_tpu.core.arrays import pack_cluster

        total_pods = sum(len(p) for p, *_ in group_inputs)
        total_nodes = sum(len(n) for _, n, *_ in group_inputs)
        self._pad_pods = max(self._pad_pods, _round_up(total_pods))
        self._pad_nodes = max(self._pad_nodes, _round_up(total_nodes))
        self._pad_groups = max(self._pad_groups, _round_up(len(group_inputs), 8))
        return pack_cluster(
            group_inputs,
            dry_mode_flags=dry_mode_flags,
            taint_trackers=taint_trackers,
            pad_pods=self._pad_pods,
            pad_nodes=self._pad_nodes,
            pad_groups=self._pad_groups,
        )


def _annotate_overlap(dispatch_end: float, sync_start: float,
                      sync_wait_sec: float, pre_synced: bool = False) -> None:
    """Timeline annotations for an OVERLAPPED decide (round 10): the host
    work executed between the unfenced dispatch returning and the first
    blocking device read, plus the residual sync wait. ``overlap_saved_ms``
    is the latency a fenced tick would have added back — exactly the host
    window when the device was still busy at the sync (wait > 0); an upper
    bound when the device finished first inside the window. ``pre_synced``
    means the decide path itself already synchronized before returning
    (e.g. the ordered-incremental repair's changed-lane-count readback), so
    the device was idle for the whole window and nothing was saved."""
    host_ms = max(0.0, (sync_start - dispatch_end) * 1e3)
    obs.annotate(
        overlap_host_ms=round(host_ms, 3),
        overlap_sync_wait_ms=round(sync_wait_sec * 1e3, 3),
        overlap_saved_ms=0.0 if pre_synced else round(host_ms, 3),
    )


def _unpack(out, group_inputs, ordered: bool = True,
            node_masks=None, dispatch_end=None,
            pre_synced: bool = False) -> List[GroupDecision]:
    """Shared kernel-output -> GroupDecision conversion for array backends.

    ordered=False means the decide ran the lazy-orders light program
    (kernel.decide with_orders=False): the order permutations are
    placeholders, and by the protocol's gate no ORDERING consumer exists —
    no tainted nodes and no negative delta. The candidate lists are then
    populated as UNORDERED membership from ``node_masks`` (the packed
    ``NodeArrays`` the decide saw, carrying the dry-mode taint view): the
    controller reads them as membership too — `_calculate_new_node_metrics`
    falls back to ``untainted + tainted + cordoned`` when an event-driven
    backend passes no node objects (controller.py:348), and an empty list
    there logged a spurious "expected new nodes: N actual: 0" after every
    scale-up (ADVICE r5). Without masks they stay empty (legacy callers).
    reap_nodes and node_pods_remaining come from flat (non-order) outputs
    and stay exact either way.

    ``dispatch_end`` marks an OVERLAPPED tick (the decide was dispatched
    unfenced at that perf_counter time): the device-independent host
    assembly below — the flat node-object list — runs FIRST, while the
    device program may still be in flight, and the first ``np.asarray``
    read then absorbs whatever tail remains (measured and annotated)."""
    # flat node index -> object, in pack order: pure host work, independent
    # of the decide output — ordered before the first device read so an
    # overlapped tick hides it under the in-flight device program
    flat_nodes: List[k8s.Node] = []
    for _, nodes, _, _ in group_inputs:
        flat_nodes.extend(nodes)

    sync_start = time.perf_counter()
    status = np.asarray(out.status)       # first device read: blocks here
    if dispatch_end is not None:
        _annotate_overlap(dispatch_end, sync_start,
                          time.perf_counter() - sync_start,
                          pre_synced=pre_synced)
    delta = np.asarray(out.nodes_delta)
    cpu_pct = np.asarray(out.cpu_percent)
    mem_pct = np.asarray(out.mem_percent)
    cpu_req = np.asarray(out.cpu_request_milli)
    mem_req = np.asarray(out.mem_request_bytes)
    cpu_cap = np.asarray(out.cpu_capacity_milli)
    mem_cap = np.asarray(out.mem_capacity_bytes)
    n_unt = np.asarray(out.num_untainted)
    n_tnt = np.asarray(out.num_tainted)
    n_crd = np.asarray(out.num_cordoned)
    n_all = np.asarray(out.num_nodes)
    n_pods = np.asarray(out.num_pods)
    if ordered:
        # device->host copies of the [pad_nodes] order arrays only when the
        # windows will actually be read — on the light path these are
        # placeholder permutations and the transfer would be pure waste
        down = np.asarray(out.scale_down_order)
        up = np.asarray(out.untaint_order)
        u_off = np.asarray(out.untainted_offsets)
        t_off = np.asarray(out.tainted_offsets)
    elif node_masks is not None:
        # unordered membership from the decided node view (no sort ran)
        nvalid = np.asarray(node_masks.valid)
        ntainted = np.asarray(node_masks.tainted)
        ncordoned = np.asarray(node_masks.cordoned)
        untainted_mask = nvalid & ~ntainted & ~ncordoned
        tainted_mask = nvalid & ntainted & ~ncordoned
    reap = np.asarray(out.reap_mask)
    remaining = np.asarray(out.node_pods_remaining)

    results: List[GroupDecision] = []
    for gi, (_pods, _nodes, _config, _state) in enumerate(group_inputs):
        decision = semantics.Decision(
            status=semantics.DecisionStatus(int(status[gi])),
            nodes_delta=int(delta[gi]),
            cpu_percent=float(cpu_pct[gi]),
            mem_percent=float(mem_pct[gi]),
            cpu_request_milli=int(cpu_req[gi]),
            mem_request_bytes=int(mem_req[gi]),
            cpu_capacity_milli=int(cpu_cap[gi]),
            mem_capacity_bytes=int(mem_cap[gi]),
            num_untainted=int(n_unt[gi]),
            num_tainted=int(n_tnt[gi]),
            num_cordoned=int(n_crd[gi]),
            num_nodes=int(n_all[gi]),
            num_pods=int(n_pods[gi]),
        )
        if ordered:
            down_nodes = [
                flat_nodes[i] for i in down[u_off[gi] : u_off[gi + 1]]
            ]
            up_nodes = [
                flat_nodes[i] for i in up[t_off[gi] : t_off[gi + 1]]
            ]
        else:
            down_nodes, up_nodes = [], []
        results.append(
            GroupDecision(
                decision=decision,
                scale_down_order=down_nodes,
                untaint_order=up_nodes,
            )
        )
    if not ordered and node_masks is not None:
        # membership lists by the packer's contiguous per-group node ranges
        # (the same layout the reap slicing below relies on)
        base = 0
        for gi, (_pods, nodes, _config, _state) in enumerate(group_inputs):
            idxs = range(base, base + len(nodes))
            results[gi].scale_down_order = [
                flat_nodes[i] for i in idxs if untainted_mask[i]
            ]
            results[gi].untaint_order = [
                flat_nodes[i] for i in idxs if tainted_mask[i]
            ]
            base += len(nodes)
    # reap + pods-remaining are flat-indexed; slice out each group's node range
    base = 0
    for gi, (_pods, nodes, _config, _state) in enumerate(group_inputs):
        idxs = range(base, base + len(nodes))
        results[gi].reap_nodes = [flat_nodes[i] for i in idxs if reap[i]]
        results[gi].node_pods_remaining = {
            flat_nodes[i].name: int(remaining[i]) for i in idxs
        }
        base += len(nodes)
    return results


def _kernel_impl() -> str:
    """Aggregation sweep selector (see ops.kernel.default_impl)."""
    from escalator_tpu.ops.kernel import default_impl

    return default_impl()


class PackingPostPass:
    """Packing-aware delta override (GroupConfig.packing_aware): for OK groups
    not in a scale-down zone, replace the average-based delta with the FFD
    overflow count from the device kernel ``ops.binpack.ffd_pack`` — the exact
    array analog of the golden model's ``semantics.packing_scale_up_delta``.

    Shared by every array backend: object-level backends assemble inputs from
    their ``group_inputs`` (:meth:`apply`); the event-driven native backend
    assembles the same tuples from its store columns under the store lock and
    calls :meth:`apply_arrays` directly. High-water power-of-two pads keep the
    jit cache to a handful of shapes as group sizes fluctuate."""

    def __init__(self):
        self._pad_pods = 0
        self._pad_bins = 0
        self._pad_groups = 0

    @staticmethod
    def select(results, group_inputs) -> List[int]:
        """Indices of groups whose delta the packing pass replaces: configured
        packing_aware, status OK, and not already a scale-down decision."""
        sel = []
        for gi, (_pods, _nodes, config, _state) in enumerate(group_inputs):
            d = results[gi].decision
            if (
                getattr(config, "packing_aware", False)
                and d.status == semantics.DecisionStatus.OK
                and d.nodes_delta >= 0
            ):
                sel.append(gi)
        return sel

    def apply(self, results, group_inputs, dry_mode_flags=None,
              taint_trackers=None) -> None:
        """Object-level assembly (lister-walking backends)."""
        sel = self.select(results, group_inputs)
        if not sel:
            return
        sel_data = []
        for gi in sel:
            pods, nodes, config, state = group_inputs[gi]
            dry = bool(dry_mode_flags[gi]) if dry_mode_flags else False
            tracker = taint_trackers[gi] if taint_trackers else None
            untainted, _, _ = semantics.filter_nodes(nodes, dry, tracker)
            reqs = [k8s.compute_pod_resource_request(p) for p in pods]
            sel_data.append((
                gi,
                np.array([r.cpu_milli for r in reqs], np.int64),
                np.array([r.mem_bytes for r in reqs], np.int64),
                np.array([n.cpu_allocatable_milli for n in untainted], np.int64),
                np.array([n.mem_allocatable_bytes for n in untainted], np.int64),
                (state.cached_cpu_milli, state.cached_mem_bytes),
                int(config.packing_budget),
            ))
        self.apply_arrays(results, sel_data)

    def apply_arrays(self, results, sel_data) -> None:
        """sel_data: [(group_index, pod_cpu[int64], pod_mem, bin_cpu, bin_mem,
        (template_cpu, template_mem), budget)]. Runs ONE vmapped device FFD
        for all selected groups and overwrites their decisions' nodes_delta.
        FFD time is recorded in the solver_packing_latency histogram."""
        if not sel_data:
            return
        t0 = time.perf_counter()
        try:
            from escalator_tpu.ops import binpack
        except ImportError:
            binpack = None

        # groups the kernel cannot size virtual nodes for take the reference's
        # +1 no-cache convention (pkg/controller/util.go:20-24) without a device call
        device_rows = []
        for row in sel_data:
            gi, pod_cpu, _m, _bc, _bm, template, _b = row
            if pod_cpu.size == 0:
                results[gi].decision.nodes_delta = 0
            elif template[0] == 0 or template[1] == 0:
                results[gi].decision.nodes_delta = 1
            else:
                device_rows.append(row)
        if not device_rows:
            return
        if binpack is None:
            # jax-less install (golden/grpc-fallback deployments): the pure
            # FFD is the same math, just per group on the host
            for gi, pc, pm, bc, bm, template, budget in device_rows:
                _, used, unplaced = semantics.ffd_pack_pure(
                    list(zip(pc.tolist(), pm.tolist(), strict=True)),
                    list(zip(bc.tolist(), bm.tolist(), strict=True)),
                    template, budget,
                )
                results[gi].decision.nodes_delta = used + unplaced
            metrics.solver_packing_latency.observe(time.perf_counter() - t0)
            return

        self._pad_pods = max(
            self._pad_pods, _round_up(max(r[1].size for r in device_rows))
        )
        self._pad_bins = max(
            self._pad_bins, _round_up(max(r[3].size for r in device_rows), 8)
        )
        # one call per distinct budget: the virtual-bin count is a static
        # kernel shape, and padding it would let FFD spill past the configured
        # budget — diverging from the golden model's exact-budget packing.
        # Budgets are config values, so distinct ones stay few and the jit
        # cache stays small.
        by_budget: Dict[int, list] = {}
        for row in device_rows:
            by_budget.setdefault(row[6], []).append(row)
        for budget, rows in by_budget.items():
            self._pad_groups = max(self._pad_groups, _round_up(len(rows), 4))
            Gp, P, M = self._pad_groups, self._pad_pods, self._pad_bins
            pod_cpu = np.zeros((Gp, P), np.int64)
            pod_mem = np.zeros((Gp, P), np.int64)
            pod_valid = np.zeros((Gp, P), bool)
            bin_cpu = np.zeros((Gp, M), np.int64)
            bin_mem = np.zeros((Gp, M), np.int64)
            bin_valid = np.zeros((Gp, M), bool)
            t_cpu = np.ones(Gp, np.int64)
            t_mem = np.ones(Gp, np.int64)
            for i, (_gi, pc, pm, bc, bm, template, _b) in enumerate(rows):
                pod_cpu[i, : pc.size] = pc
                pod_mem[i, : pm.size] = pm
                pod_valid[i, : pc.size] = True
                bin_cpu[i, : bc.size] = bc
                bin_mem[i, : bm.size] = bm
                bin_valid[i, : bc.size] = True
                t_cpu[i], t_mem[i] = template
            pack = binpack.ffd_pack(
                pod_cpu, pod_mem, pod_valid, bin_cpu, bin_mem, bin_valid,
                t_cpu, t_mem, new_bin_budget=budget,
            )
            needed = np.asarray(pack.new_nodes_needed) + np.asarray(pack.unplaced)
            for i, (gi, *_rest) in enumerate(rows):
                results[gi].decision.nodes_delta = int(needed[i])
        metrics.solver_packing_latency.observe(time.perf_counter() - t0)


def _overlap_default() -> bool:
    """Host/device overlap default (round 10): on unless
    ESCALATOR_TPU_TICK_OVERLAP disables it. Overlap changes NO decision —
    only where the tick blocks: an ordered decide's dispatch returns
    unfenced and the unpack's first device read absorbs the tail, so the
    host-side result prep runs while the device still sorts."""
    import os

    return os.environ.get("ESCALATOR_TPU_TICK_OVERLAP", "1").lower() in (
        "1", "true", "yes")


def _lazy_decide(nodes, dispatch, overlap: bool = False):
    """The lazy-orders gate shared by every array backend
    (kernel.lazy_orders_decide): ``nodes`` is the packed/stacked host-side
    node section carrying the dry-mode taint view — the decided snapshot —
    and ``dispatch(with_orders) -> DecisionArrays`` runs one decide on
    whichever program variant the caller owns. Returns ``(out, ordered)``
    for :func:`_unpack`. One implementation so the gate condition can never
    drift between backends — and the shared span site, so every array
    backend's flight record names its decide variant the same way
    (``decide_ordered`` = the program with the node-ordering tail,
    ``decide_light`` = the lazy steady-state program).

    ``overlap=True`` leaves ORDERED dispatches unfenced (phase recorded
    ``fenced=False`` — dispatch time only): no gate read follows them, so
    the caller's unpack can overlap its host assembly with the in-flight
    device program. The light dispatch stays fenced — the protocol's
    nodes_delta gate synchronizes on the program immediately anyway."""
    from escalator_tpu.ops.kernel import lazy_orders_decide

    tainted_any = bool(
        (np.asarray(nodes.valid) & np.asarray(nodes.tainted)).any())

    def instrumented(w):
        with obs.span("decide_ordered" if w else "decide_light",
                      kind="device"):
            out = dispatch(w)
            if not (overlap and w):
                out = obs.fence(out)
            return out

    return lazy_orders_decide(instrumented, tainted_any)


class JaxBackend(ComputeBackend):
    """Single-device (or data-parallel-free) batched kernel. The jit cache is keyed
    on padded shapes; capacities grow by powers of two."""

    name = "jax"

    def __init__(self, impl: Optional[str] = None,
                 overlap: Optional[bool] = None):
        from escalator_tpu.ops import kernel  # defers jax import

        self._kernel = kernel
        self._packer = PaddedPacker()
        self._impl = impl if impl is not None else _kernel_impl()
        self._packing = PackingPostPass()
        self._overlap = overlap if overlap is not None else _overlap_default()
        obs.jaxmon.install()

    def decide(self, group_inputs, now_sec, dry_mode_flags=None, taint_trackers=None):
        with obs.span(self.name):
            obs.annotate(backend=self.name, impl=self._impl)
            t0 = time.perf_counter()
            with obs.span("pack"):
                cluster = self._packer.pack(
                    group_inputs, dry_mode_flags, taint_trackers)
            t1 = time.perf_counter()
            # lazy-orders protocol: same economics as the native backend — no
            # node-ordering sort on steady ticks (gate shared via _lazy_decide)
            with obs.span("decide", kind="device"):
                out, ordered = _lazy_decide(
                    cluster.nodes,
                    lambda w: self._kernel.decide_jit(
                        cluster, np.int64(now_sec), impl=self._impl,
                        with_orders=w),
                    overlap=self._overlap,
                )
                if not (self._overlap and ordered):
                    obs.fence(out)
            t2 = time.perf_counter()
            metrics.solver_pack_latency.labels(self.name).observe(t1 - t0)
            metrics.solver_decide_latency.labels(self.name).observe(t2 - t1)
            obs.annotate(ordered=bool(ordered))
            with obs.span("unpack"):
                results = _unpack(
                    out, group_inputs, ordered=ordered,
                    node_masks=cluster.nodes,
                    dispatch_end=t2 if self._overlap and ordered else None)
            # digest reads force a device sync, so on an overlapped tick it
            # runs after unpack's first read (arrays are host-ready by then)
            _annotate_decision(self.name, out)
            with obs.span("packing_post"):
                self._packing.apply(
                    results, group_inputs, dry_mode_flags, taint_trackers)
            return results


def _snapshot_config(snapshot_dir, snapshot_every):
    """Resolve the checkpoint knobs: explicit params win, else the env pair
    (ESCALATOR_TPU_SNAPSHOT_DIR / ESCALATOR_TPU_SNAPSHOT_EVERY) the CLI and
    deployments set. ``(None, n)`` means checkpointing is off."""
    import os

    if snapshot_dir is None:
        snapshot_dir = os.environ.get("ESCALATOR_TPU_SNAPSHOT_DIR") or None
    if snapshot_every is None:
        snapshot_every = int(os.environ.get(
            "ESCALATOR_TPU_SNAPSHOT_EVERY", "64"))
    return snapshot_dir, int(snapshot_every)


def _changed_slots(old_soa, new_soa) -> np.ndarray:
    """Lane indices where ANY column differs between two packed SoA views —
    the host-diff delta extraction IncrementalJaxBackend feeds the scatter
    path (vectorized numpy compares; O(cluster) host time, microseconds per
    100k lanes, in exchange for O(churn) device work)."""
    changed = None
    for f in old_soa.__dataclass_fields__:
        d = np.asarray(getattr(old_soa, f)) != np.asarray(getattr(new_soa, f))
        changed = d if changed is None else (changed | d)
    return np.nonzero(changed)[0].astype(np.int64)


class IncrementalJaxBackend(ComputeBackend):
    """Single-device repack backend with the round-8 INCREMENTAL decide.

    Same object-level contract as :class:`JaxBackend`, different economics:
    the packed cluster stays device-resident across ticks
    (ops.device_state.DeviceClusterCache); each tick re-packs on the host
    (O(cluster) numpy — unavoidable without an event source; the native
    backend removes that too), HOST-DIFFS the packed columns against the
    previous tick's, and ships only the changed lanes through the scatter +
    aggregate-delta program. The decide then runs
    ``kernel.delta_decide`` on the compacted dirty groups
    (ops.device_state.IncrementalDecider): steady-state device work is
    O(churn + dirty groups + N elementwise) instead of the full O(P) sweep.
    Dry-mode taint views are baked into the packed columns by pack_cluster,
    so the diff picks them up like any other lane change. A padded-capacity
    change (cluster growth past the high-water mark) rebuilds the residency
    and re-derives the aggregates from scratch.

    Lane stability note: the diff compares positionally, so a caller whose
    lister order reshuffles between ticks inflates the delta batch (every
    moved lane reads as changed) — NEVER the results, which depend only on
    the diff being complete. The controller's group-ordered walk is stable
    in practice; the native backend's slot-keyed store makes it structural.

    Round 12: when the cluster client exposes a watch feed,
    :meth:`attach_event_source` retires the per-tick repack + host-diff
    entirely — steady ticks then drain watch deltas as packed triples
    through the streaming engine, and this class's pack/diff path remains
    as the bootstrap/no-event-source/warm-restore configuration."""

    name = "incremental-jax"

    def __init__(self, impl: Optional[str] = None,
                 refresh_every: "Optional[int | str]" = None,
                 overlap: Optional[bool] = None,
                 snapshot_dir: Optional[str] = None,
                 snapshot_every: Optional[int] = None):
        from escalator_tpu.ops import kernel  # defers jax import

        self._kernel = kernel
        self._packer = PaddedPacker()
        self._impl = impl if impl is not None else _kernel_impl()
        self._packing = PackingPostPass()
        self._refresh_every = refresh_every
        self._overlap = overlap if overlap is not None else _overlap_default()
        self._cache = None
        self._inc = None
        self._host_prev = None   # (PodArrays, NodeArrays) of the last pack
        #: streaming upgrade (round 12): set by attach_event_source — decide
        #: then routes to an event-driven engine and the repack/diff below
        #: becomes the bootstrap/audit path only
        self._stream = None
        # failover-grade state (round 11): periodic async checkpoints of the
        # device-resident state, and a warm start from the latest checkpoint
        # at the first decide — the standby-leader path (docs/ha.md)
        snapshot_dir, snapshot_every = _snapshot_config(
            snapshot_dir, snapshot_every)
        self._snapshot_dir, self._snapshot_every = snapshot_dir, snapshot_every
        self._writer = None
        if snapshot_dir:
            from escalator_tpu.ops.snapshot import SnapshotWriter

            self._writer = SnapshotWriter(snapshot_dir, every=snapshot_every)
        self._restore_attempted = False
        self._restored_fresh = False
        obs.jaxmon.install()

    def _try_restore(self) -> bool:
        """Warm start from the rolling checkpoint (ops/snapshot.py): adopt
        the snapshot's resident state + seed the packer pads and the diff
        baseline, so the FIRST tick host-diffs the live world against the
        snapshot and folds everything that changed while no leader ran into
        one delta batch — O(changes since checkpoint) device work, no full
        decide. A corrupt/truncated snapshot falls back to the cold start
        with a flight-recorder dump; a missing one is just the first boot."""
        from escalator_tpu.ops import snapshot as snaplib
        from escalator_tpu.ops.device_state import restore_decider

        path = self._writer.path
        with obs.span("snapshot_load"):
            try:
                leaves, meta = snaplib.read_snapshot(path)
            except FileNotFoundError:
                return False
            except snaplib.SnapshotCorruptError as e:
                self._note_corrupt_snapshot(path, e)
                return False
        try:
            cache, inc = restore_decider(
                leaves, meta, impl=self._impl,
                refresh_every=self._refresh_every, on_mismatch="repair",
                overlap=self._overlap)
        except snaplib.SnapshotCorruptError as e:
            self._note_corrupt_snapshot(path, e)
            return False
        self._cache, self._inc = cache, inc
        self._host_prev = cache.host_views
        self._packer.seed(cache.pod_capacity, cache.node_capacity,
                          int(meta["num_groups"]))
        self._restored_fresh = True
        metrics.snapshot_restores.labels("warm").inc()
        import logging

        logging.getLogger("escalator_tpu.backend").info(
            "warm start: restored device state from %s (tick %s)",
            path, meta.get("tick"))
        return True

    @staticmethod
    def _note_corrupt_snapshot(path: str, err: Exception) -> None:
        import logging

        metrics.snapshot_restores.labels("corrupt").inc()
        dump = obs.dump_on_incident("snapshot-corrupt")
        logging.getLogger("escalator_tpu.backend").error(
            "snapshot %s failed validation (%s); cold-starting instead "
            "(flight record: %s)", path, err, dump or "dump failed")

    def attach_event_source(self, client, node_group_options,
                            pod_capacity: int = 1 << 12,
                            node_capacity: int = 1 << 10,
                            store_kind: str = "auto",
                            relist_audit_every: "int | str | None" = None
                            ) -> None:
        """Upgrade this backend to STREAMING ingestion (the round-12
        tentpole): subscribe to ``client``'s watch feed and, from the next
        decide on, source cluster state from the event-maintained store
        instead of repacking + host-diffing the controller's object lists —
        the ``pack`` and ``host_diff`` phases disappear from steady ticks
        (watch deltas drain as packed ``(idx, values)`` triples straight
        into the same ``IncrementalDecider`` scatter), and the O(cluster)
        re-list survives only as bootstrap and the optional
        ``relist_audit_every`` reconciliation cadence.

        Implementation: the event-driven engine IS
        :class:`~escalator_tpu.controller.native_backend.NativeJaxBackend`
        with the incremental decide — slot-keyed store, bridge-resolved
        result objects — so attaching constructs one with this backend's
        exact decide configuration (refresh cadence, overlap, checkpoint
        dir) and flips ``needs_objects`` False (the controller then skips
        its per-tick lister walk). Flight records keep this backend's name.

        Round 18 closes the warm-restore caveat this method used to carry:
        when checkpointing is configured, the native engine's snapshots
        include a slot->key sidecar and the constructed stream passes
        ``warm_restore=True`` — after a restart it replays the snapshot's
        ingestion-ordered slot layout into a fresh store, adopts the device
        state, and resyncs only what changed while no leader ran, so a
        standby no longer has to stay on the repack path to warm-start
        (docs/ha.md)."""
        from escalator_tpu.controller.native_backend import (
            NativeJaxBackend,
            group_filters_from_options,
        )

        stream = NativeJaxBackend(
            client, group_filters_from_options(node_group_options),
            pod_capacity=pod_capacity, node_capacity=node_capacity,
            incremental=True, refresh_every=self._refresh_every,
            overlap=self._overlap, snapshot_dir=self._snapshot_dir,
            snapshot_every=self._snapshot_every, store_kind=store_kind,
            relist_audit_every=relist_audit_every,
            warm_restore=bool(self._snapshot_dir),
        )
        stream.name = self.name   # one logical backend in records/metrics
        self._stream = stream
        self.needs_objects = False

    def decide(self, group_inputs, now_sec, dry_mode_flags=None, taint_trackers=None):
        if self._stream is not None:
            return self._stream.decide(
                group_inputs, now_sec, dry_mode_flags, taint_trackers)
        with obs.span(self.name):
            obs.annotate(backend=self.name, impl=self._impl)
            return self._decide_inner(
                group_inputs, now_sec, dry_mode_flags, taint_trackers)

    def _decide_inner(self, group_inputs, now_sec, dry_mode_flags,
                      taint_trackers):
        from escalator_tpu.ops.device_state import (
            DeviceClusterCache,
            IncrementalDecider,
        )

        t0 = time.perf_counter()
        if (self._cache is None and self._writer is not None
                and not self._restore_attempted):
            # first decide of this process: probe the rolling checkpoint
            # BEFORE packing, so a warm start can seed the packer pads to
            # the snapshot's shapes (a pad mismatch would force a rebuild)
            self._restore_attempted = True
            self._try_restore()
        with obs.span("pack"):
            cluster = self._packer.pack(
                group_inputs, dry_mode_flags, taint_trackers)
        P = int(cluster.pods.valid.shape[0])
        N = int(cluster.nodes.valid.shape[0])
        rebuild = (
            self._cache is None
            or self._cache.pod_capacity != P
            or self._cache.node_capacity != N
            # the GROUP pad is high-water too, but it can grow while the
            # pod/node pads stand still (a 9th nodegroup, few new lanes) —
            # the [G]-shaped aggregates and persistent columns must rebuild
            # with it, not broadcast-crash against the resident shapes
            or int(self._cache.cluster.groups.valid.shape[0])
            != int(cluster.groups.valid.shape[0])
        )
        if rebuild and self._restored_fresh:
            # the restored snapshot's shapes no longer fit the live world
            # (cluster outgrew the checkpoint capacities): discard it and
            # cold-start — correctness never depended on the warm path
            metrics.snapshot_restores.labels("stale").inc()
            import logging

            logging.getLogger("escalator_tpu.backend").warning(
                "restored snapshot is stale for the current cluster shapes "
                "(pods %d nodes %d groups %d); cold-starting",
                P, N, int(cluster.groups.valid.shape[0]))
        self._restored_fresh = False
        if rebuild:
            with obs.span("rebuild_residency", kind="device"):
                self._cache = DeviceClusterCache(cluster)
                self._inc = IncrementalDecider(
                    self._cache, impl=self._impl,
                    refresh_every=self._refresh_every, on_mismatch="repair",
                    overlap=self._overlap)
                obs.fence(self._cache.cluster)
        else:
            with obs.span("host_diff"):
                pod_slots = _changed_slots(self._host_prev[0], cluster.pods)
                node_slots = _changed_slots(self._host_prev[1], cluster.nodes)
                self._cache.set_host(cluster.pods, cluster.nodes)
                gathered = self._cache.gather_deltas(pod_slots, node_slots)
            with obs.span("scatter", kind="device"):
                # NOT fenced: the scatter dispatch pipelines into the decide
                # dispatch (the whole point of the incremental path); a host
                # sync here would regress the tick to measure it. The decide
                # span absorbs the scatter tail; this phase is dispatch-only.
                self._inc.apply_gathered(gathered, cluster.groups)
        # pack_cluster allocates fresh arrays every call, so keeping the
        # references IS the snapshot — no copy
        self._host_prev = (cluster.pods, cluster.nodes)
        t1 = time.perf_counter()
        tainted_any = bool(
            (np.asarray(cluster.nodes.valid)
             & np.asarray(cluster.nodes.tainted)).any())
        with obs.span("decide", kind="device"):
            out, ordered = self._inc.decide(now_sec, tainted_any)
            if not (self._overlap and ordered):
                obs.fence(out)
        t2 = time.perf_counter()
        metrics.solver_pack_latency.labels(self.name).observe(t1 - t0)
        metrics.solver_decide_latency.labels(self.name).observe(t2 - t1)
        obs.annotate(ordered=bool(ordered))
        with obs.span("unpack"):
            results = _unpack(
                out, group_inputs, ordered=ordered, node_masks=cluster.nodes,
                dispatch_end=t2 if self._overlap and ordered else None,
                pre_synced=self._inc.last_decide_synced)
        _annotate_decision(self.name, out)
        with obs.span("packing_post"):
            self._packing.apply(
                results, group_inputs, dry_mode_flags, taint_trackers)
        if self._writer is not None and self._inc is not None:
            # cadence checkpoint: freeze + D2H on the tick thread (cheap,
            # amortized), serialization + atomic write on the writer thread
            with obs.span("checkpoint"):
                self._writer.maybe_checkpoint(self._inc)
        return results


class ShardedJaxBackend(ComputeBackend):
    """Nodegroup axis sharded over a device mesh (escalator_tpu.parallel.mesh)."""

    name = "sharded-jax"

    def __init__(self, mesh=None, impl: Optional[str] = None):
        from escalator_tpu.parallel import mesh as meshlib

        self._meshlib = meshlib
        self._mesh = mesh if mesh is not None else meshlib.make_mesh()
        self._init_common(impl)
        self._decider = meshlib.make_sharded_decider(self._mesh, impl=self._impl)
        self._decider_light = meshlib.make_sharded_decider(
            self._mesh, impl=self._impl, with_orders=False)
        self._num_shards = self._mesh.devices.size

    def _init_common(self, impl: Optional[str]) -> None:
        """State shared with GridJaxBackend (which builds its own mesh and
        decider but inherits decide() and therefore all of this)."""
        self._impl = impl if impl is not None else _kernel_impl()
        self._packing = PackingPostPass()
        # high-water-mark per-shard pads: same recompile-avoidance as JaxBackend
        self._pad_pods = 0
        self._pad_nodes = 0
        self._pad_groups = 0
        obs.jaxmon.install()

    def _place(self, sharded):
        """Placement hook: how the stacked [S, ...] cluster lands on the mesh
        (GridJaxBackend overrides with the 2-D grid layout)."""
        return self._meshlib.shard_cluster_arrays(sharded, self._mesh)

    def decide(self, group_inputs, now_sec, dry_mode_flags=None, taint_trackers=None):
        with obs.span(self.name):
            obs.annotate(backend=self.name, impl=self._impl)
            return self._decide_inner(
                group_inputs, now_sec, dry_mode_flags, taint_trackers)

    def _decide_inner(self, group_inputs, now_sec, dry_mode_flags,
                      taint_trackers):
        import jax

        t0 = time.perf_counter()
        with obs.span("pack"):
            assignment = self._meshlib.assign_shards(
                group_inputs, self._num_shards)
            max_pods, max_nodes, max_groups = self._meshlib.shard_capacity(
                group_inputs, assignment
            )
            self._pad_pods = max(self._pad_pods, _round_up(max_pods))
            self._pad_nodes = max(self._pad_nodes, _round_up(max_nodes))
            self._pad_groups = max(self._pad_groups, _round_up(max_groups, 8))
            sharded, assignment = self._meshlib.pack_cluster_sharded(
                group_inputs,
                num_shards=self._num_shards,
                pad_pods_per_shard=self._pad_pods,
                pad_nodes_per_shard=self._pad_nodes,
                pad_groups_per_shard=self._pad_groups,
                dry_mode_flags=dry_mode_flags,
                taint_trackers=taint_trackers,
            )
        with obs.span("place", kind="device"):
            placed = obs.fence(self._place(sharded))
        t1 = time.perf_counter()
        # lazy-orders protocol across the mesh: under vmap the ordered
        # variant can never skip its sorts dynamically (cond lowers to
        # select), so the static light decider is the only sort-free
        # steady-state path on sharded backends (gate shared: _lazy_decide)
        with obs.span("decide", kind="device"):
            out, ordered = _lazy_decide(
                sharded.nodes,
                lambda w: jax.block_until_ready(
                    (self._decider if w else self._decider_light)(
                        placed, np.int64(now_sec))),
            )
            obs.fence(out)
        t2 = time.perf_counter()
        metrics.solver_pack_latency.labels(self.name).observe(t1 - t0)
        metrics.solver_decide_latency.labels(self.name).observe(t2 - t1)
        obs.annotate(ordered=bool(ordered))
        _annotate_decision(self.name, out)

        # Reassemble per-shard outputs back to the caller's group order.
        with obs.span("unpack"):
            results: List[Optional[GroupDecision]] = [None] * len(group_inputs)
            leaves, aux = out.tree_flatten()
            nodes_t = type(sharded.nodes)
            for s, shard_groups in enumerate(assignment):
                shard_out = type(out).tree_unflatten(
                    aux, [np.asarray(leaf[s]) for leaf in leaves]
                )
                shard_inputs = [group_inputs[gi] for gi in shard_groups]
                # mask views are only read on the light path (_unpack ignores
                # them when ordered); skip building the per-shard SoA otherwise
                shard_masks = nodes_t(**{
                    f: np.asarray(getattr(sharded.nodes, f))[s]
                    for f in nodes_t.__dataclass_fields__
                }) if not ordered else None
                shard_results = _unpack(shard_out, shard_inputs,
                                        ordered=ordered,
                                        node_masks=shard_masks)
                for local, gi in enumerate(shard_groups):
                    results[gi] = shard_results[local]
        # PackingPostPass.select indexes results[gi] by group_inputs position,
        # so it must see the UNfiltered list — a partial assignment filtered
        # first would silently repack the wrong groups' deltas
        assert all(r is not None for r in results), (
            "assign_shards must cover every group"
        )
        with obs.span("packing_post"):
            self._packing.apply(
                results, group_inputs, dry_mode_flags, taint_trackers)
        return results


class GridJaxBackend(ShardedJaxBackend):
    """2-D grid (groups x pods) mesh backend (parallel.grid): nodegroups
    shard over the mesh ROWS exactly like ShardedJaxBackend (decisions stay
    communication-free and the decide tail — percent math + both node
    orderings — shards with them), while each group block's pod axis
    additionally splits over the mesh COLUMNS with one psum combining the
    pod partial sums. Bit-identical decisions (tests/test_grid.py).

    Use when BOTH axes are big: more pods per group block than one chip
    sweeps comfortably, but still several groups (the few-huge-groups
    cluster). One giant group degenerates to num_group_shards=1 (pure
    pod-axis, the PodAxisJaxBackend regime); many small groups want
    num_group_shards=devices (pure group-axis, ShardedJaxBackend's layout,
    but priced with an extra trivial psum)."""

    name = "grid-jax"

    def __init__(self, mesh=None, impl: Optional[str] = None,
                 num_group_shards: Optional[int] = None):
        from escalator_tpu.parallel import grid as gridlib
        from escalator_tpu.parallel import mesh as meshlib

        self._meshlib = meshlib
        self._gridlib = gridlib
        if mesh is None:
            import jax

            ndev = len(jax.devices())
            if num_group_shards is None:
                # default split: half the devices to each axis when possible —
                # shapes skewed enough to want an extreme split should pass
                # num_group_shards explicitly
                num_group_shards = ndev // 2 if ndev % 2 == 0 else ndev
            mesh = gridlib.make_grid_mesh(num_group_shards=num_group_shards)
        else:
            # fail at construction, not deep inside the first decide(): the
            # grid layout needs exactly these two axes
            expected = (meshlib.GROUP_AXIS, gridlib.POD_AXIS)
            if tuple(mesh.axis_names) != expected:
                raise ValueError(
                    f"grid mesh must have axes {expected}, got "
                    f"{tuple(mesh.axis_names)} (use grid.make_grid_mesh)"
                )
            if num_group_shards is not None and (
                int(mesh.shape[meshlib.GROUP_AXIS]) != num_group_shards
            ):
                # an explicit mesh carries its own split; silently dropping
                # the caller's requested one would hide the misconfiguration
                raise ValueError(
                    f"num_group_shards={num_group_shards} conflicts with the "
                    "explicit mesh's groups axis of "
                    f"{mesh.shape[meshlib.GROUP_AXIS]}"
                )
        self._mesh = mesh
        self._init_common(impl)
        self._decider = gridlib.make_grid_decider(self._mesh, impl=self._impl)
        self._decider_light = gridlib.make_grid_decider(
            self._mesh, impl=self._impl, with_orders=False)
        self._num_shards = int(self._mesh.shape[meshlib.GROUP_AXIS])

    def _place(self, sharded):
        return self._gridlib.place_grid(sharded, self._mesh)


class PodAxisJaxBackend(ComputeBackend):
    """Pod-axis-sharded kernel (parallel.podaxis): the flat pod axis is split
    over the device mesh and partial segment sums psum together. Use when ONE
    group dominates the pod count — group-axis sharding (ShardedJaxBackend)
    cannot split a single giant group, this can. Bit-identical decisions.

    Transfer note: unlike the native/event-driven path (DeviceClusterCache),
    this backend re-places the full packed cluster each tick — per-tick
    host->device traffic is O(cluster), not O(changes). The placement is at
    least split across devices (podaxis.place shards the big pod axis), but
    callers with tiny churn and huge clusters should prefer the native
    backend; this one targets the few-groups/many-pods decide-bound regime.

    Busy ticks (round 6): the ordered decide runs with the GROUP-BLOCK-
    SHARDED ordering tail (ops.order_tail) — the backend partitions the
    packed node lanes into per-device blocks each tick (O(N) numpy,
    high-water padded so the jit cache stays small), so a drain tick's
    combined sort shards across the mesh instead of replicating on every
    device (bench cfg8 measured that replication at 218 of 241 ms)."""

    name = "podaxis-jax"

    def __init__(self, mesh=None, impl: Optional[str] = None):
        from escalator_tpu.ops import order_tail
        from escalator_tpu.parallel import mesh as meshlib, podaxis

        self._podaxis = podaxis
        self._order_tail = order_tail
        self._mesh = mesh if mesh is not None else meshlib.make_mesh()
        self._impl = impl if impl is not None else _kernel_impl()
        self._decider = podaxis.make_podaxis_decider(self._mesh, impl=self._impl)
        self._decider_light = podaxis.make_podaxis_decider(
            self._mesh, impl=self._impl, with_orders=False)
        self._packer = PaddedPacker()
        self._packing = PackingPostPass()
        self._block_pad = 0
        obs.jaxmon.install()

    def _node_blocks(self, cluster):
        """Per-tick contiguous-group block map for the sharded ordering tail,
        high-water padded (same recompile-avoidance as every other pad)."""
        blocks = self._order_tail.assign_order_blocks(
            np.asarray(cluster.nodes.group), np.asarray(cluster.nodes.valid),
            int(self._mesh.devices.size),
            num_groups=int(cluster.groups.valid.shape[0]),
        )
        self._block_pad = max(self._block_pad, _round_up(blocks.shape[1], 8))
        return self._order_tail.pad_order_blocks(blocks, self._block_pad)

    def decide(self, group_inputs, now_sec, dry_mode_flags=None, taint_trackers=None):
        import jax

        with obs.span(self.name):
            obs.annotate(backend=self.name, impl=self._impl)
            t0 = time.perf_counter()
            with obs.span("pack"):
                cluster = self._packer.pack(
                    group_inputs, dry_mode_flags, taint_trackers)
            with obs.span("place", kind="device"):
                placed = obs.fence(self._podaxis.place(
                    self._podaxis.pad_pods_for_mesh(cluster, self._mesh),
                    self._mesh,
                ))
            t1 = time.perf_counter()
            # lazy-orders protocol: this path's replicated decide tail IS the
            # node sort (podaxis.py cost model), so the light variant removes
            # the dominant replicated term on steady ticks (gate: _lazy_decide);
            # a busy tick pays the BLOCK-SHARDED sort, not the replicated one.
            # The block map is built inside the dispatch, ordered branch only —
            # steady ticks (the common case) never pay its O(N) host argsort
            with obs.span("decide", kind="device"):
                out, ordered = _lazy_decide(
                    cluster.nodes,
                    lambda w: jax.block_until_ready(
                        self._decider(placed, np.int64(now_sec),
                                      self._node_blocks(cluster))
                        if w else self._decider_light(placed, np.int64(now_sec))),
                )
                obs.fence(out)
            t2 = time.perf_counter()
            metrics.solver_pack_latency.labels(self.name).observe(t1 - t0)
            metrics.solver_decide_latency.labels(self.name).observe(t2 - t1)
            obs.annotate(ordered=bool(ordered))
            _annotate_decision(self.name, out)
            with obs.span("unpack"):
                results = _unpack(out, group_inputs, ordered=ordered,
                                  node_masks=cluster.nodes)
            with obs.span("packing_post"):
                self._packing.apply(
                    results, group_inputs, dry_mode_flags, taint_trackers)
            return results


def make_backend(kind: str = "auto") -> ComputeBackend:
    """auto: sharded-jax when >1 device, jax when jax imports, else golden.
    podaxis-jax and grid-jax must be chosen explicitly — both pay a psum per
    tick; podaxis-jax wins when ONE group holds most of the pods, grid-jax
    when a few huge groups do (its 2-D mesh shards the decide tail too —
    see parallel/grid.py's cost model).

    Every jax-dispatching kind probes the accelerator first
    (jaxconfig.ensure_responsive_accelerator, cached process-wide): a wedged
    transport must degrade the solver to XLA-CPU, not hang the first
    dispatch. Centralized HERE so new entry points that construct a backend
    are safe by construction — sim.py's --sweep-deltas hang against a
    wedged tunnel came from exactly this guard living only in cli.py.
    Golden needs no probe (no jax); grpc backends are constructed
    elsewhere (their compute is remote)."""
    if kind == "golden":
        return GoldenBackend()
    if kind not in ("jax", "incremental-jax", "sharded-jax", "grid-jax",
                    "podaxis-jax", "auto"):
        raise ValueError(f"unknown backend {kind!r}")
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    if kind == "jax":
        return JaxBackend()
    if kind == "incremental-jax":
        return IncrementalJaxBackend()
    if kind == "sharded-jax":
        return ShardedJaxBackend()
    if kind == "grid-jax":
        return GridJaxBackend()
    if kind == "podaxis-jax":
        return PodAxisJaxBackend()
    try:
        import jax

        if len(jax.devices()) > 1:
            return ShardedJaxBackend()
        return JaxBackend()
    except Exception:
        return GoldenBackend()
