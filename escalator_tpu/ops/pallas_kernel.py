"""Pallas TPU kernel for the hot aggregation op: exact int64 segment sums.

The decision kernel's dominant data-shaped work is segment-summing the flat
``[P]`` pod and ``[N]`` node columns into ``[G]`` per-group aggregates
(replacing the reference's per-pod Go loops, /root/reference/pkg/k8s/util.go:27-51).
XLA lowers ``jax.ops.segment_sum`` to one scatter-add per column — eight
independent sweeps over the arrays. This module fuses them into ONE Pallas
sweep that rides the MXU:

- the packer stores pods/nodes group-contiguously, so each tile of ``T=512``
  lanes touches a narrow, contiguous window of group ids; the tile's
  contribution to the per-group totals is then a one-hot matmul
  ``onehot[W, T] @ columns[T, C]`` — the classic TPU recipe for sorted-segment
  reduction (scatter becomes a systolic-array contraction);
- int64 columns are decomposed into six 8-bit limbs lifted to f32. 8-bit
  integers survive the MXU's bf16 input rounding exactly (f32 matmuls on TPU
  run as bf16 passes by default), per-tile partial sums stay below 2^24 where
  f32 accumulation is exact, and the on-chip cross-tile accumulator is int32
  (exact below 2^31 — safe for ≤ 2^23 lanes). The limbs recombine to int64
  outside the kernel. The result is **bit-exact** against the XLA scatter
  path for values < 2^48;
- per-tile window bases ride in as scalar-prefetch arguments (SMEM), aligned
  down to the 128-lane boundary so the accumulator store is a static-size,
  aligned dynamic slice.

The group-contiguity invariant can be broken by the device-resident
incremental path (``ops.device_state`` reuses free slots across groups). That
no longer forces the scatter fallback: when the layout check fails, the
wrapper SORTS the lanes by group id on device (one argsort + gathers — cheap
next to eight scatter sweeps) and runs the same windowed kernel on the sorted
layout, so the event-driven native tick rides the MXU even on churned
clusters with interleaved slots. The XLA scatter path remains only for the
genuinely incompatible cases: values ≥ 2^48 (256 TB memory requests) exceed
the limb range, and a sorted tile can still span > MAX_SPREAD distinct groups
when groups average < ~1 lane each (tiny-group pathology). All selection is
on-device via nested ``lax.cond`` — same outputs either way, so callers see
one function; :func:`path_report` reproduces the choice for tests/benchmarks.

No reference analog: Escalator has no accelerator kernels at all (SURVEY.md
§1 "no native code"); this is the TPU-first replacement for its hot loop.

**Where it wins — measured** (bench cfg9, full-decide medians on a v5e chip,
capture TPU_BENCH_20260730T044935Z): on the CHURNED slot-reused store layout
(the pallas-sorted path this module exists for) the fused sweep runs the
decide in 0.197 ms vs XLA scatter's 0.310 ms — **1.57x faster**; on a 1M-lane
single group it is ~1.16x faster (0.257 vs 0.297 ms). On a small contiguous
layout (2048 groups / 100k pods, pallas-direct) XLA's scatter wins (0.412 vs
0.331 ms): eight small scatters fuse well, and the windowed matmul's fixed
tile overheads dominate at ~49 lanes/group. Rule of thumb: prefer
``impl="pallas"`` for the event-driven native tick (whose slot reuse churns
the layout) and for giant groups; keep the XLA default for small contiguous
repacks. ``ESCALATOR_TPU_KERNEL_IMPL=pallas`` flips every backend at once.
"""

from __future__ import annotations

import functools
import os
from typing import Dict

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

#: lanes (pods/nodes) per grid step
TILE = 512
#: group-id window per tile; contributions land in [base, base+WINDOW)
WINDOW = 512
#: window bases are aligned down to this (TPU lane/sublane friendliness)
ALIGN = 128
#: max (max_id - min_id) per tile for the fast path: base >= min-(ALIGN-1)
#: and max <= base+WINDOW-1 must hold
MAX_SPREAD = WINDOW - ALIGN
#: limb decomposition of int64 columns: LIMBS limbs of LIMB_BITS bits each.
#: 8-bit limbs are exactly representable in bf16, so the MXU's single-pass
#: bf16 f32-matmul is exact regardless of precision flags; per-tile partials
#: stay < 2^24 (exact f32 accumulation) and the cross-tile int32 accumulator
#: is exact for up to 2^23 lanes.
LIMB_BITS = 8
LIMBS = 6
#: supported value range for the fast path
MAX_VALUE = 1 << (LIMB_BITS * LIMBS)  # 2^48
#: lane-count ceiling for the fast path: per-segment limb totals must stay
#: below 2^31 in the int32 accumulator (lanes * (2^LIMB_BITS - 1) < 2^31)
MAX_LANES = 1 << 23
#: column capacity of one kernel invocation (f32 sublane multiple)
MAX_COLS = 16

_interp_env = os.environ.get("ESCALATOR_TPU_PALLAS_INTERPRET")


def _use_interpret() -> bool:
    """Interpret off-TPU (tests on the CPU backend); compiled on TPU."""
    if _interp_env is not None:
        return _interp_env not in ("0", "false", "")
    from escalator_tpu.jaxconfig import PALLAS_COMPILED_PLATFORMS

    return jax.default_backend() not in PALLAS_COMPILED_PLATFORMS


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _agg_kernel(bases_ref, ids_ref, cols_ref, out_ref):
    """One grid step: tile i's one-hot matmul contribution, accumulated.

    bases_ref: [n_tiles] int32 (SMEM, scalar-prefetched) aligned window bases
    ids_ref:   (1, 1, TILE) int32 group ids of this tile
    cols_ref:  (MAX_COLS, TILE) f32 limb/count columns of this tile
    out_ref:   (G_out, MAX_COLS) int32 running totals (whole array in VMEM)
    """
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros(out_ref.shape, out_ref.dtype)

    base = bases_ref[i]
    rel = ids_ref[0, 0, :] - base  # (TILE,) in [0, WINDOW) for in-window lanes
    lane = jax.lax.broadcasted_iota(jnp.int32, (WINDOW, TILE), 0)
    onehot = (lane == jnp.broadcast_to(rel[None, :], (WINDOW, TILE))).astype(
        jnp.float32
    )
    # (WINDOW, TILE) @ (MAX_COLS, TILE)^T -> (WINDOW, MAX_COLS) on the MXU;
    # every partial is an integer < 2^24, exact in f32.
    contrib = lax.dot_general(
        onehot,
        cols_ref[:, :],
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    win = pl.ds(base, WINDOW)
    out_ref[win, :] = out_ref[win, :] + contrib.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("num_segments", "interpret"))
def _pallas_partials(ids, cols, bases, num_segments: int, interpret: bool):
    """[G_out, MAX_COLS] int32 totals from the tiled one-hot-matmul sweep."""
    n_tiles = ids.shape[0]
    g_out = _round_up(num_segments, ALIGN) + WINDOW
    # index maps must emit int32: under jax_enable_x64 a Python literal 0
    # traces as i64, which Mosaic refuses to legalize in the block-transform
    # function — np.int32 keeps the dtype without capturing a tracer
    zero = np.int32(0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_tiles,),
        in_specs=[
            pl.BlockSpec(
                (1, 1, TILE), lambda i, *_: (i, zero, zero), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (MAX_COLS, TILE), lambda i, *_: (zero, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (g_out, MAX_COLS), lambda i, *_: (zero, zero), memory_space=pltpu.VMEM
        ),
    )
    return pl.pallas_call(
        _agg_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((g_out, MAX_COLS), jnp.int32),
        interpret=interpret,
    )(bases, ids, cols)


def fused_segment_sums(
    ids,
    valid,
    int_columns: Dict[str, jnp.ndarray],
    count_columns: Dict[str, jnp.ndarray],
    num_segments: int,
    interpret: bool | None = None,
) -> Dict[str, jnp.ndarray]:
    """Exact per-segment sums of all columns in one fused device sweep.

    ids:           [P] int32 segment (group) ids
    valid:         [P] bool; invalid lanes contribute nothing
    int_columns:   name -> [P] int64 (values must be pre-masked: invalid
                   lanes zero). Fast path requires 0 <= v < 2^48.
    count_columns: name -> [P] bool/int 0-1 weights (pre-masked likewise)
    returns        name -> [num_segments] int64

    Chooses between the Pallas windowed-matmul sweep and plain XLA
    ``segment_sum`` per column with ``lax.cond``, based on on-device
    precondition checks (group-contiguous layout, value range). Traceable;
    fixed shapes; jit-safe.
    """
    n_int = len(int_columns)
    n_cnt = len(count_columns)
    if n_int * LIMBS + n_cnt > MAX_COLS:
        raise ValueError("too many columns for one fused sweep")
    if interpret is None:
        interpret = _use_interpret()

    P = ids.shape[0]
    if P > MAX_LANES:
        # beyond the int32 accumulator's exactness bound: the scatter path is
        # the only exact option (static shapes, so this is a trace-time branch)
        ids32 = ids.astype(jnp.int32)
        out = {}
        for name, col in {**int_columns, **count_columns}.items():
            out[name] = jax.ops.segment_sum(
                col.astype(jnp.int64), ids32, num_segments=num_segments
            )
        return out
    P_pad = _round_up(max(P, TILE), TILE)
    n_tiles = P_pad // TILE
    names = list(int_columns) + list(count_columns)

    ids32 = ids.astype(jnp.int32)
    pad = P_pad - P
    # edge-pad ids (keeps per-tile spread tight); zero-pad values
    ids_p = jnp.pad(ids32, (0, pad), mode="edge" if P else "constant")
    valid_p = jnp.pad(valid, (0, pad))

    big = jnp.int32(1 << 30)
    g_out = _round_up(num_segments, ALIGN) + WINDOW

    def layout(ids_flat, valid_flat):
        """(spread_ok, ids_clean[n_tiles,TILE], bases[n_tiles]) for one lane order."""
        ids2 = ids_flat.reshape(n_tiles, TILE)
        valid2 = valid_flat.reshape(n_tiles, TILE)
        tile_min = jnp.min(jnp.where(valid2, ids2, big), axis=1)
        tile_max = jnp.max(jnp.where(valid2, ids2, -1), axis=1)
        spread_ok = jnp.all(tile_max - tile_min <= MAX_SPREAD)
        # invalid lanes: point ids at the tile's window (their values are zero)
        tile_min_ok = jnp.where(tile_min == big, 0, tile_min)
        ids_clean = jnp.where(valid2, ids2, tile_min_ok[:, None])
        bases = jnp.clip((tile_min_ok // ALIGN) * ALIGN, 0, g_out - WINDOW).astype(
            jnp.int32
        )
        return spread_ok, ids_clean, bases

    spread_direct, ids_clean_d, bases_d = layout(ids_p, valid_p)
    in_range = jnp.bool_(True)
    for col in int_columns.values():
        in_range &= jnp.all((col >= 0) & (col < MAX_VALUE))

    def xla_path(_):
        out = []
        for name in names:
            col = int_columns.get(name)
            if col is None:
                col = count_columns[name].astype(jnp.int64)
            out.append(
                jax.ops.segment_sum(
                    col.astype(jnp.int64), ids32, num_segments=num_segments
                )
            )
        return tuple(out)

    limb_mask = (1 << LIMB_BITS) - 1

    def build_cols():
        """[MAX_COLS, P_pad] f32 limb/count rows in lane order."""
        col_rows = []
        for col in int_columns.values():
            col_p = jnp.pad(col, (0, pad))
            for k in range(LIMBS):
                col_rows.append(
                    ((col_p >> (LIMB_BITS * k)) & limb_mask).astype(jnp.float32)
                )
        for col in count_columns.values():
            col_rows.append(jnp.pad(col.astype(jnp.float32), (0, pad)))
        while len(col_rows) < MAX_COLS:
            col_rows.append(jnp.zeros(P_pad, jnp.float32))
        return jnp.stack(col_rows)

    def run_pallas(ids_clean, bases, cols):
        totals = _pallas_partials(
            ids_clean[:, None, :], cols, bases,
            num_segments=num_segments, interpret=interpret,
        ).astype(jnp.int64)  # [G_out, MAX_COLS]
        out = []
        ci = 0
        for _ in int_columns:
            v = jnp.zeros(num_segments, jnp.int64)
            for k in range(LIMBS):
                v = v + (totals[:num_segments, ci] << (LIMB_BITS * k))
                ci += 1
            out.append(v)
        for _ in count_columns:
            out.append(totals[:num_segments, ci])
            ci += 1
        return tuple(out)

    def pallas_direct(_):
        return run_pallas(ids_clean_d, bases_d, build_cols())

    def pallas_sorted(_):
        # Lanes are group-interleaved (slot reuse in the incremental store):
        # restore contiguity on device. One argsort + gathers, then the same
        # MXU sweep — still far cheaper than eight scatter sweeps. Invalid
        # lanes key to `big`, so they collect at the tail.
        perm = jnp.argsort(jnp.where(valid_p, ids_p, big))
        ids_s = ids_p[perm]
        valid_s = valid_p[perm]
        spread_sorted, ids_clean_s, bases_s = layout(ids_s, valid_s)

        # a sorted tile can still span > MAX_SPREAD groups when groups average
        # under ~1 lane each — only then is scatter the right tool. The
        # [MAX_COLS, P_pad] column gather stays INSIDE the true branch so that
        # pathology doesn't pay for a gather it then discards.
        def sorted_path(__):
            cols_s = build_cols()[:, perm]
            return run_pallas(ids_clean_s, bases_s, cols_s)

        return lax.cond(spread_sorted, sorted_path, xla_path, None)

    results = lax.cond(
        in_range,
        lambda _: lax.cond(spread_direct, pallas_direct, pallas_sorted, None),
        xla_path,
        None,
    )
    return dict(zip(names, results, strict=True))


def path_report(ids, valid, int_columns=None, num_segments: int = 0) -> Dict[str, bool]:
    """Which path :func:`fused_segment_sums` takes for this input, as host values.

    Reproduces the on-device predicates (same tile math) so tests and benchmarks
    can ASSERT the MXU path is reachable rather than trusting that it was.
    Returns ``{"path": "pallas-direct"|"pallas-sorted"|"xla-scatter", ...}`` with
    the individual predicates alongside.
    """
    import numpy as np

    ids_np = np.asarray(ids, np.int64)
    valid_np = np.asarray(valid, bool)
    P = ids_np.shape[0]
    if P > MAX_LANES:
        return {
            "path": "xla-scatter", "lanes": P, "direct_ok": False,
            "sorted_ok": False, "in_range": False, "too_many_lanes": True,
        }
    P_pad = _round_up(max(P, TILE), TILE)
    n_tiles = P_pad // TILE
    pad = P_pad - P
    mode = "edge" if P else "constant"
    ids_p = np.pad(ids_np, (0, pad), mode=mode)
    valid_p = np.pad(valid_np, (0, pad))
    big = 1 << 30

    def spread_ok(ids_flat, valid_flat) -> bool:
        ids2 = ids_flat.reshape(n_tiles, TILE)
        valid2 = valid_flat.reshape(n_tiles, TILE)
        tile_min = np.min(np.where(valid2, ids2, big), axis=1)
        tile_max = np.max(np.where(valid2, ids2, -1), axis=1)
        return bool(np.all(tile_max - tile_min <= MAX_SPREAD))

    direct_ok = spread_ok(ids_p, valid_p)
    perm = np.argsort(np.where(valid_p, ids_p, big), kind="stable")
    sorted_ok = spread_ok(ids_p[perm], valid_p[perm])
    in_range = True
    for col in (int_columns or {}).values():
        col = np.asarray(col)
        in_range = in_range and bool(np.all((col >= 0) & (col < MAX_VALUE)))
    if not in_range:
        path = "xla-scatter"
    elif direct_ok:
        path = "pallas-direct"
    elif sorted_ok:
        path = "pallas-sorted"
    else:
        path = "xla-scatter"
    return {
        "path": path, "lanes": P, "direct_ok": direct_ok,
        "sorted_ok": sorted_ok, "in_range": in_range, "too_many_lanes": False,
    }
