"""Device-resident cluster state: the O(changes) host->device data path.

SURVEY.md §7 names the host<->device path as a hard part: at 100k pods, re-uploading
the packed arrays every tick costs tens of ms — more than the decision kernel itself.
The reference has no analog (its Go loops rebuild aggregate state from the watch cache
each tick, pkg/controller/controller.go:192-272); the TPU-native design instead keeps
the ``ClusterArrays`` resident in device HBM and applies each tick's watch deltas as a
scatter update:

- the native C++ store (``native/statestore.cpp``) marks dirty slots as watch events
  are ingested and drains a deduplicated slot list per tick;
- the host gathers just those lanes from the zero-copy column views (numpy fancy
  indexing, O(changes));
- one jitted scatter (``jnp.ndarray.at[idx].set``) with **donated** operands updates
  the resident arrays in place — XLA aliases input and output buffers, so HBM traffic
  per tick is O(changes), not O(cluster).

Delta batches are padded to power-of-two buckets so jit compiles a handful of shapes
total (no recompilation storm as churn fluctuates). Padding lanes target a dedicated
scratch lane (index ``P``/``N`` — the resident arrays carry one extra, never-valid
lane) and all write the same constants, keeping duplicate-index scatter deterministic.

Group config/state ([G]-sized, mutated by the controller every tick: locks, cached
capacity, requested nodes) rides along in the same jit call — it is tiny, so it is
simply re-uploaded rather than diffed.
"""

from __future__ import annotations

import logging
from dataclasses import fields, replace
from functools import partial, reduce
from operator import or_
from typing import Optional

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp
from jax import tree_util

from escalator_tpu.core.arrays import (
    NO_TAINT_TIME,
    ClusterArrays,
    GroupArrays,
    NodeArrays,
    PodArrays,
)
from escalator_tpu.ops import kernel as _kernel  # noqa: F401  (ClusterArrays pytree)


def _register(cls):
    tree_util.register_pytree_node(
        cls,
        lambda obj: ([getattr(obj, f.name) for f in fields(cls)], None),
        lambda aux, leaves: cls(*leaves),
    )


_register(PodArrays)
_register(NodeArrays)
_register(GroupArrays)

# Delta-batch bucket policy: power-of-two, min 64 — bounds the set of
# compiled scatter shapes. The ONE definition lives in the (jax-free) store
# module, because the stores' packed dirty drain must pad to exactly the
# same buckets or the two paths would compile disjoint shape sets.
from escalator_tpu.native.statestore import delta_bucket as _bucket  # noqa: E402


_POD_PAD = {"node": -1}
_NODE_PAD = {"taint_time_sec": NO_TAINT_TIME}


def _pad_one_lane(soa, pad_defaults):
    """Copy of a Pod/NodeArrays with one extra scratch lane (valid=False)."""
    out = {}
    for f in fields(soa):
        arr = getattr(soa, f.name)
        fill = pad_defaults.get(f.name, 0)
        out[f.name] = np.concatenate([arr, np.full(1, fill, arr.dtype)])
    return type(soa)(**out)


def _gather_padded(soa, slots: np.ndarray, bucket: int, scratch: int, pad_defaults):
    """(idx[int32 bucket], values SoA of [bucket]) for a dirty-slot batch.

    Pad lanes point at the scratch lane and write that lane's invariant values
    (valid=False etc.), so duplicate-index scatter stays deterministic.
    """
    k = len(slots)
    idx = np.full(bucket, scratch, np.int32)
    idx[:k] = slots
    vals = {}
    for f in fields(soa):
        arr = getattr(soa, f.name)
        fill = pad_defaults.get(f.name, 0)
        v = np.full(bucket, fill, arr.dtype)
        if k:
            v[:k] = arr[slots]
        vals[f.name] = v
    return idx, type(soa)(**vals)


def _scatter_body(pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals):
    def upd(soa, idx, vals):
        return type(soa)(
            **{
                f.name: getattr(soa, f.name).at[idx].set(getattr(vals, f.name))
                for f in fields(soa)
            }
        )

    return ClusterArrays(
        groups=groups,
        pods=upd(pods, pod_idx, pod_vals),
        nodes=upd(nodes, node_idx, node_vals),
    )


# Pods/nodes are donated (in-place on device); groups is NOT — it may be either a
# fresh host upload or the pass-through resident value, and donating a buffer that
# is also returned untouched would invalidate the caller's reference.
_scatter_update = partial(jax.jit, donate_argnums=(0, 1))(_scatter_body)


def _pack_delta_bytes(idx: np.ndarray, vals) -> np.ndarray:
    """Serialize (idx, SoA values) into ONE uint8 buffer, column-major:
    [idx int32 bytes][field0 bytes][field1 bytes]... Sixteen per-column host
    transfers become two (pods + nodes) — on transports where each transfer
    pays fixed latency, that is most of the scatter phase. The device side
    (:func:`_unpack_delta`) mirrors this layout exactly (both iterate
    ``fields()`` in order), and integer/bool bitcasts are exact."""
    parts = [np.ascontiguousarray(idx, np.int32).view(np.uint8)]
    for f in fields(vals):
        parts.append(np.ascontiguousarray(getattr(vals, f.name)).view(np.uint8))
    return np.concatenate(parts)


def _unpack_delta(buf, field_dtypes):
    """(idx, {field: array}) from a :func:`_pack_delta_bytes` buffer, inside
    jit. ``field_dtypes`` is static; the bucket size is inferred from the
    buffer length."""
    lane_bytes = 4 + sum(np.dtype(dt).itemsize for _, dt in field_dtypes)
    B = buf.shape[0] // lane_bytes

    def take(off, dt):
        k = np.dtype(dt).itemsize
        chunk = jax.lax.dynamic_slice_in_dim(buf, off * B, k * B)
        if k == 1:
            return chunk.astype(dt), off + k
        return (
            jax.lax.bitcast_convert_type(chunk.reshape(B, k), dt),
            off + k,
        )

    idx, off = take(0, np.int32)
    vals = {}
    for name, dt in field_dtypes:
        vals[name], off = take(off, dt)
    return idx, vals


def _field_dtypes(soa):
    return tuple((f.name, np.dtype(getattr(soa, f.name).dtype).type)
                 for f in fields(soa))


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("pod_dts", "node_dts"))
def _scatter_update_from_packed(pods, nodes, groups, pod_buf, node_buf,
                                pod_dts, node_dts):
    pod_idx, pod_vals = _unpack_delta(pod_buf, pod_dts)
    node_idx, node_vals = _unpack_delta(node_buf, node_dts)
    return _scatter_body(
        pods, nodes, groups,
        pod_idx, type(pods)(**pod_vals), node_idx, type(nodes)(**node_vals),
    )


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("impl", "with_orders"))
def _scatter_update_decide(
    pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals, now_sec,
    impl="xla", with_orders=True,
):
    """Fused tick: scatter this tick's deltas AND run the decision kernel in ONE
    device program. Measured on the v5e tunnel this is NOT faster than the
    two-call path (back-to-back async dispatches already pipeline, and the
    donation handoff adds overhead), so the native backend keeps the two-step
    default; this stays as the single-dispatch option for transports where each
    dispatch costs a full round-trip."""
    cluster = _scatter_body(
        pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals
    )
    return cluster, _kernel.decide(cluster, now_sec, impl=impl,
                                   with_orders=with_orders)


# ---------------------------------------------------------------------------
# Incremental aggregates (round-8 tentpole): the scatter phase, which already
# knows exactly which lanes changed, also emits exact per-group aggregate
# deltas into the persistent GroupAggregates columns and marks dirty groups.
# ---------------------------------------------------------------------------


def aggregate_lane_deltas(pod_old, pod_new, node_old, node_new,
                          node_group_old, node_group_new, G: int, N: int):
    """Exact int64 aggregate deltas from a delta batch's (old, new) lane
    values: subtract each touched lane's old contribution, add its new one.
    The i64 milli-CPU / byte columns (the R2 dtype-parity contract) make
    this drift-free by construction — integer sums commute and associate
    exactly, so ``aggregate + delta`` is bit-equal to a from-scratch
    recompute. Contribution terms mirror ``kernel.aggregate_pods`` /
    ``kernel.aggregate_nodes`` term by term.

    ``pod_old``/``pod_new`` are PodArrays of the SAME ``[B]`` lanes before
    and after the scatter (pad lanes carry identical never-valid values on
    both sides and so contribute zero); likewise the node batch. Lane
    indices within a batch must be unique — the native store drains a
    DEDUPLICATED dirty list, and the host-diff backends emit np.nonzero
    indices; a duplicate would double-count its old contribution.
    ``node_group_old``/``node_group_new`` are the full ``[N]`` node->group
    vectors before/after the scatter (the same-group pod filter of
    ``node_pods_remaining`` reads them).

    Returns ``(deltas: dict, touched: bool[G], node_group_changed: bool[])``
    where ``deltas`` has one ``[G]`` int64 entry per group-sum column plus
    ``node_pods_remaining`` (``[N]`` int64), ``touched`` marks every group a
    delta landed in (the dirty-mask contribution), and
    ``node_group_changed`` is True when any batched node lane's group column
    changed — the one case where pods OUTSIDE the batch change their
    pods-remaining contribution and the caller must re-sweep that column
    (``kernel.node_pods_remaining_sweep``)."""
    import jax
    import jax.numpy as jnp

    seg = lambda v, i, n: jax.ops.segment_sum(v, i, num_segments=n)  # noqa: E731
    I64 = jnp.int64

    def pod_terms(p, node_group):
        w = p.valid.astype(I64)
        gid = jnp.where(p.valid, p.group, 0)
        on_w = (
            p.valid
            & (p.node >= 0)
            & (p.group == node_group[jnp.clip(p.node, 0, N - 1)])
        )
        tgt = jnp.where(p.valid & (p.node >= 0), p.node, 0)
        return gid, w, on_w.astype(I64), tgt

    gid_o, w_o, on_o, tgt_o = pod_terms(pod_old, node_group_old)
    gid_n, w_n, on_n, tgt_n = pod_terms(pod_new, node_group_new)

    def node_terms(n):
        gid = jnp.where(n.valid, n.group, 0)
        u = (n.valid & ~n.tainted & ~n.cordoned).astype(I64)
        t = (n.valid & n.tainted & ~n.cordoned).astype(I64)
        c = (n.valid & n.cordoned).astype(I64)
        return gid, n.valid.astype(I64), u, t, c

    ngid_o, nv_o, u_o, t_o, c_o = node_terms(node_old)
    ngid_n, nv_n, u_n, t_n, c_n = node_terms(node_new)

    deltas = {
        "cpu_req": seg(pod_new.cpu_milli * w_n, gid_n, G)
        - seg(pod_old.cpu_milli * w_o, gid_o, G),
        "mem_req": seg(pod_new.mem_bytes * w_n, gid_n, G)
        - seg(pod_old.mem_bytes * w_o, gid_o, G),
        "num_pods": seg(w_n, gid_n, G) - seg(w_o, gid_o, G),
        "node_pods_remaining": seg(on_n, tgt_n, N) - seg(on_o, tgt_o, N),
        "cpu_cap": seg(node_new.cpu_milli * u_n, ngid_n, G)
        - seg(node_old.cpu_milli * u_o, ngid_o, G),
        "mem_cap": seg(node_new.mem_bytes * u_n, ngid_n, G)
        - seg(node_old.mem_bytes * u_o, ngid_o, G),
        "num_nodes": seg(nv_n, ngid_n, G) - seg(nv_o, ngid_o, G),
        "num_untainted": seg(u_n, ngid_n, G) - seg(u_o, ngid_o, G),
        "num_tainted": seg(t_n, ngid_n, G) - seg(t_o, ngid_o, G),
        "num_cordoned": seg(c_n, ngid_n, G) - seg(c_o, ngid_o, G),
    }
    touched = jnp.zeros(G, bool)
    for gid, valid in ((gid_o, pod_old.valid), (gid_n, pod_new.valid),
                       (ngid_o, node_old.valid), (ngid_n, node_new.valid)):
        # invalid lanes point at group 0 with a False update: a no-op
        touched = touched.at[gid].max(valid)
    # ANY group-column change counts, valid or not: aggregate_pods' same-group
    # filter reads node_group regardless of the node's validity, so a stale
    # group column flipping under an invalid lane still moves pods-remaining
    node_group_changed = jnp.any(node_old.group != node_new.group)
    return deltas, touched, node_group_changed


def group_rows_changed(groups_old, groups_new):
    """Elementwise ``[G]`` mask of group config/state rows that changed —
    the dirty-mask contribution of the per-tick group re-upload. Shared by
    the native scatter program and the pod-axis delta scatter so the
    config-dirty semantics cannot drift."""
    return reduce(or_, (
        getattr(groups_old, f.name) != getattr(groups_new, f.name)
        for f in fields(type(groups_new))
    ))


def fold_aggregate_deltas(aggs, deltas, touched, group_row_changed,
                          node_pods_remaining):
    """Apply one batch's exact deltas to the maintained
    :class:`kernel.GroupAggregates` — THE single place the column list is
    folded, used by both ``_scatter_update_aggs`` and
    ``parallel.podaxis.make_delta_scatter`` (a column added to
    GroupAggregates that is missed here fails loudly at construction
    instead of silently breaking the refresh audit's bit-equality on one
    path). ``node_pods_remaining`` is passed ready-made because the two
    callers correct the node-group-change case differently (in-program
    re-sweep vs host-level flag)."""
    return _kernel.GroupAggregates(
        cpu_req=aggs.cpu_req + deltas["cpu_req"],
        mem_req=aggs.mem_req + deltas["mem_req"],
        num_pods=aggs.num_pods + deltas["num_pods"],
        cpu_cap=aggs.cpu_cap + deltas["cpu_cap"],
        mem_cap=aggs.mem_cap + deltas["mem_cap"],
        num_nodes=aggs.num_nodes + deltas["num_nodes"],
        num_untainted=aggs.num_untainted + deltas["num_untainted"],
        num_tainted=aggs.num_tainted + deltas["num_tainted"],
        num_cordoned=aggs.num_cordoned + deltas["num_cordoned"],
        node_pods_remaining=node_pods_remaining,
        dirty=aggs.dirty | touched | group_row_changed,
    )


def _scatter_update_aggs_core(pods, nodes, groups_old, groups_new, pod_idx,
                              pod_vals, node_idx, node_vals, aggs):
    """The incremental tick's scatter: apply the dirty-lane deltas to the
    resident arrays (exactly ``_scatter_body``) AND maintain the persistent
    per-group aggregates in the same device program — subtract each touched
    lane's old contribution, add its new one, and fold the touched groups
    (plus every group whose config/state row changed between ``groups_old``
    and ``groups_new``) into the dirty mask that ``kernel.delta_decide``
    consumes. Plain traceable body: jitted (with donation) as
    ``_scatter_update_aggs`` below, and vmapped over the cluster axis
    inside the fleet step program (``_fleet_step``)."""
    G = groups_new.valid.shape[0]
    N = nodes.valid.shape[0]
    gather = lambda soa, idx: type(soa)(  # noqa: E731
        **{f.name: getattr(soa, f.name)[idx] for f in fields(soa)}
    )
    pod_old = gather(pods, pod_idx)
    node_old = gather(nodes, node_idx)
    node_group_old = nodes.group
    cluster = _scatter_body(
        pods, nodes, groups_new, pod_idx, pod_vals, node_idx, node_vals
    )
    deltas, touched, node_group_changed = aggregate_lane_deltas(
        pod_old, pod_vals, node_old, node_vals,
        node_group_old, cluster.nodes.group, G, N,
    )
    # the rare exact-correction case: a node lane's group column changed, so
    # pods outside the batch moved their pods-remaining contribution — one
    # O(P) column re-sweep (still no O(P) group sums; those are delta-exact)
    npr = jax.lax.cond(
        node_group_changed,
        lambda: _kernel.node_pods_remaining_sweep(
            cluster.pods, cluster.nodes.group, N),
        lambda: aggs.node_pods_remaining + deltas["node_pods_remaining"],
    )
    aggs_out = fold_aggregate_deltas(
        aggs, deltas, touched, group_rows_changed(groups_old, groups_new), npr)
    return cluster, aggs_out


#: Jitted scatter+aggregate program with the documented donation contract:
#: pods/nodes (in-place residency) and the aggregate columns (add in place).
_scatter_update_aggs = partial(jax.jit, donate_argnums=(0, 1, 8))(
    _scatter_update_aggs_core)


# ---------------------------------------------------------------------------
# Fleet arenas (round 14): per-tenant GroupAggregates + decision columns
# stacked along a cluster axis, updated by ONE fused per-micro-batch program.
# ---------------------------------------------------------------------------


def _fleet_step_core(pods, nodes, groups, aggs, prev_cols, tenant_rows,
                     groups_new, pod_idx, pod_vals, node_idx, node_vals,
                     dirty_idx, now_sec):
    """One fleet micro-batch as ONE device program: for the ``T`` tenants in
    ``tenant_rows``, scatter their dirty-lane delta batches into the
    C-stacked resident arrays, maintain their per-tenant aggregate arenas
    (exact integer deltas — ``_scatter_update_aggs_core`` vmapped over the
    batch), run the per-tenant delta decide on their compacted dirty-group
    buckets (``kernel._delta_decide_core`` vmapped), and write the updated
    rows back. Tenants NOT in the batch are untouched bitwise.

    Shapes: the arenas carry ``C+1`` tenant rows (row ``C`` is a scratch
    tenant, the row-level analog of the scratch lane) over per-tenant lane
    buckets ``P+1``/``N+1`` (each row keeps its own scratch lane). Batch
    operands are ``[T, ...]`` with ``T`` a power-of-two bucket: pad batch
    entries point at the scratch tenant row with no-op delta batches
    (pad-valued lanes, ``dirty_idx`` all ``G``), so duplicate row scatters
    write identical values and the program stays deterministic. The jit
    cache keys only on the bucket shapes — tenant add/evict changes row
    CONTENT, never a shape, so steady fleet traffic never retraces.

    Returns ``((pods, nodes, groups, aggs, prev_cols), out)`` where ``out``
    is the batch's stacked DecisionArrays ``[T, ...]`` (order fields are
    the light program's input-order placeholders) and the state replaces
    the donated arenas."""
    gather_rows = lambda tree: tree_util.tree_map(  # noqa: E731
        lambda a: a[tenant_rows], tree)
    pods_T = gather_rows(pods)
    nodes_T = gather_rows(nodes)
    groups_T = gather_rows(groups)
    aggs_T = gather_rows(aggs)
    prev_T = tuple(c[tenant_rows] for c in prev_cols)

    def one(p, n, g_old, g_new, pi, pv, ni, nv, a, prev, didx, now):
        cluster, a2 = _scatter_update_aggs_core(
            p, n, g_old, g_new, pi, pv, ni, nv, a)
        out, a3 = _kernel._delta_decide_core(
            g_new, cluster.nodes, a2, prev, didx, now)
        return cluster.pods, cluster.nodes, out, a3

    pods_T2, nodes_T2, out_T, aggs_T2 = jax.vmap(one)(
        pods_T, nodes_T, groups_T, groups_new, pod_idx, pod_vals,
        node_idx, node_vals, aggs_T, prev_T, dirty_idx, now_sec)

    put_rows = lambda full, upd: tree_util.tree_map(  # noqa: E731
        lambda a, b: a.at[tenant_rows].set(b), full, upd)
    state = (
        put_rows(pods, pods_T2),
        put_rows(nodes, nodes_T2),
        put_rows(groups, groups_new),
        put_rows(aggs, aggs_T2),
        tuple(
            full.at[tenant_rows].set(getattr(out_T, name))
            for full, name in zip(prev_cols, _kernel.GROUP_DECISION_FIELDS,
                                  strict=True)
        ),
    )
    return state, out_T


#: Jitted fleet step. DONATES the five arena operands — they are persistent
#: device state replaced wholesale by the returned values (the fleet engine
#: owns the drop-old-references protocol, mirroring IncrementalDecider).
_fleet_step = partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))(
    _fleet_step_core)


#: the mesh axis the fleet arenas shard over (one name everywhere — specs,
#: registry fixtures, and the engine agree by construction)
FLEET_SHARD_AXIS = "fleet_shards"

_fleet_step_sharded_cache: dict = {}


def make_fleet_step_sharded(mesh):
    """The fleet step partitioned over a device mesh: every operand gains a
    leading shard axis ``S`` (arenas ``[S, Cs+1, ...]``, batch operands
    ``[S, T, ...]``) sharded one row per device, and each device runs
    :func:`_fleet_step_core` on its own arena slice — tenants are
    embarrassingly parallel (the per-shard body has zero collectives, so
    the sharded lowering does too; jaxlint pins the 0-psum budget on the
    ``device_state.fleet_step_sharded`` entry). Donation carries through:
    the five stacked arenas alias their outputs per shard (R5-verified),
    and the jit cache still keys on bucket shapes alone — tenant add/evict
    moves row CONTENT, never a shape.

    A shard with no batch entries this micro-batch rides scratch-row
    no-ops (rows ``Cs``, pad-valued lanes, all-``G`` dirty buckets) —
    bitwise inert, exactly the single-device pad convention.

    Cached per mesh (device ids + axis names): rebuilding the wrapper per
    call would make every dispatch a fresh jit cache."""
    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    fn = _fleet_step_sharded_cache.get(key)
    if fn is None:
        from jax.sharding import PartitionSpec

        from escalator_tpu.jaxconfig import shard_map

        def per_shard(*args):
            # shard_map keeps the partitioned axis at local size 1; the
            # squeeze/unsqueeze pair is a free reshape per shard and lets
            # the body stay the SAME _fleet_step_core the unsharded jit
            # traces (one program, two launch wrappers)
            local = tree_util.tree_map(lambda a: a[0], args)
            state, out = _fleet_step_core(*local)
            return tree_util.tree_map(lambda a: a[None], (state, out))

        spec = PartitionSpec(mesh.axis_names[0])
        body = shard_map(
            per_shard, mesh=mesh,
            in_specs=tuple([spec] * 13), out_specs=spec)
        fn = partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4))(body)
        _fleet_step_sharded_cache[key] = fn
    return fn


def _fleet_order_tail_core(nodes, groups, aggs, tenant_rows):
    """Batched lazy-order repair (round 18): for the ``T2`` tenant rows in
    ``tenant_rows`` (pad entries = the scratch row ``C``), recompute the
    two order permutations of :func:`kernel.decide`'s ordered branch —
    ``ops.order_tail.node_selection_masks`` + the single 4-key
    ``combined_order_sort`` + the tainted-block roll — vmapped over the
    rows, fed the RESIDENT post-step nodes/groups/aggregates. This is
    literally the ordered-vs-light field difference: ``decide``'s
    with_orders contract says every field EXCEPT ``untaint_order``/
    ``scale_down_order`` is bit-identical between the two programs, so
    grafting these two columns over the light batch output reproduces the
    full ordered decide bit-for-bit (the victim primary reads the same
    maintained ``node_pods_remaining`` the ordered re-dispatch fed through
    ``aggregates_tuple``; ``jnp.sum(tainted_sel)`` equals the
    ``tainted_offsets[G]`` roll amount by construction).

    Returns ``(untaint_order, scale_down_order)`` int32 ``[T2, N+1]``.
    Read-only — no donation: the arenas stay resident."""
    from escalator_tpu.ops.order_tail import (
        combined_order_sort,
        node_selection_masks,
    )

    G = groups.valid.shape[-1]
    nodes_T = tree_util.tree_map(lambda a: a[tenant_rows], nodes)
    empt_T = groups.emptiest[tenant_rows]
    npr_T = aggs.node_pods_remaining[tenant_rows]

    def one(n, empt, npr):
        ngroup, untainted_sel, tainted_sel = node_selection_masks(
            n.valid, n.group, n.tainted, n.cordoned)
        victim_primary = jnp.where(empt[ngroup], npr, jnp.int64(0))
        N = n.valid.shape[0]
        # the same variance tie as decide(): under shard_map the sorted
        # branch is device-varying and cond requires both branches to match
        trivial = jnp.arange(N, dtype=jnp.int32) + ngroup.astype(jnp.int32) * 0

        def _combined(_):
            iota = jax.lax.iota(jnp.int64, N)
            _, perm = combined_order_sort(
                ngroup, tainted_sel, untainted_sel, victim_primary,
                n.creation_ns, G, iota)
            return perm.astype(jnp.int32)

        untaint = jax.lax.cond(
            jnp.any(untainted_sel | tainted_sel), _combined,
            lambda _: trivial, None)
        scale_down = jnp.roll(untaint, -jnp.sum(tainted_sel))
        return untaint, scale_down

    return jax.vmap(one)(nodes_T, empt_T, npr_T)


_fleet_order_tail_sharded_cache: dict = {}


def make_fleet_order_tail_sharded(mesh):
    """:func:`_fleet_order_tail_core` partitioned over the fleet mesh: each
    shard repairs ITS order-needing rows (``tenant_rows [S, T2]``, scratch-
    row pads) against its own arena slice — zero collectives, like the
    fleet step (jaxlint pins the 0-psum budget on the
    ``device_state.fleet_order_tail_sharded`` entry). ONE dispatch per
    micro-batch replaces the per-tenant ``fleet_shard_local`` + ordered
    ``decide_jit`` re-dispatch (55 ms O(arena) per draining tenant at the
    cfg17 arena). No donation: the tail only READS the resident arenas.
    Cached per mesh, same key policy as :func:`make_fleet_step_sharded`."""
    key = (tuple(d.id for d in mesh.devices.flat), mesh.axis_names)
    fn = _fleet_order_tail_sharded_cache.get(key)
    if fn is None:
        from jax.sharding import PartitionSpec

        from escalator_tpu.jaxconfig import shard_map

        def per_shard(*args):
            local = tree_util.tree_map(lambda a: a[0], args)
            out = _fleet_order_tail_core(*local)
            return tree_util.tree_map(lambda a: a[None], out)

        spec = PartitionSpec(mesh.axis_names[0])
        body = shard_map(
            per_shard, mesh=mesh,
            in_specs=tuple([spec] * 4), out_specs=spec)
        fn = jax.jit(body)
        _fleet_order_tail_sharded_cache[key] = fn
    return fn


def fleet_shard_local(tree, shard: int):
    """The per-device block of a ``[S, …]``-sharded arena tree for mesh
    row ``shard``: zero-copy references to the committed per-device
    buffers (``jax.Array.addressable_shards``), each ``[1, Cs+1, …]``.
    This is how the ordered tail reads ONE shard without SPMD: a traced
    ``a[shard, row]`` gather on the sharded axis lowers to an
    O(arena) cross-device program (measured 55 ms/call at the cfg17
    arena vs <1 ms for the local path)."""
    def pick(a):
        for sh in a.addressable_shards:
            idx = sh.index[0]
            start = 0 if idx.start is None else int(idx.start)
            stop = a.shape[0] if idx.stop is None else int(idx.stop)
            if start <= shard < stop:
                data = sh.data
                if stop - start > 1:   # defensive: multi-row block
                    data = data[shard - start: shard - start + 1]
                return data
        raise KeyError(f"shard {shard} is not addressable in this process")
    return tree_util.tree_map(pick, tree)


@jax.jit
def _fleet_tenant_state_local(pods, nodes, groups, aggs, row):
    """:func:`_fleet_tenant_state` over ONE shard's local arena block
    ``[1, Cs+1, …]`` (from :func:`fleet_shard_local`): gather the
    tenant's resident row as an unstacked ``(ClusterArrays,
    GroupAggregates)`` pair, O(row) on the shard's own device. ``row``
    is traced — one compiled gather per shard device serves every
    tenant (the ordered tail is rare by design: steady fleets never pay
    this crossing)."""
    g = lambda tree: tree_util.tree_map(  # noqa: E731
        lambda a: a[0, row], tree)
    return (
        ClusterArrays(groups=g(groups), pods=g(pods), nodes=g(nodes)),
        g(aggs),
    )


@jax.jit
def _fleet_tenant_state(pods, nodes, groups, aggs, row):
    """Gather ONE tenant's resident row as an unstacked
    ``(ClusterArrays, GroupAggregates)`` pair — the fleet service's ordered
    re-dispatch path slices this and feeds ``kernel.decide_jit`` with the
    maintained aggregates (so even the per-tenant ordered follow-up skips
    the O(cluster) sweeps). ``row`` is traced: one compiled gather serves
    every tenant."""
    g = lambda tree: tree_util.tree_map(lambda a: a[row], tree)  # noqa: E731
    return (
        ClusterArrays(groups=g(groups), pods=g(pods), nodes=g(nodes)),
        g(aggs),
    )


def _explain_terms(groups, aggs):
    """The explain kernel over maintained aggregates: feed
    :func:`kernel.explain_decide` exactly what ``kernel.decide`` feeds
    :func:`kernel.group_decision_math` — the ``aggregates_tuple`` unpack
    and the int64→int32 count casts replicated verbatim, so the
    reconstructed columns can only differ from the committed ones if the
    AGGREGATES drifted (the cross-check's entire point)."""
    pod_aggs, node_aggs = _kernel.aggregates_tuple(aggs)
    cpu_req, mem_req, num_pods64, _node_pods_remaining = pod_aggs
    cpu_cap, mem_cap, nn64, nu64, nt64, nc64 = node_aggs
    return _kernel.explain_decide(
        groups, cpu_req, mem_req, cpu_cap, mem_cap,
        num_pods64.astype(jnp.int32), nn64.astype(jnp.int32),
        nu64.astype(jnp.int32), nt64.astype(jnp.int32),
        nc64.astype(jnp.int32))


_explain_groups_core = jax.jit(_explain_terms)


def explain_groups(cluster: ClusterArrays, aggs):
    """Re-derive the full decision calculus for every group of a resident
    single-cluster state as a named term dict (see
    ``kernel.explain_decide``). READ-ONLY: no donation — explaining a
    decision must never invalidate the state that produced it. Same
    wedged-transport guard as the decide entries (debug-explain is a raw
    library surface)."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _explain_groups_core(cluster.groups, aggs)


@jax.jit
def _explain_tenant_core(groups, aggs, prev_cols, row):
    """One fleet tenant's explain gather over a shard's local arena block
    ``[1, Cs+1, …]`` (from :func:`fleet_shard_local`): slice the tenant's
    group rows, aggregates and committed decision columns at ``[0, row]``
    and run the explain kernel on the slice — O(row) on the shard's own
    device, no cross-device program. ``row`` is traced: one compile per
    process serves every tenant (the retrace pin in the analysis registry
    holds this). Returns ``(terms, committed_cols)``."""
    g = lambda tree: tree_util.tree_map(  # noqa: E731
        lambda a: a[0, row], tree)
    terms = _explain_terms(g(groups), g(aggs))
    return terms, tuple(c[0, row] for c in prev_cols)


def explain_tenant_local(groups, aggs, prev_cols, row):
    """Guarded wrapper over :func:`_explain_tenant_core` (the fleet
    engine's per-tenant explain entry; READ-ONLY, arenas stay resident)."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _explain_tenant_core(groups, aggs, prev_cols, row)


class DeviceClusterCache:
    """Keeps the packed cluster resident on one device across ticks.

    Construct from host-side arrays (typically the native store's zero-copy views),
    then per tick call :meth:`apply_dirty` with the store's drained dirty-slot lists.
    ``cluster`` is the jit-ready device value for ``ops.kernel.decide``.
    """

    def __init__(self, host: ClusterArrays, device=None):
        if device is None:
            # wedged-transport guard: raw library construction (no
            # CLI/backend upstream) reaches backend init right here, and a
            # wedged tunnel hangs it forever; cached per process
            from escalator_tpu.jaxconfig import guarded_devices

            device = guarded_devices()[0]
        self._device = device
        self._host_pods = host.pods
        self._host_nodes = host.nodes
        self.pod_capacity = int(host.pods.valid.shape[0])
        self.node_capacity = int(host.nodes.valid.shape[0])
        self._cluster = jax.device_put(
            ClusterArrays(
                groups=host.groups,
                pods=_pad_one_lane(host.pods, _POD_PAD),
                nodes=_pad_one_lane(host.nodes, _NODE_PAD),
            ),
            self._device,
        )
        self._register_resources()

    def _register_resources(self) -> None:
        """Account the resident cluster with the device resource registry
        (observability/resources.py): per-owner live bytes from array
        metadata, budgeted by the executable envelope formula. Re-called on
        refresh_full (capacity growth re-keys the budget); the weakref'd
        registration dies with the cache."""
        from escalator_tpu.observability import resources

        G = int(self._cluster.groups.valid.shape[0])
        resources.RESOURCES.register(
            "cluster_arrays", self, lambda c: c._cluster,
            budget=lambda c, _G=G: resources.expected_cluster_bytes(
                c.pod_capacity, c.node_capacity, _G))

    @property
    def cluster(self) -> ClusterArrays:
        return self._cluster

    @property
    def device(self):
        """The device the cluster is resident on (impl selection keys off its
        platform — see ops.kernel.native_tick_impl)."""
        return self._device

    @property
    def host_views(self):
        """The current host-side gather views ``(pods, nodes)`` — after a
        snapshot restore these are the snapshot's own columns, which the
        repack backend adopts as its diff baseline (the warm start's
        'changed since checkpoint' comparison point)."""
        return self._host_pods, self._host_nodes

    def set_host(self, pods: PodArrays, nodes: NodeArrays) -> None:
        """Rebind the host-side views gathers read from. Needed when the store
        re-views its buffers (growth) or a per-tick corrected view (dry mode)
        replaces the raw columns. Shapes must match the resident capacity."""
        if (
            int(pods.valid.shape[0]) != self.pod_capacity
            or int(nodes.valid.shape[0]) != self.node_capacity
        ):
            raise ValueError(
                "host view shape changed; use refresh_full() after store growth"
            )
        self._host_pods = pods
        self._host_nodes = nodes

    def _gather_deltas(self, pod_slots: np.ndarray, node_slots: np.ndarray):
        """(pod_idx, pod_vals, node_idx, node_vals) for a dirty-slot batch —
        the shared O(changes) host gather both tick paths use."""
        pidx, pvals = _gather_padded(
            self._host_pods,
            np.asarray(pod_slots, np.int64),
            _bucket(len(pod_slots)),
            self.pod_capacity,
            _POD_PAD,
        )
        nidx, nvals = _gather_padded(
            self._host_nodes,
            np.asarray(node_slots, np.int64),
            _bucket(len(node_slots)),
            self.node_capacity,
            _NODE_PAD,
        )
        return pidx, pvals, nidx, nvals

    def gather_deltas(self, pod_slots: np.ndarray, node_slots: np.ndarray):
        """The host-side half of :meth:`apply_dirty`: copy the dirty lanes out
        of the (live, possibly shared) host views into padded numpy buffers.
        Callers that share the views with a writer thread run THIS under the
        store lock and :meth:`apply_gathered` outside it — the gather is the
        only part that reads shared memory; the device dispatch (and any jit
        compile it triggers) must not stall ingestion."""
        return self._gather_deltas(pod_slots, node_slots)

    def apply_gathered(
        self, gathered, groups: Optional[GroupArrays] = None
    ) -> ClusterArrays:
        """Device half of :meth:`apply_dirty`: scatter a `gather_deltas` batch
        (already-copied buffers — safe to run unlocked) into the resident arrays."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = gathered
        self._cluster = _scatter_update(
            self._cluster.pods, self._cluster.nodes, groups, pidx, pvals, nidx, nvals
        )
        return self._cluster

    def apply_gathered_with_aggregates(self, gathered, groups, aggs):
        """:meth:`apply_gathered` fused with the persistent-aggregate delta
        maintenance (``_scatter_update_aggs``): scatter the batch into the
        resident arrays and return the updated :class:`GroupAggregates`
        (donated in, replaced out — drop the old reference). ``groups`` may
        be None to keep the resident group rows (no config-dirty compare
        triggers then)."""
        groups_old = self._cluster.groups
        if groups is None:
            groups = groups_old
        pidx, pvals, nidx, nvals = gathered
        self._cluster, aggs = _scatter_update_aggs(
            self._cluster.pods, self._cluster.nodes, groups_old, groups,
            pidx, pvals, nidx, nvals, aggs,
        )
        return self._cluster, aggs

    def apply_dirty(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        groups: Optional[GroupArrays] = None,
    ) -> ClusterArrays:
        """Scatter this tick's dirty lanes (plus fresh group state) into the
        resident arrays. O(changes) host work + transfer; returns the updated
        device cluster."""
        return self.apply_gathered(self.gather_deltas(pod_slots, node_slots), groups)

    def apply_dirty_packed(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        groups: Optional[GroupArrays] = None,
    ) -> ClusterArrays:
        """:meth:`apply_dirty` with the delta batch crossing host->device as
        TWO packed byte buffers instead of sixteen per-column arrays (see
        ``_pack_delta_bytes``). Bit-identical resident state (integer/bool
        bitcasts are exact — test-locked); which variant is faster is a
        transport property, so the bench times both per capture and the
        default stays the per-column path until a device capture says
        otherwise."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = self._gather_deltas(pod_slots, node_slots)
        self._cluster = _scatter_update_from_packed(
            self._cluster.pods, self._cluster.nodes, groups,
            _pack_delta_bytes(pidx, pvals), _pack_delta_bytes(nidx, nvals),
            _field_dtypes(self._host_pods), _field_dtypes(self._host_nodes),
        )
        return self._cluster

    def apply_dirty_and_decide(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        now_sec,
        groups: Optional[GroupArrays] = None,
        impl: str = "xla",
        with_orders: bool = True,
    ):
        """Fused per-tick path: scatter the dirty lanes and run the decision
        kernel in one device dispatch. Returns the DecisionArrays; the updated
        cluster stays resident (``self.cluster``). ``with_orders=False`` is
        the lazy-orders light program (kernel.decide docstring) so the fused
        variant prices the same steady-state tick as the two-call path."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = self._gather_deltas(pod_slots, node_slots)
        self._cluster, out = _scatter_update_decide(
            self._cluster.pods, self._cluster.nodes, groups,
            pidx, pvals, nidx, nvals, jnp.int64(now_sec), impl=impl,
            with_orders=with_orders,
        )
        return out

    def refresh_full(self, host: ClusterArrays) -> ClusterArrays:
        """Full re-upload after a capacity change (store growth re-views buffers;
        resident shapes must follow). Rare by design — capacities double."""
        self.__init__(host, self._device)
        return self._cluster

    @classmethod
    def adopt_resident(cls, resident: ClusterArrays,
                       host_pods: PodArrays, host_nodes: NodeArrays,
                       device=None) -> "DeviceClusterCache":
        """Construct around ALREADY-RESIDENT arrays (the snapshot restore
        path, ops/snapshot.py): the arrays carry their scratch lane and live
        on device — no padding, no upload. ``host_pods``/``host_nodes`` seed
        the host-side gather views (the snapshot's unpadded columns; callers
        rebind per tick via :meth:`set_host` exactly as after ``__init__``)."""
        self = cls.__new__(cls)
        if device is None:
            from escalator_tpu.jaxconfig import guarded_devices

            device = guarded_devices()[0]
        self._device = device
        self._host_pods = host_pods
        self._host_nodes = host_nodes
        self.pod_capacity = int(host_pods.valid.shape[0])
        self.node_capacity = int(host_nodes.valid.shape[0])
        if (int(resident.pods.valid.shape[0]) != self.pod_capacity + 1
                or int(resident.nodes.valid.shape[0]) != self.node_capacity + 1):
            raise ValueError(
                "adopt_resident: resident arrays must carry exactly one "
                "scratch lane over the host capacity")
        self._cluster = resident
        self._register_resources()
        return self


class AggregateParityError(AssertionError):
    """The incrementally maintained aggregates diverged from a from-scratch
    recompute — the refresh audit's bit-equality contract was broken (a
    delta-maintenance bug, or a caller mutating resident state outside the
    incremental scatter path)."""


_DEFAULT_REFRESH_EVERY = 256


def parse_refresh_every(value, source: str = "refresh_every") -> int:
    """Validate a refresh-audit cadence: a positive integer, or ``"off"``
    to disable the audit explicitly. Rejects 0 / negative / non-integer
    values with a clear error — the old ``int(env)`` accepted ``"0"`` as a
    silent disable and crashed opaquely on anything else. Returns the
    cadence in ticks (0 = disabled, only ever via ``"off"``)."""
    bad = ValueError(
        f"{source} must be a positive integer number of ticks or 'off' "
        f"(disable the audit), got {value!r}"
    )
    if isinstance(value, str):
        if value.strip().lower() == "off":
            return 0
        try:
            parsed = int(value.strip())
        except ValueError:
            raise bad from None
    elif isinstance(value, bool) or not isinstance(value, int):
        raise bad
    else:
        parsed = value
    if parsed <= 0:
        raise bad
    return parsed


def _fresh_buffer(x):
    """An op XLA cannot alias back into the input buffer (no donation is
    declared): the double-buffer snapshot's per-leaf copy."""
    if x.dtype == jnp.bool_:
        return x ^ False
    return x + jnp.zeros((), x.dtype)


@jax.jit
def _audit_snapshot(cluster: ClusterArrays, aggs):
    """Freeze the audit's inputs into a double buffer: one device program of
    pure on-device copies (no host sync, no donation — the live buffers stay
    valid and keep mutating under subsequent ticks while the background
    audit reads the frozen snapshot). Registered with jaxlint as
    ``device_state.audit_snapshot``: zero collectives, zero host callbacks,
    donation explicitly ABSENT (aliasing an input here would let a later
    tick's scatter corrupt the frozen state)."""
    return (
        tree_util.tree_map(_fresh_buffer, cluster),
        tree_util.tree_map(_fresh_buffer, aggs),
    )


class IncrementalDecider:
    """Owns the persistent incremental-decide state for one
    :class:`DeviceClusterCache`: the :class:`kernel.GroupAggregates`
    maintained by scatter deltas, the persistent ``[G]`` decision columns,
    and the refresh-cadence self-audit — the round-8 tentpole's
    orchestration, shared by the native backend, the host-diff repack
    backend (controller.backend.IncrementalJaxBackend) and bench cfg14.

    Per tick: :meth:`apply_gathered` (instead of the cache's plain
    ``apply_gathered``) scatters the dirty lanes AND folds their exact
    aggregate deltas + dirty-group marks in one device program; then
    :meth:`decide` runs the lazy-orders protocol over the incremental
    programs — the LIGHT dispatch is ``kernel.delta_decide`` on the
    compacted dirty rows (O(D + N), no O(P) sweep, no sort, zero
    collectives), and the ORDERED dispatch is the full ``kernel.decide``
    fed the persistent aggregates (so even drain ticks skip the O(cluster)
    aggregation; the ordering tail already runs only there).

    ``refresh_every`` (default env ESCALATOR_TPU_REFRESH_EVERY, else 256;
    ``"off"`` disables — 0/negative/non-int are rejected, see
    :func:`parse_refresh_every`) periodically re-derives the aggregates from
    scratch and asserts BIT-equality against the maintained state, so
    correctness is self-auditing in production; ``on_mismatch`` is "raise"
    (:class:`AggregateParityError`) or "repair" (log an error, adopt the
    recomputed truth, mark every group dirty). The audit is O(cluster) —
    same cost as one pre-round-8 decide — amortized over the cadence.

    **Background audit** (round 10, default on; ``background=False`` or env
    ESCALATOR_TPU_REFRESH_BACKGROUND=0 restores the synchronous form): the
    audit tick no longer blocks on the O(cluster) recompute — nor on the
    double-buffer snapshot copy. The audit tick hands the live refs to a
    worker thread, which freezes them into a double buffer (one on-device
    copy program — ``_audit_snapshot``) and runs the recompute +
    bit-compare against the FROZEN snapshot, while subsequent ticks keep
    mutating the live buffers; the only tick-thread coupling left is a
    donation gate (the next scatter/delta dispatch waits until the
    snapshot has materialized — normally already true by then). The
    verdict is reconciled at the next tick boundary (or :meth:`drain_audit`)
    with the synchronous semantics preserved exactly: same mismatch counter,
    same flight-recorder dump, same raise/repair behavior — "raise" simply
    surfaces one tick later, and "repair" re-derives from the CURRENT
    resident cluster (the snapshot's truth is already stale by then). The
    verdict itself is equivalent to the synchronous audit's at the same
    tick: the snapshot freezes exactly the inputs the blocking audit would
    have read (locked by the lockstep soak in tests/test_incremental_decide).

    **Incremental ordered ticks** (round 10, default on;
    ``incremental_orders=False`` opts out): an ordered dispatch no longer
    pays the full [N] node sort. Group columns come from the same
    ``delta_decide`` program the light tick runs, and the ordering
    permutation from persistent per-lane order state (ops.order_tail:
    resident sort-key columns + the last full-sort permutation, repaired by
    a dirty-lane rank merge — O(dirty · log N + N · log dirty), bit-exact
    vs the full sort). Above ``order_repair_max_dirty_frac`` dirty lanes the
    repair would approach the sort's cost for nothing, so the tick falls
    back to the full key sort (which also reseeds the state).

    The aggregate sweeps pin ``impl="xla"``-style scatter adds regardless of
    the construction ``impl`` only at delta scale; the bootstrap/refresh
    full sweeps honor ``impl`` (a TPU caller keeps the measured Pallas win
    where it exists — the O(cluster) recompute)."""

    def __init__(self, cache: DeviceClusterCache, impl: str = "xla",
                 refresh_every: "Optional[int | str]" = None,
                 on_mismatch: str = "raise",
                 background: Optional[bool] = None,
                 incremental_orders: bool = True,
                 order_repair_max_dirty_frac: float = 0.25,
                 overlap: bool = False,
                 aggregates=None):
        import os

        if on_mismatch not in ("raise", "repair"):
            raise ValueError(f"unknown on_mismatch {on_mismatch!r}")
        if refresh_every is None:
            env = os.environ.get("ESCALATOR_TPU_REFRESH_EVERY")
            refresh_every = (
                parse_refresh_every(env, "ESCALATOR_TPU_REFRESH_EVERY")
                if env is not None else _DEFAULT_REFRESH_EVERY)
        elif refresh_every != 0:
            # 0 stays the legacy programmatic disable; "off" is the
            # documented spelling (and the only one the env accepts)
            refresh_every = parse_refresh_every(refresh_every)
        if background is None:
            background = os.environ.get(
                "ESCALATOR_TPU_REFRESH_BACKGROUND", "1"
            ).lower() in ("1", "true", "yes")
        self._cache = cache
        self._impl = impl
        self._refresh_every = int(refresh_every)
        self._on_mismatch = on_mismatch
        self._background = bool(background)
        self._incremental_orders = bool(incremental_orders)
        self._order_repair_max_dirty_frac = float(order_repair_max_dirty_frac)
        self._overlap = bool(overlap)
        # restore path (ops/snapshot.py): inject the snapshot's maintained
        # aggregates instead of paying the O(cluster) bootstrap recompute —
        # the whole point of a warm start
        self._aggs = (aggregates if aggregates is not None
                      else _kernel.compute_aggregates_jit(cache.cluster,
                                                          impl=impl))
        self._prev_cols = None   # tuple in kernel.GROUP_DECISION_FIELDS order
        self._order_state = None  # (major, k1, k2, perm) — ops.order_tail
        #: order_update_jit's static compaction width: power-of-two growth
        #: on overflow (same recompile-bounding scheme as the delta buckets)
        self._order_bucket = 256
        self._audit_pool = None
        self._audit_future = None
        self._snap_ready = None   # Event: in-flight audit's snapshot frozen
        #: the background audit's frozen double buffer, held ONLY while a
        #: worker audit is in flight (observability: the resource registry
        #: accounts it, so the transient 2x cluster footprint is visible)
        self._audit_bufs = None
        #: a snapshot freeze's device copies, held only inside
        #: snapshot_state (same accounting purpose)
        self._snapshot_frozen = None
        self._register_resources()
        self._ticks = 0
        self._dirty_counted_tick = -1
        #: apply_gathered batches pending attachment to this tick's input
        #: record (observability/replay.py; empty unless recording is on)
        self._replay_pending: list = []
        # a NEW decider is a new replay epoch: its tick counter restarts
        # (cold/rebuild) or rewinds to a snapshot (restore), so entries
        # recorded by a previous decider in this process would mix two
        # epochs with overlapping tick numbers into one ring — a dump of
        # that is unreplayable at best, silently divergent at worst. The
        # ring describes exactly ONE decider's history.
        from escalator_tpu.observability import replay as _replay

        if _replay.INPUT_LOG.enabled():
            _replay.INPUT_LOG.clear()
        #: True when this decider warm-started from a snapshot (flight
        #: records carry it; the failover soak asserts on it)
        self.restored = False
        self.last_dirty_count = 0
        self.last_order_dirty_count = 0
        self.last_decide_synced = False
        self.refreshes = 0
        self.last_audit_ok = True
        #: ordered-tick path counts: bootstrap / repair / clean / full_sort
        self.order_stats: dict = {}

    def _register_resources(self) -> None:
        """Register every persistent buffer this decider owns with the
        device resource registry (observability/resources.py), each with
        its executable byte budget — the docs' envelope formulas, asserted
        live in bench --smoke. Budgets for state that does not exist yet
        (decision columns before the first decide, order state before the
        first ordered tick, the audit double buffer between audits) are
        None until the buffers appear; measured bytes are 0 then too."""
        from escalator_tpu.observability import resources as res

        def _shapes(i):
            G = int(i._aggs.dirty.shape[0])
            N1 = int(i._aggs.node_pods_remaining.shape[0])
            return G, N1

        def _aggs_budget(i):
            G, N1 = _shapes(i)
            return res.expected_aggregates_bytes(G, N1)

        def _cols_budget(i):
            if i._prev_cols is None:
                return None
            G, _N1 = _shapes(i)
            return res.expected_decision_columns_bytes(G)

        def _order_budget(i):
            if i._order_state is None:
                return None
            _G, N1 = _shapes(i)
            return res.expected_order_state_bytes(N1)

        def _audit_budget(i):
            if i._audit_bufs is None:
                return None
            G, N1 = _shapes(i)
            return (res.expected_cluster_bytes(
                        i._cache.pod_capacity, i._cache.node_capacity, G)
                    + res.expected_aggregates_bytes(G, N1))

        def _freeze_budget(i):
            if i._snapshot_frozen is None:
                return None
            G, N1 = _shapes(i)
            total = (res.expected_cluster_bytes(
                         i._cache.pod_capacity, i._cache.node_capacity, G)
                     + res.expected_aggregates_bytes(G, N1)
                     + res.expected_decision_columns_bytes(G))
            if i._order_state is not None:
                total += res.expected_order_state_bytes(N1)
            return total

        reg = res.RESOURCES.register
        reg("group_aggregates", self, lambda i: i._aggs,
            budget=_aggs_budget)
        reg("decision_columns", self, lambda i: i._prev_cols,
            budget=_cols_budget)
        reg("order_state", self, lambda i: i._order_state,
            budget=_order_budget)
        reg("audit_double_buffer", self, lambda i: i._audit_bufs,
            budget=_audit_budget)
        reg("snapshot_freeze", self, lambda i: i._snapshot_frozen,
            budget=_freeze_budget)

    @property
    def aggregates(self):
        return self._aggs

    def apply_gathered(self, gathered, groups=None) -> ClusterArrays:
        """Scatter a ``cache.gather_deltas`` batch into the resident arrays
        while maintaining the aggregates + dirty mask. Replaces the plain
        ``cache.apply_gathered`` in an incremental tick."""
        from escalator_tpu.observability import replay as _replay

        if _replay.INPUT_LOG.enabled():
            # capture BEFORE the dispatch: the scatter donates the resident
            # buffers, but the gathered batch itself is host numpy — encode
            # is a pure copy (a few KB at production churn)
            self._replay_pending.append(_replay.encode_batch(gathered, groups))
        self._await_snapshot()   # the scatter DONATES the live buffers
        cluster, self._aggs = self._cache.apply_gathered_with_aggregates(
            gathered, groups, self._aggs)
        return cluster

    def _set_prev(self, out) -> None:
        self._prev_cols = tuple(
            getattr(out, f) for f in _kernel.GROUP_DECISION_FIELDS)

    # -- decision provenance (round 19) -------------------------------------

    def _scale_down_candidates(self, max_per_group: int = 8):
        """Per-group scale-down victim windows from the persistent order
        state, host-side: the combined perm's untainted block rolled to the
        front IS scale_down_order (kernel.decide's assembly), and the
        maintained per-group untainted counts are exactly its window
        offsets. O(N) host copies on a debug surface; None when no ordered
        tick has run yet."""
        if self._order_state is None:
            return None
        *_, perm = self._order_state
        perm_h = np.asarray(perm)
        scale_down = np.roll(perm_h,
                             -int(np.asarray(self._aggs.num_tainted).sum()))
        offsets = np.concatenate(
            [[0], np.cumsum(np.asarray(self._aggs.num_untainted))])
        from escalator_tpu.observability import provenance

        return provenance.candidate_windows(scale_down, offsets,
                                            max_per_group)

    def explain(self, groups=None):
        """Explain the committed decision: re-derive the full calculus from
        the resident state (``explain_groups`` — READ-ONLY, nothing
        donated), bit-cross-check the reconstruction against the committed
        decision columns (dirty groups excluded: their columns are
        legitimately one pending delta behind) and return per-group
        explanation documents. Any mismatch is itself a finding — journal
        event + counter + rate-limited flight dump — because the shared
        math core leaves aggregate drift as the only possible cause.

        Call between ticks (same thread discipline as :meth:`decide`: the
        read must not race a donating dispatch)."""
        from escalator_tpu import observability as obs
        from escalator_tpu.observability import provenance

        self._await_snapshot()
        with obs.span("explain", kind="device"):
            terms = explain_groups(self._cache.cluster, self._aggs)
            terms = obs.fence(terms)
        host_terms = {k: np.asarray(v) for k, v in terms.items()}
        committed = None
        if self._prev_cols is not None:
            committed = {
                f: np.asarray(c) for f, c in
                zip(_kernel.GROUP_DECISION_FIELDS, self._prev_cols,
                    strict=True)}
        dirty = np.asarray(self._aggs.dirty)
        if committed is not None:
            mismatches = provenance.cross_check(host_terms, committed,
                                                skip=dirty)
            if mismatches:
                provenance.report_mismatches("incremental", mismatches)
        return provenance.build_explanations(
            host_terms, committed, dirty=dirty, groups=groups,
            candidates=self._scale_down_candidates())

    def decide(self, now_sec, tainted_any: bool, _record: bool = True,
               overlap_work=None):
        """One lazy-orders tick (``kernel.lazy_orders_decide``) over the
        incremental dispatch pair. Returns ``(DecisionArrays, ordered)``
        with the protocol's exact semantics: when ``ordered`` is False the
        order fields are input-order placeholders and no window may be
        read.

        ``_record=False`` suppresses input recording for this tick — the
        replay executor's own decides must not re-record themselves into
        the ring they are replaying.

        ``overlap_work`` (round 12): an optional zero-arg host callback run
        ONCE, in the window between the tick's decide dispatch returning
        and its first blocking device read — i.e. while the device program
        is in flight. This is how the streaming backend hides tick t+1's
        event-drain under tick t's device time (the callback pre-drains the
        store's accumulated watch deltas into a pending batch): the light
        delta tick, whose lazy gate otherwise synchronizes immediately
        after dispatch, gains the same host/device overlap the PR-5 ordered
        path already had. The callback must not touch device state — it
        runs with a donating dispatch in flight."""
        self._ticks += 1
        # repaired ordered-incremental ticks read a scalar AFTER the fused
        # program (see _order_finish) so the device is idle by the time the
        # caller unpacks — backends consult this to keep overlap_saved_ms
        # honest (0 on a pre-synced tick)
        self.last_decide_synced = False
        # the dispatches below donate the live aggregates (delta_decide) —
        # an in-flight audit's snapshot must be frozen before they run
        self._await_snapshot()
        # pick up a finished background audit first: its verdict (and a
        # raise/repair) lands at the tick boundary, never mid-dispatch
        self._reconcile_audit(block=False)
        from escalator_tpu.chaos import CHAOS

        if CHAOS.should_fire("audit_mismatch"):
            # chaos: corrupt ONE maintained aggregate lane on device so the
            # next cadence audit must detect (and raise/repair) a REAL
            # divergence between the maintained state and the recompute
            self._aggs = replace(
                self._aggs,
                node_pods_remaining=self._aggs.node_pods_remaining.at[0].add(1),
            )
        audit_due = bool(
            self._refresh_every and self._ticks % self._refresh_every == 0)
        if audit_due and not self._background:
            self.refresh()
        now = np.int64(now_sec)

        from escalator_tpu import observability as obs

        # at most ONE overlap-work run per tick, whichever dispatch path
        # fires first (the lazy protocol may dispatch twice on a drain start)
        overlap_ran = [False]

        def run_overlap():
            if overlap_work is None or overlap_ran[0]:
                return
            overlap_ran[0] = True
            with obs.span("event_predrain"):
                overlap_work()

        def dispatch(with_orders):
            if (with_orders and self._incremental_orders
                    and self._prev_cols is not None):
                return self._ordered_incremental(now, run_overlap)
            if with_orders or self._prev_cols is None:
                # full decide, fed the persistent aggregates: the O(P)/O(N)
                # sweeps are skipped; every [G] row recomputes (cheap), so
                # the persistent columns refresh wholesale
                with obs.span(
                        "decide_ordered" if with_orders else "decide_full",
                        kind="device"):
                    out = _kernel.decide_jit(
                        self._cache.cluster, now, impl=self._impl,
                        aggregates=_kernel.aggregates_tuple(self._aggs),
                        with_orders=with_orders,
                    )
                    run_overlap()
                    if not (self._overlap and with_orders):
                        # fence blocks (and propagates device failures) —
                        # one synchronization, not a redundant pair; an
                        # overlapped ordered tick instead lets the caller's
                        # unpack absorb the device tail (phase unfenced)
                        out = obs.fence(out)
                self._set_prev(out)
                return out
            dirty = np.asarray(self._aggs.dirty)
            self._note_dirty(dirty)
            with obs.span("delta_decide", kind="device"):
                idx = _kernel.dirty_indices(dirty)
                out, self._aggs = _kernel.delta_decide_jit(
                    self._cache.cluster, self._aggs, self._prev_cols, idx, now)
                # the overlap window the light tick otherwise lacks: the
                # gate reads nodes_delta right after this dispatch, so any
                # host work that can run now (the streaming backend's event
                # pre-drain) hides under the in-flight delta program
                run_overlap()
                # fenced: the lazy gate synchronizes here regardless
                out = obs.fence(out)
            self._set_prev(out)
            return out

        result = _kernel.lazy_orders_decide(dispatch, tainted_any)
        if _record:
            self._record_tick_inputs(result, now, tainted_any)
        else:
            self._replay_pending = []
        if audit_due and self._background:
            # kicked AFTER the dispatch, not before it: the decide mutates
            # neither the resident cluster nor the aggregate sum columns
            # (delta_decide only clears `dirty`, which the compare excludes),
            # so the verdict is identical to an entry-time audit — but the
            # snapshot copy and the worker's recompute both land in the
            # inter-tick gap instead of queuing in front of (or under) this
            # tick's decide
            self._start_background_audit()
        return result

    def _record_tick_inputs(self, result, now, tainted_any: bool) -> None:
        """Attach this tick's inputs (the pending scatter batches) + outcome
        (lazy-orders flag, crc32 decision digest) to the input log — the
        record/replay half of the round-11 tentpole. No-op (and O(1)) when
        recording is off; when on, the digest read synchronizes on the
        decide output, which the documented debug mode accepts."""
        from escalator_tpu.observability import replay as _replay

        pending, self._replay_pending = self._replay_pending, []
        if not _replay.INPUT_LOG.enabled():
            return
        out, ordered = result
        _replay.INPUT_LOG.record({
            "tick": self._ticks,
            "now_sec": int(now),
            "tainted_any": bool(tainted_any),
            "ordered": bool(ordered),
            "digest": _replay.decision_digest(out),
            "batches": pending,
        })

    def _note_dirty(self, dirty_mask: np.ndarray) -> None:
        """Record the tick's consumed dirty-group count ONCE: a lazy-orders
        re-dispatch (light then ordered) runs two delta programs in one
        tick, the second over an already-cleared mask — the first dispatch's
        count is the tick's."""
        from escalator_tpu import observability as obs

        if self._dirty_counted_tick != self._ticks:
            self._dirty_counted_tick = self._ticks
            self.last_dirty_count = int(dirty_mask.sum())
            obs.annotate(dirty_groups=self.last_dirty_count)

    # -- incremental ordered ticks (round 10) -------------------------------

    def _ordered_incremental(self, now, run_overlap=None):
        """An ordered dispatch WITHOUT the full [N] sort: group columns via
        the same ``delta_decide`` program the light tick runs, the ordering
        permutation via the persistent order state's rank-repair merge
        (ops.order_tail). Output contract identical to the full ordered
        decide: every non-order field bit-exact, the ordering WINDOWS
        bit-exact vs the full sort (the whole permutation is, in fact —
        both formulations produce the unique strict 4-key order).
        ``run_overlap`` (round 12) fires after the fused dispatch, before
        the repair's one scalar readback — the ordered tick's overlap
        window."""
        from escalator_tpu import observability as obs

        with obs.span("decide_ordered_incremental", kind="device"):
            dirty = np.asarray(self._aggs.dirty)
            self._note_dirty(dirty)
            idx = _kernel.dirty_indices(dirty)
            if self._order_state is None:
                # bootstrap: no state to repair — separate delta + full-sort
                # dispatches, seeding the key columns + permutation
                out, self._aggs = _kernel.delta_decide_jit(
                    self._cache.cluster, self._aggs, self._prev_cols, idx,
                    now)
                if run_overlap is not None:
                    run_overlap()
                perm, scale_down = self._order_bootstrap(out.tainted_offsets)
            else:
                # steady state: delta decide + order repair as ONE fused
                # program (kernel.ordered_delta_decide_jit) — one dispatch,
                # shared [N] passes; the old state is donated into it
                om, ok1, ok2, operm = self._order_state
                self._order_state = None   # donated — refs die here
                out, self._aggs, ostate = _kernel.ordered_delta_decide_jit(
                    self._cache.cluster, self._aggs, self._prev_cols, idx,
                    now, om, ok1, ok2, operm, self._order_bucket)
                if run_overlap is not None:
                    run_overlap()
                perm, scale_down = self._order_finish(
                    ostate, out.tainted_offsets)
            # tainted block first = untaint order; rolled to the tail =
            # scale-down order (exactly kernel.decide's assembly)
            out = replace(
                out, untaint_order=perm, scale_down_order=scale_down)
            if not self._overlap:
                out = obs.fence(out)
        self._set_prev(out)
        return out

    def _order_bootstrap(self, tainted_offsets):
        """Seed the persistent order state: full key recompute + full 4-key
        sort (there is nothing to repair yet). Returns ``(perm,
        scale_down)`` and stores ``(major, k1, k2, perm)`` for the fused
        steady path."""
        from escalator_tpu import observability as obs
        from escalator_tpu.ops import order_tail as _ot

        nodes = self._cache.cluster.nodes
        with obs.span("order_repair", kind="device"):
            major, k1, k2 = _ot.order_keys_jit(
                self._cache.cluster.groups.emptiest, nodes.valid,
                nodes.group, nodes.tainted, nodes.cordoned,
                nodes.creation_ns, self._aggs.node_pods_remaining)
            perm = _ot.order_sort_jit(major, k1, k2)
            scale_down = jnp.roll(perm, -tainted_offsets[-1])
        self.last_order_dirty_count = int(nodes.valid.shape[0])
        self._order_state = (major, k1, k2, perm)
        self.order_stats["bootstrap"] = (
            self.order_stats.get("bootstrap", 0) + 1)
        obs.annotate(order_path="bootstrap",
                     order_dirty_lanes=self.last_order_dirty_count)
        return perm, scale_down

    def _order_finish(self, ostate, tainted_offsets):
        """Adopt a fused dispatch's order outputs: read back the changed-lane
        count (the tick's ONE host scalar), consult the bucket-overflow and
        dirty-fraction fallbacks to the full key sort, replace the state.
        Returns ``(perm, scale_down)``."""
        from escalator_tpu import observability as obs
        from escalator_tpu.ops import order_tail as _ot

        major, k1, k2, perm, scale_down, count = ostate
        N = int(perm.shape[0])
        with obs.span("order_repair", kind="device"):
            D = int(count)     # the path's one host readback: a scalar
            self.last_order_dirty_count = D
            if D == 0:
                path = "clean"
            elif (D > self._order_repair_max_dirty_frac * N
                    or D > self._order_bucket):
                # past the threshold where the merge stops paying — or the
                # bucket truncated the dirty set, making the merged perm
                # INVALID: full key sort (also reseeds), then grow the
                # bucket so next tick's compaction fits
                perm = _ot.order_sort_jit(major, k1, k2)
                scale_down = jnp.roll(perm, -tainted_offsets[-1])
                path = "full_sort"
                cap = max(1, int(self._order_repair_max_dirty_frac * N))
                self._order_bucket = 1 << (
                    min(max(D, 1), cap) - 1).bit_length()
            else:
                path = "repair"
        # clean/repair: the int(count) read above synchronized the fused
        # program and nothing was dispatched since; full_sort re-dispatched
        # after the read, so the device is busy again
        self.last_decide_synced = path != "full_sort"
        self._order_state = (major, k1, k2, perm)
        self.order_stats[path] = self.order_stats.get(path, 0) + 1
        obs.annotate(order_path=path,
                     order_dirty_lanes=self.last_order_dirty_count)
        return perm, scale_down

    @staticmethod
    def _mismatched_columns(aggs, fresh) -> list:
        """Column names where the maintained aggregates differ bitwise from
        a recompute — the ONE comparison both audit forms run, so the
        background verdict cannot drift from the synchronous one."""
        return [
            f.name for f in fields(_kernel.GroupAggregates)
            if f.name != "dirty"
            and not np.array_equal(np.asarray(getattr(aggs, f.name)),
                                   np.asarray(getattr(fresh, f.name)))
        ]

    def refresh(self) -> bool:
        """Re-derive the aggregates from the resident cluster and assert
        bit-equality against the incrementally maintained state (the
        SYNCHRONOUS self-audit — the background cadence path no longer calls
        this on the tick thread, but the semantics here remain the reference
        the background verdict is proven equivalent to). Returns True when
        the audit passed.

        A mismatch — in BOTH modes — increments
        ``escalator_tpu_incremental_audit_mismatch_total`` (the alertable
        counter the silent backend-mode "repair+log" lacked) and dumps the
        flight recorder, so the ticks whose deltas diverged are captured at
        the moment of detection, not reconstructed from memory."""
        from escalator_tpu import observability as obs

        self.refreshes += 1
        with obs.span("refresh_audit", kind="device"):
            fresh = obs.fence(
                _kernel.compute_aggregates_jit(self._cache.cluster,
                                               impl=self._impl))
            mismatched = self._mismatched_columns(self._aggs, fresh)
        if not mismatched:
            self.last_audit_ok = True
            obs.annotate(refresh_audit="ok")
            return True
        self.last_audit_ok = False
        self._raise_or_repair(mismatched, fresh=fresh)
        return False

    def _raise_or_repair(self, mismatched: list, fresh=None) -> None:
        """The mismatch tail shared by both audit forms: count, dump,
        then raise or repair. Repair adopts a recompute of the CURRENT
        resident cluster and marks every group dirty: the synchronous
        form passes its already-computed ``fresh`` (the cluster has not
        moved since the compare); the background form passes None and
        re-derives, because the snapshot's recompute is one audit-latency
        stale by reconcile time."""
        from escalator_tpu import observability as obs
        from escalator_tpu.metrics import metrics

        metrics.incremental_audit_mismatch.inc()
        obs.journal.JOURNAL.event(
            "audit-mismatch", columns=mismatched, ticks=self._ticks,
            mode=self._on_mismatch)
        dump_path = obs.dump_on_incident("audit-mismatch")
        msg = (
            "incremental aggregate refresh mismatch on columns "
            f"{mismatched} after {self._ticks} ticks — the maintained "
            "state diverged from a from-scratch recompute"
            f" (flight record: {dump_path or 'dump failed'})"
        )
        if self._on_mismatch == "raise":
            obs.annotate(refresh_audit="mismatch-raised")
            raise AggregateParityError(msg)
        obs.annotate(refresh_audit="mismatch-repaired")
        logging.getLogger("escalator_tpu.device_state").error(
            "%s; repairing: adopting a fresh recompute and marking every "
            "group dirty", msg)
        if fresh is None:
            fresh = _kernel.compute_aggregates_jit(self._cache.cluster,
                                                   impl=self._impl)
        G = int(np.asarray(fresh.dirty).shape[0])
        self._aggs = replace(fresh, dirty=jnp.ones(G, bool))

    # -- background audit (round 10) ----------------------------------------

    def _await_snapshot(self) -> None:
        """Gate a device mutation on the in-flight audit's double-buffer
        copy. The worker freezes the snapshot and signals; until then the
        live buffers may not be DONATED out from under it (the copy would
        read reused memory — or a deleted-array error — instead of this
        tick's state). Nearly always already signalled: the copy runs
        under the caller's inter-dispatch host work (upsert/drain/gather).
        A residual wait is real cost, so it runs under a visible span
        instead of hiding inside the next scatter's dispatch."""
        evt = self._snap_ready
        if evt is None:
            return
        self._snap_ready = None
        if evt.is_set():
            return
        from escalator_tpu import observability as obs

        with obs.span("audit_snapshot_wait"):
            evt.wait()

    def _start_background_audit(self) -> None:
        """The audit tick's on-path cost: a ref capture + thread handoff.
        Even the double-buffer snapshot copy is dispatched from the WORKER
        — jax 0.4.x CPU dispatch is synchronous, so dispatching the copy
        here would put the full O(cluster) memcpy back on the audit tick
        (~30 ms at 1M pods: the exact spike this mode exists to kill).
        The next device mutation gates on the frozen snapshot instead
        (:meth:`_await_snapshot`), where the copy overlaps the caller's
        inter-dispatch host work. The recompute + bit-compare then run on
        the worker against the frozen state — the same inputs the
        synchronous audit would have read this tick."""
        import threading
        from concurrent.futures import ThreadPoolExecutor

        from escalator_tpu import observability as obs

        if self._audit_future is not None:
            # a previous audit still in flight at the next cadence point
            # (pathological cadence/duration ratio): settle it first so at
            # most one audit exists and verdicts stay ordered
            self._reconcile_audit(block=True)
        self.refreshes += 1
        if self._audit_pool is None:
            self._audit_pool = ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="escalator-tpu-audit")
        # capture the refs NOW: later reassignment (a repair, the next
        # tick's delta aggs) must not move the audit off this tick's inputs
        self._snap_ready = snap_ready = threading.Event()
        self._audit_future = self._audit_pool.submit(
            self._audit_worker, self._cache.cluster, self._aggs, snap_ready)
        obs.annotate(refresh_audit="background-started")

    def _audit_worker(self, cluster, aggs, snap_ready) -> list:
        """Worker-thread body: freeze the double buffer, then recompute +
        compare against it. Runs under its own span root, so the flight
        recorder carries one ``refresh_audit_bg`` timeline per background
        audit (span state is thread-local — no interleaving with tick
        timelines). ``snap_ready`` is set the moment the snapshot has
        materialized — set even on failure, so a worker error surfaces at
        reconcile as the future's exception, never as a deadlocked tick
        thread."""
        from escalator_tpu import observability as obs

        from escalator_tpu.chaos import CHAOS

        with obs.span("refresh_audit_bg", kind="device"):
            try:
                with obs.span("audit_snapshot", kind="device"):
                    snap_cluster, snap_aggs = obs.fence(
                        _audit_snapshot(cluster, aggs))
            finally:
                snap_ready.set()
            # account the frozen double buffer while it lives (resource
            # registry owner "audit_double_buffer"): the transient 2x
            # cluster footprint is part of the HBM envelope and must be
            # visible, not folklore
            self._audit_bufs = (snap_cluster, snap_aggs)
            try:
                # chaos: worker-thread death AFTER the snapshot gate
                # released — the tick thread must never deadlock on a dead
                # worker, and the reconcile path must degrade to the
                # synchronous audit
                CHAOS.inject("audit_worker")
                fresh = obs.fence(_kernel.compute_aggregates_jit(
                    snap_cluster, impl=self._impl))
                mismatched = self._mismatched_columns(snap_aggs, fresh)
            finally:
                self._audit_bufs = None
            obs.annotate(refresh_audit="ok" if not mismatched
                         else f"mismatch:{','.join(mismatched)}")
        return mismatched

    def _reconcile_audit(self, block: bool) -> None:
        """Adopt a background audit's verdict on the tick thread. With
        ``block=False`` (every tick's entry) a still-running audit is left
        alone; ``block=True`` (:meth:`drain_audit`, or an audit still
        pending at the next cadence point) waits for it. Mismatch semantics
        are the synchronous audit's, one tick boundary later."""
        fut = self._audit_future
        if fut is None or (not block and not fut.done()):
            return
        self._audit_future = None
        try:
            mismatched = fut.result()
        except Exception:
            # worker-thread death (round 11 hardening): before this, a dead
            # audit worker crashed the NEXT tick with the worker's traceback
            # — an observability thread taking down the control loop. Now it
            # degrades: count it, dump the ring (the ticks around the death
            # are the post-mortem), and re-run the audit SYNCHRONOUSLY so
            # the verdict this cadence point owed still lands with the exact
            # raise/repair semantics. The sync form reads the CURRENT
            # resident cluster — one audit-latency later than the dead
            # worker's snapshot, which the cadence contract permits.
            from escalator_tpu.metrics import metrics

            metrics.audit_worker_failures.inc()
            from escalator_tpu import observability as obs

            obs.journal.JOURNAL.event("audit-worker-death", ticks=self._ticks)
            dump_path = obs.dump_on_incident("audit-worker-death")
            logging.getLogger("escalator_tpu.device_state").error(
                "background refresh-audit worker died; degrading to the "
                "synchronous audit (flight record: %s)",
                dump_path or "dump failed", exc_info=True)
            obs.annotate(refresh_audit="worker-died")
            self.refresh()
            return
        self.last_audit_ok = not mismatched
        if mismatched:
            self._raise_or_repair(mismatched)

    def drain_audit(self) -> bool:
        """Block until any in-flight background audit completes and
        reconcile its verdict (raising / repairing exactly as the
        synchronous audit would). Returns the last audit verdict (True =
        passed, or no audit has ever run)."""
        self._reconcile_audit(block=True)
        return self.last_audit_ok

    # -- snapshot / restore (round 11) --------------------------------------

    def snapshot_state(self):
        """Freeze the persistent device state — resident cluster, maintained
        aggregates, the 13 decision columns, the order state — into host
        arrays ready for :func:`escalator_tpu.ops.snapshot.write_snapshot`.
        Returns ``(leaves, meta)``, or None before the first decide (there
        is no decision state worth persisting yet).

        The freeze is the audit double buffer's construction generalized
        (``snapshot._freeze_state``): one device program of pure on-device
        copies, no donation — safe to run concurrently with an in-flight
        background audit (neither donates) and consistent by construction
        when called at a tick boundary, which every caller
        (:class:`~escalator_tpu.ops.snapshot.SnapshotWriter` per tick,
        tests) does. The host copy of the frozen buffers is the method's
        only blocking cost."""
        from escalator_tpu.ops import snapshot as snaplib

        if self._prev_cols is None:
            return None
        from escalator_tpu import observability as obs

        with obs.span("snapshot_freeze", kind="device"):
            frozen = obs.fence(snaplib.freeze_state(
                (self._cache.cluster, self._aggs, self._prev_cols,
                 self._order_state)))
        cluster_f, aggs_f, cols_f, order_f = frozen
        # account the device-side freeze copies while they live (resource
        # registry owner "snapshot_freeze") — they die when the host copy
        # below completes and `frozen` goes out of scope
        self._snapshot_frozen = frozen
        try:
            leaves = snaplib.state_to_leaves(cluster_f, aggs_f, cols_f,
                                             order_f)
        finally:
            self._snapshot_frozen = None
        meta = {
            "tick": self._ticks,
            "order_bucket": self._order_bucket,
            "pod_capacity": self._cache.pod_capacity,
            "node_capacity": self._cache.node_capacity,
            "num_groups": int(np.asarray(aggs_f.dirty).shape[0]),
            "impl": self._impl,
        }
        return leaves, meta


def restore_decider(leaves, meta, device=None, impl: "str | None" = None,
                    refresh_every: "Optional[int | str]" = None,
                    on_mismatch: str = "repair",
                    background: Optional[bool] = None,
                    incremental_orders: bool = True,
                    overlap: bool = False,
                    post_restore_audit: bool = True):
    """Warm-start a ``(DeviceClusterCache, IncrementalDecider)`` pair from a
    snapshot's ``(leaves, meta)`` (ops/snapshot.py) — the standby leader's
    O(1)-tick restore path. Costs ONE H2D upload of the state (the donated
    ``snapshot.restore_adopt`` makes the device-side handover copy-free);
    performs NO re-list, NO aggregate recompute, NO decide.

    ``post_restore_audit=True`` (the default everywhere but replay) kicks
    the background refresh audit immediately: the worker recomputes the
    aggregates from the restored cluster and bit-compares against the
    restored maintained state, so a corrupted-but-crc-valid snapshot (or a
    serializer bug) is detected within one audit latency with the standard
    raise/repair semantics — the restore's bit-exactness is self-checking,
    not assumed.

    Raises :class:`~escalator_tpu.ops.snapshot.SnapshotCorruptError` on
    structural violations the crc pass cannot see (missing leaves, shape
    inconsistencies, an order state that is not a permutation)."""
    from escalator_tpu import observability as obs
    from escalator_tpu.ops import snapshot as snaplib

    with obs.span("restore", kind="device"):
        cluster, aggs, prev_cols, order_state = snaplib.leaves_to_state(leaves)
        P1 = int(cluster.pods.valid.shape[0])
        N1 = int(cluster.nodes.valid.shape[0])
        G = int(cluster.groups.valid.shape[0])
        if (int(meta.get("pod_capacity", -1)) != P1 - 1
                or int(meta.get("node_capacity", -1)) != N1 - 1
                or int(meta.get("num_groups", -1)) != G):
            raise snaplib.SnapshotCorruptError(
                "snapshot meta capacities disagree with its array shapes: "
                f"meta={meta!r} vs pods[{P1}] nodes[{N1}] groups[{G}]")
        if order_state is not None:
            from escalator_tpu.ops.order_tail import validate_order_state

            try:
                validate_order_state(*order_state, num_lanes=N1)
            except ValueError as e:
                raise snaplib.SnapshotCorruptError(
                    f"snapshot order state invalid: {e}") from e
        # host gather views: the unpadded leading lanes of the snapshot's
        # own columns (callers rebind live views via set_host per tick)
        host_pods = type(cluster.pods)(**{
            f.name: getattr(cluster.pods, f.name)[:P1 - 1]
            for f in fields(type(cluster.pods))})
        host_nodes = type(cluster.nodes)(**{
            f.name: getattr(cluster.nodes, f.name)[:N1 - 1]
            for f in fields(type(cluster.nodes))})
        with obs.span("restore_upload", kind="device"):
            resident = obs.fence(snaplib.restore_adopt(
                (cluster, aggs, prev_cols, order_state), device=device))
        r_cluster, r_aggs, r_cols, r_order = resident
        cache = DeviceClusterCache.adopt_resident(
            r_cluster, host_pods, host_nodes, device=device)
        inc = IncrementalDecider(
            cache, impl=impl if impl is not None else meta.get("impl", "xla"),
            refresh_every=refresh_every, on_mismatch=on_mismatch,
            background=background, incremental_orders=incremental_orders,
            overlap=overlap, aggregates=r_aggs)
        inc._prev_cols = tuple(r_cols)
        inc._order_state = tuple(r_order) if r_order is not None else None
        inc._order_bucket = int(meta.get("order_bucket", inc._order_bucket))
        inc._ticks = int(meta.get("tick", 0))
        inc.restored = True
        obs.annotate(restored=True, restored_tick=inc._ticks)
        if post_restore_audit:
            # bit-exactness of the restored aggregates vs a recompute of the
            # restored cluster, verified off the critical path
            inc._start_background_audit()
    return cache, inc
