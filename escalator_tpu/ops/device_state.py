"""Device-resident cluster state: the O(changes) host->device data path.

SURVEY.md §7 names the host<->device path as a hard part: at 100k pods, re-uploading
the packed arrays every tick costs tens of ms — more than the decision kernel itself.
The reference has no analog (its Go loops rebuild aggregate state from the watch cache
each tick, pkg/controller/controller.go:192-272); the TPU-native design instead keeps
the ``ClusterArrays`` resident in device HBM and applies each tick's watch deltas as a
scatter update:

- the native C++ store (``native/statestore.cpp``) marks dirty slots as watch events
  are ingested and drains a deduplicated slot list per tick;
- the host gathers just those lanes from the zero-copy column views (numpy fancy
  indexing, O(changes));
- one jitted scatter (``jnp.ndarray.at[idx].set``) with **donated** operands updates
  the resident arrays in place — XLA aliases input and output buffers, so HBM traffic
  per tick is O(changes), not O(cluster).

Delta batches are padded to power-of-two buckets so jit compiles a handful of shapes
total (no recompilation storm as churn fluctuates). Padding lanes target a dedicated
scratch lane (index ``P``/``N`` — the resident arrays carry one extra, never-valid
lane) and all write the same constants, keeping duplicate-index scatter deterministic.

Group config/state ([G]-sized, mutated by the controller every tick: locks, cached
capacity, requested nodes) rides along in the same jit call — it is tiny, so it is
simply re-uploaded rather than diffed.
"""

from __future__ import annotations

import logging
from dataclasses import fields, replace
from functools import partial, reduce
from operator import or_
from typing import Optional

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp
from jax import tree_util

from escalator_tpu.core.arrays import (
    NO_TAINT_TIME,
    ClusterArrays,
    GroupArrays,
    NodeArrays,
    PodArrays,
)
from escalator_tpu.ops import kernel as _kernel  # noqa: F401  (ClusterArrays pytree)


def _register(cls):
    tree_util.register_pytree_node(
        cls,
        lambda obj: ([getattr(obj, f.name) for f in fields(cls)], None),
        lambda aux, leaves: cls(*leaves),
    )


_register(PodArrays)
_register(NodeArrays)
_register(GroupArrays)

_MIN_BUCKET = 64


def _bucket(n: int) -> int:
    """Smallest power-of-two >= n (min 64): bounds the set of compiled shapes."""
    return max(_MIN_BUCKET, 1 << (max(n, 1) - 1).bit_length())


_POD_PAD = {"node": -1}
_NODE_PAD = {"taint_time_sec": NO_TAINT_TIME}


def _pad_one_lane(soa, pad_defaults):
    """Copy of a Pod/NodeArrays with one extra scratch lane (valid=False)."""
    out = {}
    for f in fields(soa):
        arr = getattr(soa, f.name)
        fill = pad_defaults.get(f.name, 0)
        out[f.name] = np.concatenate([arr, np.full(1, fill, arr.dtype)])
    return type(soa)(**out)


def _gather_padded(soa, slots: np.ndarray, bucket: int, scratch: int, pad_defaults):
    """(idx[int32 bucket], values SoA of [bucket]) for a dirty-slot batch.

    Pad lanes point at the scratch lane and write that lane's invariant values
    (valid=False etc.), so duplicate-index scatter stays deterministic.
    """
    k = len(slots)
    idx = np.full(bucket, scratch, np.int32)
    idx[:k] = slots
    vals = {}
    for f in fields(soa):
        arr = getattr(soa, f.name)
        fill = pad_defaults.get(f.name, 0)
        v = np.full(bucket, fill, arr.dtype)
        if k:
            v[:k] = arr[slots]
        vals[f.name] = v
    return idx, type(soa)(**vals)


def _scatter_body(pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals):
    def upd(soa, idx, vals):
        return type(soa)(
            **{
                f.name: getattr(soa, f.name).at[idx].set(getattr(vals, f.name))
                for f in fields(soa)
            }
        )

    return ClusterArrays(
        groups=groups,
        pods=upd(pods, pod_idx, pod_vals),
        nodes=upd(nodes, node_idx, node_vals),
    )


# Pods/nodes are donated (in-place on device); groups is NOT — it may be either a
# fresh host upload or the pass-through resident value, and donating a buffer that
# is also returned untouched would invalidate the caller's reference.
_scatter_update = partial(jax.jit, donate_argnums=(0, 1))(_scatter_body)


def _pack_delta_bytes(idx: np.ndarray, vals) -> np.ndarray:
    """Serialize (idx, SoA values) into ONE uint8 buffer, column-major:
    [idx int32 bytes][field0 bytes][field1 bytes]... Sixteen per-column host
    transfers become two (pods + nodes) — on transports where each transfer
    pays fixed latency, that is most of the scatter phase. The device side
    (:func:`_unpack_delta`) mirrors this layout exactly (both iterate
    ``fields()`` in order), and integer/bool bitcasts are exact."""
    parts = [np.ascontiguousarray(idx, np.int32).view(np.uint8)]
    for f in fields(vals):
        parts.append(np.ascontiguousarray(getattr(vals, f.name)).view(np.uint8))
    return np.concatenate(parts)


def _unpack_delta(buf, field_dtypes):
    """(idx, {field: array}) from a :func:`_pack_delta_bytes` buffer, inside
    jit. ``field_dtypes`` is static; the bucket size is inferred from the
    buffer length."""
    lane_bytes = 4 + sum(np.dtype(dt).itemsize for _, dt in field_dtypes)
    B = buf.shape[0] // lane_bytes

    def take(off, dt):
        k = np.dtype(dt).itemsize
        chunk = jax.lax.dynamic_slice_in_dim(buf, off * B, k * B)
        if k == 1:
            return chunk.astype(dt), off + k
        return (
            jax.lax.bitcast_convert_type(chunk.reshape(B, k), dt),
            off + k,
        )

    idx, off = take(0, np.int32)
    vals = {}
    for name, dt in field_dtypes:
        vals[name], off = take(off, dt)
    return idx, vals


def _field_dtypes(soa):
    return tuple((f.name, np.dtype(getattr(soa, f.name).dtype).type)
                 for f in fields(soa))


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("pod_dts", "node_dts"))
def _scatter_update_from_packed(pods, nodes, groups, pod_buf, node_buf,
                                pod_dts, node_dts):
    pod_idx, pod_vals = _unpack_delta(pod_buf, pod_dts)
    node_idx, node_vals = _unpack_delta(node_buf, node_dts)
    return _scatter_body(
        pods, nodes, groups,
        pod_idx, type(pods)(**pod_vals), node_idx, type(nodes)(**node_vals),
    )


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("impl", "with_orders"))
def _scatter_update_decide(
    pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals, now_sec,
    impl="xla", with_orders=True,
):
    """Fused tick: scatter this tick's deltas AND run the decision kernel in ONE
    device program. Measured on the v5e tunnel this is NOT faster than the
    two-call path (back-to-back async dispatches already pipeline, and the
    donation handoff adds overhead), so the native backend keeps the two-step
    default; this stays as the single-dispatch option for transports where each
    dispatch costs a full round-trip."""
    cluster = _scatter_body(
        pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals
    )
    return cluster, _kernel.decide(cluster, now_sec, impl=impl,
                                   with_orders=with_orders)


# ---------------------------------------------------------------------------
# Incremental aggregates (round-8 tentpole): the scatter phase, which already
# knows exactly which lanes changed, also emits exact per-group aggregate
# deltas into the persistent GroupAggregates columns and marks dirty groups.
# ---------------------------------------------------------------------------


def aggregate_lane_deltas(pod_old, pod_new, node_old, node_new,
                          node_group_old, node_group_new, G: int, N: int):
    """Exact int64 aggregate deltas from a delta batch's (old, new) lane
    values: subtract each touched lane's old contribution, add its new one.
    The i64 milli-CPU / byte columns (the R2 dtype-parity contract) make
    this drift-free by construction — integer sums commute and associate
    exactly, so ``aggregate + delta`` is bit-equal to a from-scratch
    recompute. Contribution terms mirror ``kernel.aggregate_pods`` /
    ``kernel.aggregate_nodes`` term by term.

    ``pod_old``/``pod_new`` are PodArrays of the SAME ``[B]`` lanes before
    and after the scatter (pad lanes carry identical never-valid values on
    both sides and so contribute zero); likewise the node batch. Lane
    indices within a batch must be unique — the native store drains a
    DEDUPLICATED dirty list, and the host-diff backends emit np.nonzero
    indices; a duplicate would double-count its old contribution.
    ``node_group_old``/``node_group_new`` are the full ``[N]`` node->group
    vectors before/after the scatter (the same-group pod filter of
    ``node_pods_remaining`` reads them).

    Returns ``(deltas: dict, touched: bool[G], node_group_changed: bool[])``
    where ``deltas`` has one ``[G]`` int64 entry per group-sum column plus
    ``node_pods_remaining`` (``[N]`` int64), ``touched`` marks every group a
    delta landed in (the dirty-mask contribution), and
    ``node_group_changed`` is True when any batched node lane's group column
    changed — the one case where pods OUTSIDE the batch change their
    pods-remaining contribution and the caller must re-sweep that column
    (``kernel.node_pods_remaining_sweep``)."""
    import jax
    import jax.numpy as jnp

    seg = lambda v, i, n: jax.ops.segment_sum(v, i, num_segments=n)  # noqa: E731
    I64 = jnp.int64

    def pod_terms(p, node_group):
        w = p.valid.astype(I64)
        gid = jnp.where(p.valid, p.group, 0)
        on_w = (
            p.valid
            & (p.node >= 0)
            & (p.group == node_group[jnp.clip(p.node, 0, N - 1)])
        )
        tgt = jnp.where(p.valid & (p.node >= 0), p.node, 0)
        return gid, w, on_w.astype(I64), tgt

    gid_o, w_o, on_o, tgt_o = pod_terms(pod_old, node_group_old)
    gid_n, w_n, on_n, tgt_n = pod_terms(pod_new, node_group_new)

    def node_terms(n):
        gid = jnp.where(n.valid, n.group, 0)
        u = (n.valid & ~n.tainted & ~n.cordoned).astype(I64)
        t = (n.valid & n.tainted & ~n.cordoned).astype(I64)
        c = (n.valid & n.cordoned).astype(I64)
        return gid, n.valid.astype(I64), u, t, c

    ngid_o, nv_o, u_o, t_o, c_o = node_terms(node_old)
    ngid_n, nv_n, u_n, t_n, c_n = node_terms(node_new)

    deltas = {
        "cpu_req": seg(pod_new.cpu_milli * w_n, gid_n, G)
        - seg(pod_old.cpu_milli * w_o, gid_o, G),
        "mem_req": seg(pod_new.mem_bytes * w_n, gid_n, G)
        - seg(pod_old.mem_bytes * w_o, gid_o, G),
        "num_pods": seg(w_n, gid_n, G) - seg(w_o, gid_o, G),
        "node_pods_remaining": seg(on_n, tgt_n, N) - seg(on_o, tgt_o, N),
        "cpu_cap": seg(node_new.cpu_milli * u_n, ngid_n, G)
        - seg(node_old.cpu_milli * u_o, ngid_o, G),
        "mem_cap": seg(node_new.mem_bytes * u_n, ngid_n, G)
        - seg(node_old.mem_bytes * u_o, ngid_o, G),
        "num_nodes": seg(nv_n, ngid_n, G) - seg(nv_o, ngid_o, G),
        "num_untainted": seg(u_n, ngid_n, G) - seg(u_o, ngid_o, G),
        "num_tainted": seg(t_n, ngid_n, G) - seg(t_o, ngid_o, G),
        "num_cordoned": seg(c_n, ngid_n, G) - seg(c_o, ngid_o, G),
    }
    touched = jnp.zeros(G, bool)
    for gid, valid in ((gid_o, pod_old.valid), (gid_n, pod_new.valid),
                       (ngid_o, node_old.valid), (ngid_n, node_new.valid)):
        # invalid lanes point at group 0 with a False update: a no-op
        touched = touched.at[gid].max(valid)
    # ANY group-column change counts, valid or not: aggregate_pods' same-group
    # filter reads node_group regardless of the node's validity, so a stale
    # group column flipping under an invalid lane still moves pods-remaining
    node_group_changed = jnp.any(node_old.group != node_new.group)
    return deltas, touched, node_group_changed


def group_rows_changed(groups_old, groups_new):
    """Elementwise ``[G]`` mask of group config/state rows that changed —
    the dirty-mask contribution of the per-tick group re-upload. Shared by
    the native scatter program and the pod-axis delta scatter so the
    config-dirty semantics cannot drift."""
    return reduce(or_, (
        getattr(groups_old, f.name) != getattr(groups_new, f.name)
        for f in fields(type(groups_new))
    ))


def fold_aggregate_deltas(aggs, deltas, touched, group_row_changed,
                          node_pods_remaining):
    """Apply one batch's exact deltas to the maintained
    :class:`kernel.GroupAggregates` — THE single place the column list is
    folded, used by both ``_scatter_update_aggs`` and
    ``parallel.podaxis.make_delta_scatter`` (a column added to
    GroupAggregates that is missed here fails loudly at construction
    instead of silently breaking the refresh audit's bit-equality on one
    path). ``node_pods_remaining`` is passed ready-made because the two
    callers correct the node-group-change case differently (in-program
    re-sweep vs host-level flag)."""
    return _kernel.GroupAggregates(
        cpu_req=aggs.cpu_req + deltas["cpu_req"],
        mem_req=aggs.mem_req + deltas["mem_req"],
        num_pods=aggs.num_pods + deltas["num_pods"],
        cpu_cap=aggs.cpu_cap + deltas["cpu_cap"],
        mem_cap=aggs.mem_cap + deltas["mem_cap"],
        num_nodes=aggs.num_nodes + deltas["num_nodes"],
        num_untainted=aggs.num_untainted + deltas["num_untainted"],
        num_tainted=aggs.num_tainted + deltas["num_tainted"],
        num_cordoned=aggs.num_cordoned + deltas["num_cordoned"],
        node_pods_remaining=node_pods_remaining,
        dirty=aggs.dirty | touched | group_row_changed,
    )


@partial(jax.jit, donate_argnums=(0, 1, 8))
def _scatter_update_aggs(pods, nodes, groups_old, groups_new, pod_idx,
                         pod_vals, node_idx, node_vals, aggs):
    """The incremental tick's scatter: apply the dirty-lane deltas to the
    resident arrays (exactly ``_scatter_body``) AND maintain the persistent
    per-group aggregates in the same device program — subtract each touched
    lane's old contribution, add its new one, and fold the touched groups
    (plus every group whose config/state row changed between ``groups_old``
    and ``groups_new``) into the dirty mask that ``kernel.delta_decide``
    consumes. Donates pods/nodes (as ``_scatter_update``) and the aggregate
    columns (each output sum aliases its input buffer: one add in place)."""
    G = groups_new.valid.shape[0]
    N = nodes.valid.shape[0]
    gather = lambda soa, idx: type(soa)(  # noqa: E731
        **{f.name: getattr(soa, f.name)[idx] for f in fields(soa)}
    )
    pod_old = gather(pods, pod_idx)
    node_old = gather(nodes, node_idx)
    node_group_old = nodes.group
    cluster = _scatter_body(
        pods, nodes, groups_new, pod_idx, pod_vals, node_idx, node_vals
    )
    deltas, touched, node_group_changed = aggregate_lane_deltas(
        pod_old, pod_vals, node_old, node_vals,
        node_group_old, cluster.nodes.group, G, N,
    )
    # the rare exact-correction case: a node lane's group column changed, so
    # pods outside the batch moved their pods-remaining contribution — one
    # O(P) column re-sweep (still no O(P) group sums; those are delta-exact)
    npr = jax.lax.cond(
        node_group_changed,
        lambda: _kernel.node_pods_remaining_sweep(
            cluster.pods, cluster.nodes.group, N),
        lambda: aggs.node_pods_remaining + deltas["node_pods_remaining"],
    )
    aggs_out = fold_aggregate_deltas(
        aggs, deltas, touched, group_rows_changed(groups_old, groups_new), npr)
    return cluster, aggs_out


class DeviceClusterCache:
    """Keeps the packed cluster resident on one device across ticks.

    Construct from host-side arrays (typically the native store's zero-copy views),
    then per tick call :meth:`apply_dirty` with the store's drained dirty-slot lists.
    ``cluster`` is the jit-ready device value for ``ops.kernel.decide``.
    """

    def __init__(self, host: ClusterArrays, device=None):
        if device is None:
            # wedged-transport guard: raw library construction (no
            # CLI/backend upstream) reaches backend init right here, and a
            # wedged tunnel hangs it forever; cached per process
            from escalator_tpu.jaxconfig import guarded_devices

            device = guarded_devices()[0]
        self._device = device
        self._host_pods = host.pods
        self._host_nodes = host.nodes
        self.pod_capacity = int(host.pods.valid.shape[0])
        self.node_capacity = int(host.nodes.valid.shape[0])
        self._cluster = jax.device_put(
            ClusterArrays(
                groups=host.groups,
                pods=_pad_one_lane(host.pods, _POD_PAD),
                nodes=_pad_one_lane(host.nodes, _NODE_PAD),
            ),
            self._device,
        )

    @property
    def cluster(self) -> ClusterArrays:
        return self._cluster

    @property
    def device(self):
        """The device the cluster is resident on (impl selection keys off its
        platform — see ops.kernel.native_tick_impl)."""
        return self._device

    def set_host(self, pods: PodArrays, nodes: NodeArrays) -> None:
        """Rebind the host-side views gathers read from. Needed when the store
        re-views its buffers (growth) or a per-tick corrected view (dry mode)
        replaces the raw columns. Shapes must match the resident capacity."""
        if (
            int(pods.valid.shape[0]) != self.pod_capacity
            or int(nodes.valid.shape[0]) != self.node_capacity
        ):
            raise ValueError(
                "host view shape changed; use refresh_full() after store growth"
            )
        self._host_pods = pods
        self._host_nodes = nodes

    def _gather_deltas(self, pod_slots: np.ndarray, node_slots: np.ndarray):
        """(pod_idx, pod_vals, node_idx, node_vals) for a dirty-slot batch —
        the shared O(changes) host gather both tick paths use."""
        pidx, pvals = _gather_padded(
            self._host_pods,
            np.asarray(pod_slots, np.int64),
            _bucket(len(pod_slots)),
            self.pod_capacity,
            _POD_PAD,
        )
        nidx, nvals = _gather_padded(
            self._host_nodes,
            np.asarray(node_slots, np.int64),
            _bucket(len(node_slots)),
            self.node_capacity,
            _NODE_PAD,
        )
        return pidx, pvals, nidx, nvals

    def gather_deltas(self, pod_slots: np.ndarray, node_slots: np.ndarray):
        """The host-side half of :meth:`apply_dirty`: copy the dirty lanes out
        of the (live, possibly shared) host views into padded numpy buffers.
        Callers that share the views with a writer thread run THIS under the
        store lock and :meth:`apply_gathered` outside it — the gather is the
        only part that reads shared memory; the device dispatch (and any jit
        compile it triggers) must not stall ingestion."""
        return self._gather_deltas(pod_slots, node_slots)

    def apply_gathered(
        self, gathered, groups: Optional[GroupArrays] = None
    ) -> ClusterArrays:
        """Device half of :meth:`apply_dirty`: scatter a `gather_deltas` batch
        (already-copied buffers — safe to run unlocked) into the resident arrays."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = gathered
        self._cluster = _scatter_update(
            self._cluster.pods, self._cluster.nodes, groups, pidx, pvals, nidx, nvals
        )
        return self._cluster

    def apply_gathered_with_aggregates(self, gathered, groups, aggs):
        """:meth:`apply_gathered` fused with the persistent-aggregate delta
        maintenance (``_scatter_update_aggs``): scatter the batch into the
        resident arrays and return the updated :class:`GroupAggregates`
        (donated in, replaced out — drop the old reference). ``groups`` may
        be None to keep the resident group rows (no config-dirty compare
        triggers then)."""
        groups_old = self._cluster.groups
        if groups is None:
            groups = groups_old
        pidx, pvals, nidx, nvals = gathered
        self._cluster, aggs = _scatter_update_aggs(
            self._cluster.pods, self._cluster.nodes, groups_old, groups,
            pidx, pvals, nidx, nvals, aggs,
        )
        return self._cluster, aggs

    def apply_dirty(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        groups: Optional[GroupArrays] = None,
    ) -> ClusterArrays:
        """Scatter this tick's dirty lanes (plus fresh group state) into the
        resident arrays. O(changes) host work + transfer; returns the updated
        device cluster."""
        return self.apply_gathered(self.gather_deltas(pod_slots, node_slots), groups)

    def apply_dirty_packed(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        groups: Optional[GroupArrays] = None,
    ) -> ClusterArrays:
        """:meth:`apply_dirty` with the delta batch crossing host->device as
        TWO packed byte buffers instead of sixteen per-column arrays (see
        ``_pack_delta_bytes``). Bit-identical resident state (integer/bool
        bitcasts are exact — test-locked); which variant is faster is a
        transport property, so the bench times both per capture and the
        default stays the per-column path until a device capture says
        otherwise."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = self._gather_deltas(pod_slots, node_slots)
        self._cluster = _scatter_update_from_packed(
            self._cluster.pods, self._cluster.nodes, groups,
            _pack_delta_bytes(pidx, pvals), _pack_delta_bytes(nidx, nvals),
            _field_dtypes(self._host_pods), _field_dtypes(self._host_nodes),
        )
        return self._cluster

    def apply_dirty_and_decide(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        now_sec,
        groups: Optional[GroupArrays] = None,
        impl: str = "xla",
        with_orders: bool = True,
    ):
        """Fused per-tick path: scatter the dirty lanes and run the decision
        kernel in one device dispatch. Returns the DecisionArrays; the updated
        cluster stays resident (``self.cluster``). ``with_orders=False`` is
        the lazy-orders light program (kernel.decide docstring) so the fused
        variant prices the same steady-state tick as the two-call path."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = self._gather_deltas(pod_slots, node_slots)
        self._cluster, out = _scatter_update_decide(
            self._cluster.pods, self._cluster.nodes, groups,
            pidx, pvals, nidx, nvals, jnp.int64(now_sec), impl=impl,
            with_orders=with_orders,
        )
        return out

    def refresh_full(self, host: ClusterArrays) -> ClusterArrays:
        """Full re-upload after a capacity change (store growth re-views buffers;
        resident shapes must follow). Rare by design — capacities double."""
        self.__init__(host, self._device)
        return self._cluster


class AggregateParityError(AssertionError):
    """The incrementally maintained aggregates diverged from a from-scratch
    recompute — the refresh audit's bit-equality contract was broken (a
    delta-maintenance bug, or a caller mutating resident state outside the
    incremental scatter path)."""


class IncrementalDecider:
    """Owns the persistent incremental-decide state for one
    :class:`DeviceClusterCache`: the :class:`kernel.GroupAggregates`
    maintained by scatter deltas, the persistent ``[G]`` decision columns,
    and the refresh-cadence self-audit — the round-8 tentpole's
    orchestration, shared by the native backend, the host-diff repack
    backend (controller.backend.IncrementalJaxBackend) and bench cfg14.

    Per tick: :meth:`apply_gathered` (instead of the cache's plain
    ``apply_gathered``) scatters the dirty lanes AND folds their exact
    aggregate deltas + dirty-group marks in one device program; then
    :meth:`decide` runs the lazy-orders protocol over the incremental
    programs — the LIGHT dispatch is ``kernel.delta_decide`` on the
    compacted dirty rows (O(D + N), no O(P) sweep, no sort, zero
    collectives), and the ORDERED dispatch is the full ``kernel.decide``
    fed the persistent aggregates (so even drain ticks skip the O(cluster)
    aggregation; the ordering tail already runs only there).

    ``refresh_every`` (default env ESCALATOR_TPU_REFRESH_EVERY, else 256)
    periodically re-derives the aggregates from scratch and asserts
    BIT-equality against the maintained state, so correctness is
    self-auditing in production; ``on_mismatch`` is "raise"
    (:class:`AggregateParityError`) or "repair" (log an error, adopt the
    recomputed truth, mark every group dirty). The audit is O(cluster) —
    same cost as one pre-round-8 decide — amortized over the cadence.

    The aggregate sweeps pin ``impl="xla"``-style scatter adds regardless of
    the construction ``impl`` only at delta scale; the bootstrap/refresh
    full sweeps honor ``impl`` (a TPU caller keeps the measured Pallas win
    where it exists — the O(cluster) recompute)."""

    def __init__(self, cache: DeviceClusterCache, impl: str = "xla",
                 refresh_every: Optional[int] = None,
                 on_mismatch: str = "raise"):
        import os

        if on_mismatch not in ("raise", "repair"):
            raise ValueError(f"unknown on_mismatch {on_mismatch!r}")
        if refresh_every is None:
            refresh_every = int(os.environ.get(
                "ESCALATOR_TPU_REFRESH_EVERY", "256"))
        self._cache = cache
        self._impl = impl
        self._refresh_every = int(refresh_every)
        self._on_mismatch = on_mismatch
        self._aggs = _kernel.compute_aggregates_jit(cache.cluster, impl=impl)
        self._prev_cols = None   # tuple in kernel.GROUP_DECISION_FIELDS order
        self._ticks = 0
        self.last_dirty_count = 0
        self.refreshes = 0

    @property
    def aggregates(self):
        return self._aggs

    def apply_gathered(self, gathered, groups=None) -> ClusterArrays:
        """Scatter a ``cache.gather_deltas`` batch into the resident arrays
        while maintaining the aggregates + dirty mask. Replaces the plain
        ``cache.apply_gathered`` in an incremental tick."""
        cluster, self._aggs = self._cache.apply_gathered_with_aggregates(
            gathered, groups, self._aggs)
        return cluster

    def _set_prev(self, out) -> None:
        self._prev_cols = tuple(
            getattr(out, f) for f in _kernel.GROUP_DECISION_FIELDS)

    def decide(self, now_sec, tainted_any: bool):
        """One lazy-orders tick (``kernel.lazy_orders_decide``) over the
        incremental dispatch pair. Returns ``(DecisionArrays, ordered)``
        with the protocol's exact semantics: when ``ordered`` is False the
        order fields are input-order placeholders and no window may be
        read."""
        self._ticks += 1
        if self._refresh_every and self._ticks % self._refresh_every == 0:
            self.refresh()
        now = np.int64(now_sec)

        from escalator_tpu import observability as obs

        def dispatch(with_orders):
            if with_orders or self._prev_cols is None:
                # full decide, fed the persistent aggregates: the O(P)/O(N)
                # sweeps are skipped; every [G] row recomputes (cheap), so
                # the persistent columns refresh wholesale
                with obs.span(
                        "decide_ordered" if with_orders else "decide_full",
                        kind="device"):
                    # fence blocks (and propagates device failures) — one
                    # synchronization, not a redundant block_until_ready pair
                    out = obs.fence(_kernel.decide_jit(
                        self._cache.cluster, now, impl=self._impl,
                        aggregates=_kernel.aggregates_tuple(self._aggs),
                        with_orders=with_orders,
                    ))
                self._set_prev(out)
                return out
            dirty = np.asarray(self._aggs.dirty)
            self.last_dirty_count = int(dirty.sum())
            obs.annotate(dirty_groups=self.last_dirty_count)
            with obs.span("delta_decide", kind="device"):
                idx = _kernel.dirty_indices(dirty)
                out, self._aggs = _kernel.delta_decide_jit(
                    self._cache.cluster, self._aggs, self._prev_cols, idx, now)
                out = obs.fence(out)
            self._set_prev(out)
            return out

        return _kernel.lazy_orders_decide(dispatch, tainted_any)

    def refresh(self) -> bool:
        """Re-derive the aggregates from the resident cluster and assert
        bit-equality against the incrementally maintained state (the
        self-audit). Returns True when the audit passed.

        A mismatch — in BOTH modes — increments
        ``escalator_tpu_incremental_audit_mismatch_total`` (the alertable
        counter the silent backend-mode "repair+log" lacked) and dumps the
        flight recorder, so the ticks whose deltas diverged are captured at
        the moment of detection, not reconstructed from memory."""
        from escalator_tpu import observability as obs

        self.refreshes += 1
        with obs.span("refresh_audit", kind="device"):
            fresh = obs.fence(
                _kernel.compute_aggregates_jit(self._cache.cluster,
                                               impl=self._impl))
            mismatched = [
                f.name for f in fields(_kernel.GroupAggregates)
                if f.name != "dirty"
                and not np.array_equal(np.asarray(getattr(self._aggs, f.name)),
                                       np.asarray(getattr(fresh, f.name)))
            ]
        if not mismatched:
            obs.annotate(refresh_audit="ok")
            return True
        from escalator_tpu.metrics import metrics

        metrics.incremental_audit_mismatch.inc()
        dump_path = obs.dump_on_incident("audit-mismatch")
        msg = (
            "incremental aggregate refresh mismatch on columns "
            f"{mismatched} after {self._ticks} ticks — the maintained "
            "state diverged from a from-scratch recompute"
            f" (flight record: {dump_path or 'dump failed'})"
        )
        if self._on_mismatch == "raise":
            obs.annotate(refresh_audit="mismatch-raised")
            raise AggregateParityError(msg)
        obs.annotate(refresh_audit="mismatch-repaired")
        logging.getLogger("escalator_tpu.device_state").error(
            "%s; repairing: adopting the recompute and marking every group "
            "dirty", msg)
        G = int(np.asarray(fresh.dirty).shape[0])
        import jax.numpy as jnp

        self._aggs = replace(fresh, dirty=jnp.ones(G, bool))
        return False
