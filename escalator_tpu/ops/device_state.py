"""Device-resident cluster state: the O(changes) host->device data path.

SURVEY.md §7 names the host<->device path as a hard part: at 100k pods, re-uploading
the packed arrays every tick costs tens of ms — more than the decision kernel itself.
The reference has no analog (its Go loops rebuild aggregate state from the watch cache
each tick, pkg/controller/controller.go:192-272); the TPU-native design instead keeps
the ``ClusterArrays`` resident in device HBM and applies each tick's watch deltas as a
scatter update:

- the native C++ store (``native/statestore.cpp``) marks dirty slots as watch events
  are ingested and drains a deduplicated slot list per tick;
- the host gathers just those lanes from the zero-copy column views (numpy fancy
  indexing, O(changes));
- one jitted scatter (``jnp.ndarray.at[idx].set``) with **donated** operands updates
  the resident arrays in place — XLA aliases input and output buffers, so HBM traffic
  per tick is O(changes), not O(cluster).

Delta batches are padded to power-of-two buckets so jit compiles a handful of shapes
total (no recompilation storm as churn fluctuates). Padding lanes target a dedicated
scratch lane (index ``P``/``N`` — the resident arrays carry one extra, never-valid
lane) and all write the same constants, keeping duplicate-index scatter deterministic.

Group config/state ([G]-sized, mutated by the controller every tick: locks, cached
capacity, requested nodes) rides along in the same jit call — it is tiny, so it is
simply re-uploaded rather than diffed.
"""

from __future__ import annotations

from dataclasses import fields
from functools import partial
from typing import Optional

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp
from jax import tree_util

from escalator_tpu.core.arrays import (
    NO_TAINT_TIME,
    ClusterArrays,
    GroupArrays,
    NodeArrays,
    PodArrays,
)
from escalator_tpu.ops import kernel as _kernel  # noqa: F401  (ClusterArrays pytree)


def _register(cls):
    tree_util.register_pytree_node(
        cls,
        lambda obj: ([getattr(obj, f.name) for f in fields(cls)], None),
        lambda aux, leaves: cls(*leaves),
    )


_register(PodArrays)
_register(NodeArrays)
_register(GroupArrays)

_MIN_BUCKET = 64


def _bucket(n: int) -> int:
    """Smallest power-of-two >= n (min 64): bounds the set of compiled shapes."""
    return max(_MIN_BUCKET, 1 << (max(n, 1) - 1).bit_length())


_POD_PAD = {"node": -1}
_NODE_PAD = {"taint_time_sec": NO_TAINT_TIME}


def _pad_one_lane(soa, pad_defaults):
    """Copy of a Pod/NodeArrays with one extra scratch lane (valid=False)."""
    out = {}
    for f in fields(soa):
        arr = getattr(soa, f.name)
        fill = pad_defaults.get(f.name, 0)
        out[f.name] = np.concatenate([arr, np.full(1, fill, arr.dtype)])
    return type(soa)(**out)


def _gather_padded(soa, slots: np.ndarray, bucket: int, scratch: int, pad_defaults):
    """(idx[int32 bucket], values SoA of [bucket]) for a dirty-slot batch.

    Pad lanes point at the scratch lane and write that lane's invariant values
    (valid=False etc.), so duplicate-index scatter stays deterministic.
    """
    k = len(slots)
    idx = np.full(bucket, scratch, np.int32)
    idx[:k] = slots
    vals = {}
    for f in fields(soa):
        arr = getattr(soa, f.name)
        fill = pad_defaults.get(f.name, 0)
        v = np.full(bucket, fill, arr.dtype)
        if k:
            v[:k] = arr[slots]
        vals[f.name] = v
    return idx, type(soa)(**vals)


def _scatter_body(pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals):
    def upd(soa, idx, vals):
        return type(soa)(
            **{
                f.name: getattr(soa, f.name).at[idx].set(getattr(vals, f.name))
                for f in fields(soa)
            }
        )

    return ClusterArrays(
        groups=groups,
        pods=upd(pods, pod_idx, pod_vals),
        nodes=upd(nodes, node_idx, node_vals),
    )


# Pods/nodes are donated (in-place on device); groups is NOT — it may be either a
# fresh host upload or the pass-through resident value, and donating a buffer that
# is also returned untouched would invalidate the caller's reference.
_scatter_update = partial(jax.jit, donate_argnums=(0, 1))(_scatter_body)


def _pack_delta_bytes(idx: np.ndarray, vals) -> np.ndarray:
    """Serialize (idx, SoA values) into ONE uint8 buffer, column-major:
    [idx int32 bytes][field0 bytes][field1 bytes]... Sixteen per-column host
    transfers become two (pods + nodes) — on transports where each transfer
    pays fixed latency, that is most of the scatter phase. The device side
    (:func:`_unpack_delta`) mirrors this layout exactly (both iterate
    ``fields()`` in order), and integer/bool bitcasts are exact."""
    parts = [np.ascontiguousarray(idx, np.int32).view(np.uint8)]
    for f in fields(vals):
        parts.append(np.ascontiguousarray(getattr(vals, f.name)).view(np.uint8))
    return np.concatenate(parts)


def _unpack_delta(buf, field_dtypes):
    """(idx, {field: array}) from a :func:`_pack_delta_bytes` buffer, inside
    jit. ``field_dtypes`` is static; the bucket size is inferred from the
    buffer length."""
    lane_bytes = 4 + sum(np.dtype(dt).itemsize for _, dt in field_dtypes)
    B = buf.shape[0] // lane_bytes

    def take(off, dt):
        k = np.dtype(dt).itemsize
        chunk = jax.lax.dynamic_slice_in_dim(buf, off * B, k * B)
        if k == 1:
            return chunk.astype(dt), off + k
        return (
            jax.lax.bitcast_convert_type(chunk.reshape(B, k), dt),
            off + k,
        )

    idx, off = take(0, np.int32)
    vals = {}
    for name, dt in field_dtypes:
        vals[name], off = take(off, dt)
    return idx, vals


def _field_dtypes(soa):
    return tuple((f.name, np.dtype(getattr(soa, f.name).dtype).type)
                 for f in fields(soa))


@partial(jax.jit, donate_argnums=(0, 1), static_argnames=("pod_dts", "node_dts"))
def _scatter_update_from_packed(pods, nodes, groups, pod_buf, node_buf,
                                pod_dts, node_dts):
    pod_idx, pod_vals = _unpack_delta(pod_buf, pod_dts)
    node_idx, node_vals = _unpack_delta(node_buf, node_dts)
    return _scatter_body(
        pods, nodes, groups,
        pod_idx, type(pods)(**pod_vals), node_idx, type(nodes)(**node_vals),
    )


@partial(jax.jit, donate_argnums=(0, 1),
         static_argnames=("impl", "with_orders"))
def _scatter_update_decide(
    pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals, now_sec,
    impl="xla", with_orders=True,
):
    """Fused tick: scatter this tick's deltas AND run the decision kernel in ONE
    device program. Measured on the v5e tunnel this is NOT faster than the
    two-call path (back-to-back async dispatches already pipeline, and the
    donation handoff adds overhead), so the native backend keeps the two-step
    default; this stays as the single-dispatch option for transports where each
    dispatch costs a full round-trip."""
    cluster = _scatter_body(
        pods, nodes, groups, pod_idx, pod_vals, node_idx, node_vals
    )
    return cluster, _kernel.decide(cluster, now_sec, impl=impl,
                                   with_orders=with_orders)


class DeviceClusterCache:
    """Keeps the packed cluster resident on one device across ticks.

    Construct from host-side arrays (typically the native store's zero-copy views),
    then per tick call :meth:`apply_dirty` with the store's drained dirty-slot lists.
    ``cluster`` is the jit-ready device value for ``ops.kernel.decide``.
    """

    def __init__(self, host: ClusterArrays, device=None):
        if device is None:
            # wedged-transport guard: raw library construction (no
            # CLI/backend upstream) reaches backend init right here, and a
            # wedged tunnel hangs it forever; cached per process
            from escalator_tpu.jaxconfig import guarded_devices

            device = guarded_devices()[0]
        self._device = device
        self._host_pods = host.pods
        self._host_nodes = host.nodes
        self.pod_capacity = int(host.pods.valid.shape[0])
        self.node_capacity = int(host.nodes.valid.shape[0])
        self._cluster = jax.device_put(
            ClusterArrays(
                groups=host.groups,
                pods=_pad_one_lane(host.pods, _POD_PAD),
                nodes=_pad_one_lane(host.nodes, _NODE_PAD),
            ),
            self._device,
        )

    @property
    def cluster(self) -> ClusterArrays:
        return self._cluster

    @property
    def device(self):
        """The device the cluster is resident on (impl selection keys off its
        platform — see ops.kernel.native_tick_impl)."""
        return self._device

    def set_host(self, pods: PodArrays, nodes: NodeArrays) -> None:
        """Rebind the host-side views gathers read from. Needed when the store
        re-views its buffers (growth) or a per-tick corrected view (dry mode)
        replaces the raw columns. Shapes must match the resident capacity."""
        if (
            int(pods.valid.shape[0]) != self.pod_capacity
            or int(nodes.valid.shape[0]) != self.node_capacity
        ):
            raise ValueError(
                "host view shape changed; use refresh_full() after store growth"
            )
        self._host_pods = pods
        self._host_nodes = nodes

    def _gather_deltas(self, pod_slots: np.ndarray, node_slots: np.ndarray):
        """(pod_idx, pod_vals, node_idx, node_vals) for a dirty-slot batch —
        the shared O(changes) host gather both tick paths use."""
        pidx, pvals = _gather_padded(
            self._host_pods,
            np.asarray(pod_slots, np.int64),
            _bucket(len(pod_slots)),
            self.pod_capacity,
            _POD_PAD,
        )
        nidx, nvals = _gather_padded(
            self._host_nodes,
            np.asarray(node_slots, np.int64),
            _bucket(len(node_slots)),
            self.node_capacity,
            _NODE_PAD,
        )
        return pidx, pvals, nidx, nvals

    def gather_deltas(self, pod_slots: np.ndarray, node_slots: np.ndarray):
        """The host-side half of :meth:`apply_dirty`: copy the dirty lanes out
        of the (live, possibly shared) host views into padded numpy buffers.
        Callers that share the views with a writer thread run THIS under the
        store lock and :meth:`apply_gathered` outside it — the gather is the
        only part that reads shared memory; the device dispatch (and any jit
        compile it triggers) must not stall ingestion."""
        return self._gather_deltas(pod_slots, node_slots)

    def apply_gathered(
        self, gathered, groups: Optional[GroupArrays] = None
    ) -> ClusterArrays:
        """Device half of :meth:`apply_dirty`: scatter a `gather_deltas` batch
        (already-copied buffers — safe to run unlocked) into the resident arrays."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = gathered
        self._cluster = _scatter_update(
            self._cluster.pods, self._cluster.nodes, groups, pidx, pvals, nidx, nvals
        )
        return self._cluster

    def apply_dirty(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        groups: Optional[GroupArrays] = None,
    ) -> ClusterArrays:
        """Scatter this tick's dirty lanes (plus fresh group state) into the
        resident arrays. O(changes) host work + transfer; returns the updated
        device cluster."""
        return self.apply_gathered(self.gather_deltas(pod_slots, node_slots), groups)

    def apply_dirty_packed(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        groups: Optional[GroupArrays] = None,
    ) -> ClusterArrays:
        """:meth:`apply_dirty` with the delta batch crossing host->device as
        TWO packed byte buffers instead of sixteen per-column arrays (see
        ``_pack_delta_bytes``). Bit-identical resident state (integer/bool
        bitcasts are exact — test-locked); which variant is faster is a
        transport property, so the bench times both per capture and the
        default stays the per-column path until a device capture says
        otherwise."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = self._gather_deltas(pod_slots, node_slots)
        self._cluster = _scatter_update_from_packed(
            self._cluster.pods, self._cluster.nodes, groups,
            _pack_delta_bytes(pidx, pvals), _pack_delta_bytes(nidx, nvals),
            _field_dtypes(self._host_pods), _field_dtypes(self._host_nodes),
        )
        return self._cluster

    def apply_dirty_and_decide(
        self,
        pod_slots: np.ndarray,
        node_slots: np.ndarray,
        now_sec,
        groups: Optional[GroupArrays] = None,
        impl: str = "xla",
        with_orders: bool = True,
    ):
        """Fused per-tick path: scatter the dirty lanes and run the decision
        kernel in one device dispatch. Returns the DecisionArrays; the updated
        cluster stays resident (``self.cluster``). ``with_orders=False`` is
        the lazy-orders light program (kernel.decide docstring) so the fused
        variant prices the same steady-state tick as the two-call path."""
        if groups is None:
            groups = self._cluster.groups
        pidx, pvals, nidx, nvals = self._gather_deltas(pod_slots, node_slots)
        self._cluster, out = _scatter_update_decide(
            self._cluster.pods, self._cluster.nodes, groups,
            pidx, pvals, nidx, nvals, jnp.int64(now_sec), impl=impl,
            with_orders=with_orders,
        )
        return out

    def refresh_full(self, host: ClusterArrays) -> ClusterArrays:
        """Full re-upload after a capacity change (store growth re-views buffers;
        resident shapes must follow). Rare by design — capacities double."""
        self.__init__(host, self._device)
        return self._cluster
