"""What-if simulation: sweep candidate scale deltas for every nodegroup at once.

The reference can only compute THE delta its formula prescribes
(/root/reference/pkg/controller/util.go:13-46). The dense formulation buys more
(SURVEY.md §7 step 6): evaluate *all* candidate deltas — and candidate instance
types — in one batched sweep, answering "what would utilisation be if group g added
d nodes of type t?" for the whole fleet in one device program. Capacity planners and
the simulation CLI use this for fleet-scale dry-runs the reference cannot do.

Shapes: ``[G]`` groups x ``[D]`` candidate deltas (x ``[T]`` instance types for the
typed variant). All dense, jit-once, MXU/VPU-friendly broadcasting.
"""

from __future__ import annotations

from dataclasses import dataclass

from escalator_tpu.jaxconfig import ensure_x64

ensure_x64()

import jax
import jax.numpy as jnp

from escalator_tpu.core.arrays import ClusterArrays
from escalator_tpu.ops.kernel import _segsum

_F64 = jnp.float64
_I64 = jnp.int64


@dataclass
class DeltaSweep:
    """[G, D] post-delta utilisation and feasibility, plus the minimal feasible
    delta per group (D = infeasible-at-any-candidate sentinel)."""

    post_cpu_percent: jnp.ndarray   # float64 [G, D]
    post_mem_percent: jnp.ndarray   # float64 [G, D]
    feasible: jnp.ndarray           # bool [G, D] both percents <= threshold
    min_feasible_delta: jnp.ndarray  # int32 [G]

    def tree_flatten(self):
        return (
            [self.post_cpu_percent, self.post_mem_percent, self.feasible,
             self.min_feasible_delta],
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, leaves):
        return cls(*leaves)


jax.tree_util.register_pytree_node(
    DeltaSweep, DeltaSweep.tree_flatten, DeltaSweep.tree_unflatten
)


def _group_aggregates(cluster: ClusterArrays):
    g, p, n = cluster.groups, cluster.pods, cluster.nodes
    G = g.valid.shape[0]
    pw = p.valid.astype(_I64)
    pgroup = jnp.where(p.valid, p.group, 0)
    cpu_req = _segsum(p.cpu_milli * pw, pgroup, G)
    mem_req = _segsum(p.mem_bytes * pw, pgroup, G)
    untainted = n.valid & ~n.tainted & ~n.cordoned
    uw = untainted.astype(_I64)
    ngroup = jnp.where(n.valid, n.group, 0)
    cpu_cap = _segsum(n.cpu_milli * uw, ngroup, G)
    mem_cap = _segsum(n.mem_bytes * uw, ngroup, G)
    return cpu_req, mem_req, cpu_cap, mem_cap


def sweep_deltas(cluster: ClusterArrays, num_candidates: int) -> DeltaSweep:
    """Candidate deltas d in [0, num_candidates): each adds d nodes of the group's
    cached per-node capacity to its untainted capacity."""
    g = cluster.groups
    cpu_req, mem_req, cpu_cap, mem_cap = _group_aggregates(cluster)
    d = jnp.arange(num_candidates, dtype=_I64)[None, :]           # [1, D]
    add_cpu = g.cached_cpu_milli[:, None] * d                     # [G, D]
    add_mem = g.cached_mem_bytes[:, None] * d
    total_cpu = (cpu_cap[:, None] + add_cpu).astype(_F64)
    total_mem = (mem_cap[:, None] + add_mem).astype(_F64)
    safe_cpu = jnp.where(total_cpu == 0, 1.0, total_cpu)
    safe_mem = jnp.where(total_mem == 0, 1.0, total_mem)
    post_cpu = jnp.where(
        total_cpu == 0, jnp.inf, cpu_req[:, None].astype(_F64) / safe_cpu * 100.0
    )
    post_mem = jnp.where(
        total_mem == 0, jnp.inf, mem_req[:, None].astype(_F64) / safe_mem * 100.0
    )
    thr = g.scale_up_thr.astype(_F64)[:, None]
    feasible = (post_cpu <= thr) & (post_mem <= thr) & g.valid[:, None]
    # first feasible candidate; num_candidates when none
    min_delta = jnp.where(
        feasible.any(axis=1),
        jnp.argmax(feasible, axis=1),
        num_candidates,
    ).astype(jnp.int32)
    return DeltaSweep(post_cpu, post_mem, feasible, min_delta)


def sweep_deltas_by_type(
    cluster: ClusterArrays,
    type_cpu_milli: jnp.ndarray,   # int64 [T] per-node cpu of each instance type
    type_mem_bytes: jnp.ndarray,   # int64 [T]
    num_candidates: int,
):
    """[G, T, D] what-if: post-delta percents if group g added d nodes of type t.
    Returns (post_cpu, post_mem, feasible, min_delta[G, T])."""
    g = cluster.groups
    cpu_req, mem_req, cpu_cap, mem_cap = _group_aggregates(cluster)
    d = jnp.arange(num_candidates, dtype=_I64)[None, None, :]       # [1,1,D]
    add_cpu = type_cpu_milli[None, :, None] * d                     # [1,T,D]
    add_mem = type_mem_bytes[None, :, None] * d
    total_cpu = (cpu_cap[:, None, None] + add_cpu).astype(_F64)     # [G,T,D]
    total_mem = (mem_cap[:, None, None] + add_mem).astype(_F64)
    safe_cpu = jnp.where(total_cpu == 0, 1.0, total_cpu)
    safe_mem = jnp.where(total_mem == 0, 1.0, total_mem)
    post_cpu = jnp.where(
        total_cpu == 0, jnp.inf,
        cpu_req[:, None, None].astype(_F64) / safe_cpu * 100.0,
    )
    post_mem = jnp.where(
        total_mem == 0, jnp.inf,
        mem_req[:, None, None].astype(_F64) / safe_mem * 100.0,
    )
    thr = g.scale_up_thr.astype(_F64)[:, None, None]
    feasible = (post_cpu <= thr) & (post_mem <= thr) & g.valid[:, None, None]
    min_delta = jnp.where(
        feasible.any(axis=2), jnp.argmax(feasible, axis=2), num_candidates
    ).astype(jnp.int32)
    return post_cpu, post_mem, feasible, min_delta


_sweep_deltas_raw = jax.jit(sweep_deltas, static_argnames=("num_candidates",))
_sweep_deltas_by_type_raw = jax.jit(
    sweep_deltas_by_type, static_argnames=("num_candidates",)
)


def sweep_deltas_jit(cluster, num_candidates: int):
    """Jitted :func:`sweep_deltas` with the wedged-transport guard at first
    dispatch (same rationale as ``kernel.decide_jit``: raw library use never
    crosses the CLI/backend construction guards, and a wedged accelerator
    would hang the first dispatch forever; the probe is cached per process)."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _sweep_deltas_raw(cluster, num_candidates=num_candidates)


def sweep_deltas_by_type_jit(cluster, type_cpu_milli, type_mem_bytes,
                             num_candidates: int):
    """Jitted :func:`sweep_deltas_by_type`; guarded like sweep_deltas_jit."""
    from escalator_tpu.jaxconfig import ensure_responsive_accelerator

    ensure_responsive_accelerator()
    return _sweep_deltas_by_type_raw(
        cluster, type_cpu_milli, type_mem_bytes,
        num_candidates=num_candidates)
