"""The combined node-ordering sort, single-device and group-block-sharded.

Every consumer of a node ordering in this codebase goes through ONE 4-key
``lax.sort`` (round 5, ops/kernel.py decide): each lane carries a selection-
class major key — tainted first, untainted second, everything else last — so
the tainted block sorts (group asc, creation desc) at the front (untaint
order, reference pkg/controller/sort.go:27-39) and the untainted block sorts
(group asc, victim-primary, creation asc) right after it (scale-down order,
sort.go:12-24). :func:`combined_order_sort` is that sort, extracted here so
the single-device kernel, the grid's per-block tail, and the pod-axis
sharded tail all run literally the same key construction.

The second half of this module is the **group-block-sharded ordering tail**
(round 6): ``parallel.podaxis`` replicates its node arrays, so its ordered
(busy/drain-tick) decide used to pay the full [N] sort once per device —
bench cfg8 measured that replicated tail at 218 of 241 ms on the 8-virtual-
device rig (0.23x vs single device; VERDICT r5 weak-point 2). The grid
backend already had the fix — nodes shard by group block, each device sorts
only its block — but its layout is baked into the 2-D packer. Here the same
idea is expressed as a standalone tail any replicated-node decider can call:

- :func:`assign_order_blocks` (host, O(N)) partitions the node lanes into S
  CONTIGUOUS-GROUP blocks balanced by lane count and returns a ``[S, Nb]``
  gather map (``-1`` padding);
- :func:`make_sharded_order_tail` builds the jitted device tail: one
  ``shard_map`` in which each device gathers its block's lanes, runs the
  combined sort on ``[Nb]`` lanes (skipped entirely via ``lax.cond`` when
  the block has no tainted/untainted lane — the all-padding blocks of a
  single-giant-group cluster), then a cheap replicated O(N) reassembly
  scatters the per-block class segments back into the global permutation.

Why the reassembly is exact where it matters: the global sort's major key is
``class * G + group`` and blocks are ascending contiguous group ranges, so
the global class-c segment is the concatenation, block by block, of each
block's class-c segment — same keys, same global-lane-index tie-break, so
the scale-down and untaint WINDOWS (the only contractually ordered regions,
see kernel.decide) are bit-identical to the single-device sort. The region
beyond the windows (class-2 lanes: invalid/cordoned) is unspecified contract
either way and may differ when a selection-free block skips its sort.

Cost model per busy tick, S devices, balanced groups: the replicated
``sort(N)`` term becomes ``sort(N/S)`` per device (the grid's win, now on
the pod-axis path); one giant group degenerates to ONE device paying
``sort(N)`` while the rest skip — on real chips that is the single-device
tail (not S of them burning energy), and on this repo's 1-core bench rig it
is the difference between 8x serialized sorts and 1 (bench cfg8 busy rows).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64, shard_map

ensure_x64()

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_I32 = jnp.int32
_I64 = jnp.int64


def node_selection_masks(valid, group, tainted, cordoned):
    """The ONE definition of how node lanes classify for ordering/selection:
    ``(key_group, untainted_sel, tainted_sel)`` with invalid lanes keyed to
    group 0. kernel.decide and the pod-axis sharded tail both build their
    sort keys from this, so the selection semantics cannot drift between
    the replicated and block-sharded ordering programs."""
    key_group = jnp.where(valid, group, 0)
    untainted_sel = valid & ~tainted & ~cordoned
    tainted_sel = valid & tainted & ~cordoned
    return key_group, untainted_sel, tainted_sel


def combined_order_sort(
    group: jnp.ndarray,          # int [L] group id per lane (invalid lanes -> 0)
    tainted_sel: jnp.ndarray,    # bool [L]
    untainted_sel: jnp.ndarray,  # bool [L]
    victim_primary: jnp.ndarray,  # int64 [L] pods-remaining for emptiest_first, else 0
    creation_ns: jnp.ndarray,    # int64 [L]
    num_groups: int,
    lane_key: jnp.ndarray,       # int64 [L] unique tie-break / payload (global index)
    pad_mask: Optional[jnp.ndarray] = None,  # bool [L] lanes beyond the real set
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """ONE 4-key ``lax.sort`` producing the combined ordering (see module
    docstring). Returns ``(sorted_major, sorted_lane_key)``: the lane keys in
    combined order, plus each lane's major key (``class * G + group``) from
    which the selection class is recoverable as ``major // G``. ``pad_mask``
    lanes get class 3 and sink below every real lane (the sharded tail's
    block padding)."""
    lane_class = jnp.where(
        tainted_sel, jnp.int64(0),
        jnp.where(untainted_sel, jnp.int64(1), jnp.int64(2)),
    )
    if pad_mask is not None:
        lane_class = jnp.where(pad_mask, jnp.int64(3), lane_class)
    major = lane_class * jnp.int64(num_groups) + group.astype(_I64)
    k1 = jnp.where(tainted_sel, -creation_ns, victim_primary)
    k2 = jnp.where(tainted_sel, jnp.int64(0), creation_ns)
    out = jax.lax.sort((major, k1, k2, lane_key), num_keys=4, is_stable=False)
    return out[0], out[-1]


# ---------------------------------------------------------------------------
# Host-side block partition
# ---------------------------------------------------------------------------


def assign_order_blocks(
    node_group: np.ndarray,
    node_valid: np.ndarray,
    num_blocks: int,
    num_groups: Optional[int] = None,
) -> np.ndarray:
    """Partition the node lanes into ``num_blocks`` contiguous-group blocks
    balanced by lane count (host-side, O(N + G) numpy — the pod-axis analog
    of what ``mesh.pack_cluster_sharded`` does at pack time for the grid).

    Groups are assigned to blocks by cumulative lane count, so every group's
    lanes land in exactly ONE block and block group-ranges ascend — the
    property the sharded tail's exact reassembly relies on. Invalid lanes
    carry key group 0 (exactly as ``kernel.decide``'s ``ngroup`` does) and
    ride with group 0's block. Returns an int32 ``[num_blocks, Nb]`` global-
    lane-index map, ``-1`` padded; one giant group yields one full block and
    ``num_blocks - 1`` all-padding blocks (whose devices skip their sort).
    """
    node_group = np.asarray(node_group)
    node_valid = np.asarray(node_valid)
    N = int(node_group.shape[0])
    if num_groups is None:
        num_groups = int(node_group.max()) + 1 if N else 1
    key_group = np.where(node_valid, node_group, 0).astype(np.int64)
    counts = np.bincount(key_group, minlength=num_groups)
    # contiguous ranges: group g's block = scaled position of its first lane
    # in the cumulative count (floor keeps blocks ascending and contiguous)
    before = np.cumsum(counts) - counts
    block_of_group = np.minimum(
        before * num_blocks // max(N, 1), num_blocks - 1
    ).astype(np.int64)
    lane_block = block_of_group[key_group]
    order = np.argsort(lane_block, kind="stable")
    per_block = np.bincount(lane_block, minlength=num_blocks)
    Nb = max(int(per_block.max()) if N else 0, 1)
    blocks = np.full((num_blocks, Nb), -1, np.int32)
    start = 0
    for b in range(num_blocks):
        n_b = int(per_block[b])
        blocks[b, :n_b] = order[start:start + n_b]
        start += n_b
    return blocks


def pad_order_blocks(blocks: np.ndarray, width: int) -> np.ndarray:
    """Pad the block map's lane axis to ``width`` (-1 lanes): callers keep a
    high-water-mark width so the jitted tail's shape set stays small as the
    cluster's block balance shifts tick to tick."""
    Nb = blocks.shape[1]
    if width <= Nb:
        return blocks
    return np.pad(blocks, ((0, 0), (0, width - Nb)), constant_values=-1)


# ---------------------------------------------------------------------------
# Device-side sharded tail
# ---------------------------------------------------------------------------


def _leading_spec(mesh: Mesh) -> P:
    names = tuple(mesh.axis_names)
    return P(names if len(names) > 1 else names[0])


def make_sharded_order_tail(mesh: Mesh):
    """Build the group-block-sharded ordering tail for ``mesh`` (1-D or
    hybrid; the block axis spans ALL mesh axes, so S = total devices).

    Returns ``tail(group, tainted_sel, untainted_sel, victim_primary,
    creation_ns, num_groups, block_index) -> (untaint_order, scale_down_order)``
    — trace-safe (call under jit). Inputs are the replicated per-node arrays
    exactly as ``kernel.decide`` computes them; ``block_index`` is the
    ``[S, Nb]`` host map from :func:`assign_order_blocks`. Outputs are the
    replicated ``[N]`` int32 permutations with the same window contract as
    ``kernel.decide``'s (see module docstring for the exactness argument).
    """
    spec = _leading_spec(mesh)
    axis_names = tuple(mesh.axis_names)
    num_blocks = int(mesh.devices.size)

    def tail(group, tainted_sel, untainted_sel, victim_primary, creation_ns,
             num_groups: int, block_index):
        N = int(group.shape[0])
        G = int(num_groups)
        S, Nb = block_index.shape
        if S != num_blocks:
            raise ValueError(
                f"block_index has {S} blocks for a {num_blocks}-device mesh"
            )
        axis_sizes = [int(mesh.shape[ax]) for ax in axis_names]

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), spec),
            out_specs=P(),
            # replicated inputs + a device-varying gather map: the vma
            # checker cannot see through the in-body psums that restore
            # replication
            check_vma=False,
        )
        def block_perm(g_all, t_all, u_all, vp_all, cr_all, idx):
            idx = idx.reshape(-1)                       # [Nb] local block
            pad = idx < 0
            safe = jnp.clip(idx, 0, N - 1)
            t_l = jnp.where(pad, False, t_all[safe])
            u_l = jnp.where(pad, False, u_all[safe])

            # ---- this block's class counts -> every block's, via one tiny
            # [S, 3] psum (each device contributes its own row). Classes
            # need no ordering, so this runs before (and regardless of) the
            # sort. my_row is the device's position along the block axis.
            my_row = jnp.int64(0)
            for ax, size in zip(axis_names, axis_sizes, strict=True):
                my_row = my_row * size + jax.lax.axis_index(ax)
            cls_l = jnp.where(
                t_l, jnp.int64(0), jnp.where(u_l, jnp.int64(1), jnp.int64(2))
            )
            cls_l = jnp.where(pad, jnp.int64(3), cls_l)
            counts_local = jnp.stack(
                [jnp.sum((cls_l == c).astype(_I64)) for c in range(3)]
            )
            counts_all = jnp.where(
                (jnp.arange(S, dtype=_I64) == my_row)[:, None],
                counts_local[None, :], jnp.int64(0),
            )
            for ax in reversed(axis_names):
                counts_all = jax.lax.psum(counts_all, ax)   # [S, 3]
            class_tot = counts_all.sum(axis=0)
            class_start = jnp.concatenate(
                [jnp.zeros(1, _I64), jnp.cumsum(class_tot)]
            )[:3]
            before_me = jnp.where(
                (jnp.arange(S, dtype=_I64) < my_row)[:, None],
                counts_all, jnp.int64(0),
            ).sum(axis=0)                                   # [3]
            starts = class_start + before_me

            def live_block(_):
                """Gather the block's lanes, order them (sorting only when
                an ordering window can reference them), and scatter them at
                their global positions."""
                g_l = jnp.where(pad, 0, g_all[safe])
                vp_l = jnp.where(pad, jnp.int64(0), vp_all[safe])
                cr_l = jnp.where(pad, jnp.int64(0), cr_all[safe])
                gidx = jnp.where(pad, jnp.int64(-1), idx.astype(_I64))

                def do_sort(_):
                    return combined_order_sort(
                        g_l, t_l, u_l, vp_l, cr_l, G, gidx, pad_mask=pad
                    )

                def skip_sort(_):
                    # no tainted/untainted lane here: nothing this block
                    # holds is inside any ordering window, so its class-2
                    # segment may stay in block order (unspecified region)
                    major = cls_l * jnp.int64(G) + g_l.astype(_I64)
                    return major, gidx

                major_s, gidx_s = jax.lax.cond(
                    jnp.any(t_l | u_l), do_sort, skip_sort, None
                )
                cls = jnp.clip(major_s // jnp.int64(max(G, 1)), 0, 3)
                # rank within this block's class-c sequence; global position
                # = block segment start + rank; pads scatter off-array
                rank = jnp.select(
                    [cls == c for c in range(3)],
                    [jnp.cumsum((cls == c).astype(_I64)) - 1
                     for c in range(3)],
                    jnp.int64(0),
                )
                pos = jnp.where(
                    cls >= 3, jnp.int64(N),
                    jnp.take(starts, jnp.clip(cls, 0, 2), mode="clip") + rank,
                )
                return jnp.zeros(N, _I32).at[pos].set(
                    gidx_s.astype(_I32), mode="drop"
                )

            # an all-padding block (a giant-group layout leaves S-1 of them)
            # contributes nothing: skip its gathers/ranks/scatter entirely.
            # Collectives stay OUTSIDE both conds — every device runs them.
            part = jax.lax.cond(
                jnp.any(~pad), live_block, lambda _: jnp.zeros(N, _I32), None
            )
            # blocks write disjoint position sets covering 0..N-1, so ONE
            # psum assembles the full permutation (and replicates it)
            for ax in reversed(axis_names):
                part = jax.lax.psum(part, ax)
            return part

        perm = block_perm(
            group, tainted_sel, untainted_sel,
            victim_primary, creation_ns, block_index,
        )
        # tainted block first in the combined permutation (= untaint order);
        # rolling it to the tail yields scale-down order, as in kernel.decide
        total_tainted = jnp.sum(tainted_sel.astype(_I64))
        scale_down = jnp.roll(perm, -total_tainted)
        return perm, scale_down

    return tail


__all__: Sequence[str] = (
    "combined_order_sort",
    "assign_order_blocks",
    "pad_order_blocks",
    "make_sharded_order_tail",
)
