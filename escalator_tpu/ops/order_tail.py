"""The combined node-ordering sort, single-device and group-block-sharded.

Every consumer of a node ordering in this codebase goes through ONE 4-key
``lax.sort`` (round 5, ops/kernel.py decide): each lane carries a selection-
class major key — tainted first, untainted second, everything else last — so
the tainted block sorts (group asc, creation desc) at the front (untaint
order, reference pkg/controller/sort.go:27-39) and the untainted block sorts
(group asc, victim-primary, creation asc) right after it (scale-down order,
sort.go:12-24). :func:`combined_order_sort` is that sort, extracted here so
the single-device kernel, the grid's per-block tail, and the pod-axis
sharded tail all run literally the same key construction.

The second half of this module is the **group-block-sharded ordering tail**
(round 6): ``parallel.podaxis`` replicates its node arrays, so its ordered
(busy/drain-tick) decide used to pay the full [N] sort once per device —
bench cfg8 measured that replicated tail at 218 of 241 ms on the 8-virtual-
device rig (0.23x vs single device; VERDICT r5 weak-point 2). The grid
backend already had the fix — nodes shard by group block, each device sorts
only its block — but its layout is baked into the 2-D packer. Here the same
idea is expressed as a standalone tail any replicated-node decider can call:

- :func:`assign_order_blocks` (host, O(N)) partitions the node lanes into S
  CONTIGUOUS-GROUP blocks balanced by lane count and returns a ``[S, Nb]``
  gather map (``-1`` padding);
- :func:`make_sharded_order_tail` builds the jitted device tail: one
  ``shard_map`` in which each device gathers its block's lanes, runs the
  combined sort on ``[Nb]`` lanes (skipped entirely via ``lax.cond`` when
  the block has no tainted/untainted lane — the all-padding blocks of a
  single-giant-group cluster), then a cheap replicated O(N) reassembly
  scatters the per-block class segments back into the global permutation.

Why the reassembly is exact where it matters: the global sort's major key is
``class * G + group`` and blocks are ascending contiguous group ranges, so
the global class-c segment is the concatenation, block by block, of each
block's class-c segment — same keys, same global-lane-index tie-break, so
the scale-down and untaint WINDOWS (the only contractually ordered regions,
see kernel.decide) are bit-identical to the single-device sort. The region
beyond the windows (class-2 lanes: invalid/cordoned) is unspecified contract
either way and may differ when a selection-free block skips its sort.

Cost model per busy tick, S devices, balanced groups: the replicated
``sort(N)`` term becomes ``sort(N/S)`` per device (the grid's win, now on
the pod-axis path); one giant group degenerates to ONE device paying
``sort(N)`` while the rest skip — on real chips that is the single-device
tail (not S of them burning energy), and on this repo's 1-core bench rig it
is the difference between 8x serialized sorts and 1 (bench cfg8 busy rows).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import numpy as np

from escalator_tpu.jaxconfig import ensure_x64, shard_map

ensure_x64()

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

_I32 = jnp.int32
_I64 = jnp.int64


def node_selection_masks(valid, group, tainted, cordoned):
    """The ONE definition of how node lanes classify for ordering/selection:
    ``(key_group, untainted_sel, tainted_sel)`` with invalid lanes keyed to
    group 0. kernel.decide and the pod-axis sharded tail both build their
    sort keys from this, so the selection semantics cannot drift between
    the replicated and block-sharded ordering programs."""
    key_group = jnp.where(valid, group, 0)
    untainted_sel = valid & ~tainted & ~cordoned
    tainted_sel = valid & tainted & ~cordoned
    return key_group, untainted_sel, tainted_sel


def order_sort_keys(
    group: jnp.ndarray,          # int [L] group id per lane (invalid lanes -> 0)
    tainted_sel: jnp.ndarray,    # bool [L]
    untainted_sel: jnp.ndarray,  # bool [L]
    victim_primary: jnp.ndarray,  # int64 [L] pods-remaining for emptiest_first, else 0
    creation_ns: jnp.ndarray,    # int64 [L]
    num_groups: int,
    pad_mask: Optional[jnp.ndarray] = None,  # bool [L] lanes beyond the real set
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The combined ordering's per-lane sort keys ``(major, k1, k2)`` — THE
    single definition, shared by :func:`combined_order_sort` (the full sort)
    and the incremental order-state path (:func:`order_repair`), so the two
    formulations cannot drift: same keys in, bit-identical permutation out.
    ``major = class * G + group`` (class recoverable as ``major // G``);
    ``pad_mask`` lanes get class 3 and sink below every real lane."""
    lane_class = jnp.where(
        tainted_sel, jnp.int64(0),
        jnp.where(untainted_sel, jnp.int64(1), jnp.int64(2)),
    )
    if pad_mask is not None:
        lane_class = jnp.where(pad_mask, jnp.int64(3), lane_class)
    major = lane_class * jnp.int64(num_groups) + group.astype(_I64)
    k1 = jnp.where(tainted_sel, -creation_ns, victim_primary)
    k2 = jnp.where(tainted_sel, jnp.int64(0), creation_ns)
    return major, k1, k2


def combined_order_sort(
    group: jnp.ndarray,          # int [L] group id per lane (invalid lanes -> 0)
    tainted_sel: jnp.ndarray,    # bool [L]
    untainted_sel: jnp.ndarray,  # bool [L]
    victim_primary: jnp.ndarray,  # int64 [L] pods-remaining for emptiest_first, else 0
    creation_ns: jnp.ndarray,    # int64 [L]
    num_groups: int,
    lane_key: jnp.ndarray,       # int64 [L] unique tie-break / payload (global index)
    pad_mask: Optional[jnp.ndarray] = None,  # bool [L] lanes beyond the real set
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """ONE 4-key ``lax.sort`` producing the combined ordering (see module
    docstring). Returns ``(sorted_major, sorted_lane_key)``: the lane keys in
    combined order, plus each lane's major key (``class * G + group``) from
    which the selection class is recoverable as ``major // G``. ``pad_mask``
    lanes get class 3 and sink below every real lane (the sharded tail's
    block padding)."""
    major, k1, k2 = order_sort_keys(
        group, tainted_sel, untainted_sel, victim_primary, creation_ns,
        num_groups, pad_mask=pad_mask,
    )
    out = jax.lax.sort((major, k1, k2, lane_key), num_keys=4, is_stable=False)
    return out[0], out[-1]


# ---------------------------------------------------------------------------
# Host-side block partition
# ---------------------------------------------------------------------------


def assign_order_blocks(
    node_group: np.ndarray,
    node_valid: np.ndarray,
    num_blocks: int,
    num_groups: Optional[int] = None,
) -> np.ndarray:
    """Partition the node lanes into ``num_blocks`` contiguous-group blocks
    balanced by lane count (host-side, O(N + G) numpy — the pod-axis analog
    of what ``mesh.pack_cluster_sharded`` does at pack time for the grid).

    Groups are assigned to blocks by cumulative lane count, so every group's
    lanes land in exactly ONE block and block group-ranges ascend — the
    property the sharded tail's exact reassembly relies on. Invalid lanes
    carry key group 0 (exactly as ``kernel.decide``'s ``ngroup`` does) and
    ride with group 0's block. Returns an int32 ``[num_blocks, Nb]`` global-
    lane-index map, ``-1`` padded; one giant group yields one full block and
    ``num_blocks - 1`` all-padding blocks (whose devices skip their sort).
    """
    node_group = np.asarray(node_group)
    node_valid = np.asarray(node_valid)
    N = int(node_group.shape[0])
    if num_groups is None:
        num_groups = int(node_group.max()) + 1 if N else 1
    key_group = np.where(node_valid, node_group, 0).astype(np.int64)
    counts = np.bincount(key_group, minlength=num_groups)
    # contiguous ranges: group g's block = scaled position of its first lane
    # in the cumulative count (floor keeps blocks ascending and contiguous)
    before = np.cumsum(counts) - counts
    block_of_group = np.minimum(
        before * num_blocks // max(N, 1), num_blocks - 1
    ).astype(np.int64)
    lane_block = block_of_group[key_group]
    order = np.argsort(lane_block, kind="stable")
    per_block = np.bincount(lane_block, minlength=num_blocks)
    Nb = max(int(per_block.max()) if N else 0, 1)
    blocks = np.full((num_blocks, Nb), -1, np.int32)
    start = 0
    for b in range(num_blocks):
        n_b = int(per_block[b])
        blocks[b, :n_b] = order[start:start + n_b]
        start += n_b
    return blocks


def pad_order_blocks(blocks: np.ndarray, width: int) -> np.ndarray:
    """Pad the block map's lane axis to ``width`` (-1 lanes): callers keep a
    high-water-mark width so the jitted tail's shape set stays small as the
    cluster's block balance shifts tick to tick."""
    Nb = blocks.shape[1]
    if width <= Nb:
        return blocks
    return np.pad(blocks, ((0, 0), (0, width - Nb)), constant_values=-1)


# ---------------------------------------------------------------------------
# Device-side sharded tail
# ---------------------------------------------------------------------------


def _leading_spec(mesh: Mesh) -> P:
    names = tuple(mesh.axis_names)
    return P(names if len(names) > 1 else names[0])


def make_sharded_order_tail(mesh: Mesh):
    """Build the group-block-sharded ordering tail for ``mesh`` (1-D or
    hybrid; the block axis spans ALL mesh axes, so S = total devices).

    Returns ``tail(group, tainted_sel, untainted_sel, victim_primary,
    creation_ns, num_groups, block_index) -> (untaint_order, scale_down_order)``
    — trace-safe (call under jit). Inputs are the replicated per-node arrays
    exactly as ``kernel.decide`` computes them; ``block_index`` is the
    ``[S, Nb]`` host map from :func:`assign_order_blocks`. Outputs are the
    replicated ``[N]`` int32 permutations with the same window contract as
    ``kernel.decide``'s (see module docstring for the exactness argument).
    """
    spec = _leading_spec(mesh)
    axis_names = tuple(mesh.axis_names)
    num_blocks = int(mesh.devices.size)

    def tail(group, tainted_sel, untainted_sel, victim_primary, creation_ns,
             num_groups: int, block_index):
        N = int(group.shape[0])
        G = int(num_groups)
        S, Nb = block_index.shape
        if S != num_blocks:
            raise ValueError(
                f"block_index has {S} blocks for a {num_blocks}-device mesh"
            )
        axis_sizes = [int(mesh.shape[ax]) for ax in axis_names]

        @partial(
            shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), spec),
            out_specs=P(),
            # replicated inputs + a device-varying gather map: the vma
            # checker cannot see through the in-body psums that restore
            # replication
            check_vma=False,
        )
        def block_perm(g_all, t_all, u_all, vp_all, cr_all, idx):
            idx = idx.reshape(-1)                       # [Nb] local block
            pad = idx < 0
            safe = jnp.clip(idx, 0, N - 1)
            t_l = jnp.where(pad, False, t_all[safe])
            u_l = jnp.where(pad, False, u_all[safe])

            # ---- this block's class counts -> every block's, via one tiny
            # [S, 3] psum (each device contributes its own row). Classes
            # need no ordering, so this runs before (and regardless of) the
            # sort. my_row is the device's position along the block axis.
            my_row = jnp.int64(0)
            for ax, size in zip(axis_names, axis_sizes, strict=True):
                my_row = my_row * size + jax.lax.axis_index(ax)
            cls_l = jnp.where(
                t_l, jnp.int64(0), jnp.where(u_l, jnp.int64(1), jnp.int64(2))
            )
            cls_l = jnp.where(pad, jnp.int64(3), cls_l)
            counts_local = jnp.stack(
                [jnp.sum((cls_l == c).astype(_I64)) for c in range(3)]
            )
            counts_all = jnp.where(
                (jnp.arange(S, dtype=_I64) == my_row)[:, None],
                counts_local[None, :], jnp.int64(0),
            )
            for ax in reversed(axis_names):
                counts_all = jax.lax.psum(counts_all, ax)   # [S, 3]
            class_tot = counts_all.sum(axis=0)
            class_start = jnp.concatenate(
                [jnp.zeros(1, _I64), jnp.cumsum(class_tot)]
            )[:3]
            before_me = jnp.where(
                (jnp.arange(S, dtype=_I64) < my_row)[:, None],
                counts_all, jnp.int64(0),
            ).sum(axis=0)                                   # [3]
            starts = class_start + before_me

            def live_block(_):
                """Gather the block's lanes, order them (sorting only when
                an ordering window can reference them), and scatter them at
                their global positions."""
                g_l = jnp.where(pad, 0, g_all[safe])
                vp_l = jnp.where(pad, jnp.int64(0), vp_all[safe])
                cr_l = jnp.where(pad, jnp.int64(0), cr_all[safe])
                gidx = jnp.where(pad, jnp.int64(-1), idx.astype(_I64))

                def do_sort(_):
                    return combined_order_sort(
                        g_l, t_l, u_l, vp_l, cr_l, G, gidx, pad_mask=pad
                    )

                def skip_sort(_):
                    # no tainted/untainted lane here: nothing this block
                    # holds is inside any ordering window, so its class-2
                    # segment may stay in block order (unspecified region)
                    major = cls_l * jnp.int64(G) + g_l.astype(_I64)
                    return major, gidx

                major_s, gidx_s = jax.lax.cond(
                    jnp.any(t_l | u_l), do_sort, skip_sort, None
                )
                cls = jnp.clip(major_s // jnp.int64(max(G, 1)), 0, 3)
                # rank within this block's class-c sequence; global position
                # = block segment start + rank; pads scatter off-array
                rank = jnp.select(
                    [cls == c for c in range(3)],
                    [jnp.cumsum((cls == c).astype(_I64)) - 1
                     for c in range(3)],
                    jnp.int64(0),
                )
                pos = jnp.where(
                    cls >= 3, jnp.int64(N),
                    jnp.take(starts, jnp.clip(cls, 0, 2), mode="clip") + rank,
                )
                return jnp.zeros(N, _I32).at[pos].set(
                    gidx_s.astype(_I32), mode="drop"
                )

            # an all-padding block (a giant-group layout leaves S-1 of them)
            # contributes nothing: skip its gathers/ranks/scatter entirely.
            # Collectives stay OUTSIDE both conds — every device runs them.
            part = jax.lax.cond(
                jnp.any(~pad), live_block, lambda _: jnp.zeros(N, _I32), None
            )
            # blocks write disjoint position sets covering 0..N-1, so ONE
            # psum assembles the full permutation (and replicates it)
            for ax in reversed(axis_names):
                part = jax.lax.psum(part, ax)
            return part

        perm = block_perm(
            group, tainted_sel, untainted_sel,
            victim_primary, creation_ns, block_index,
        )
        # tainted block first in the combined permutation (= untaint order);
        # rolling it to the tail yields scale-down order, as in kernel.decide
        total_tainted = jnp.sum(tainted_sel.astype(_I64))
        scale_down = jnp.roll(perm, -total_tainted)
        return perm, scale_down

    return tail


# ---------------------------------------------------------------------------
# Incremental ordered ticks (round 10): persistent per-lane order state +
# dirty-lane rank-repair merge, so "ordered" stops meaning "full resort".
# ---------------------------------------------------------------------------
#
# The ordered decide's dominant cost is the full [N] 4-key sort (~12 ms per
# 50k lanes on the CPU fallback; cfg6_drain_start_decide_ms 182 vs 72 light).
# But tick-to-tick only the lanes whose KEYS changed can move: a taint flip,
# a node add/remove, a pods-remaining change in an emptiest_first group. The
# incremental path therefore keeps the last ordered tick's keys and
# permutation resident on device and, per ordered tick:
#
# 1. recomputes every lane's keys (O(N) elementwise — the cheap part of the
#    sort) and diffs them against the stored keys -> the dirty-lane set;
# 2. compacts the dirty lanes into a [Db] power-of-two bucket, Db << N
#    (rank-via-binary-search over the dirty cumsum — gathers, no scatter);
# 3. sorts just the dirty lanes by their new keys (Db log Db);
# 4. merges the dirty bucket back against the unchanged remainder of the
#    stored permutation by rank arithmetic: each dirty lane
#    binary-searches perm_old under the OLD keys (Db * log2 N tuple
#    compares) and subtracts the dirty lanes below it; each clean lane's
#    dirty-before count then falls out of the dirty lanes' OWN insertion
#    points (a histogram + cumsum — no per-clean search), and final
#    position = clean index + cross-count — the classic two-way merge,
#    branch-free, fixed-shape, and gather-shaped except for the single
#    [N] scatter that materializes the new permutation.
#
# The whole step — keys, diff, compaction, merge, scale-down roll — is one
# jit program (order_update_jit): the ordered tick dispatches it once and
# reads back ONE scalar (the changed-lane count, for the bucket-overflow /
# dirty-fraction fallback), where the first formulation serialized four
# dispatches around an [N]-bool mask readback and a host-side compaction.
#
# Exactness: the 4-key order is STRICT (the lane index is the last key), so
# the full sort's output is the unique sorted sequence — and a merge of two
# strictly-sorted subsequences under the same comparator reproduces it
# bit-for-bit, over ALL lanes (class-2 region included; the bootstrap sort
# is unconditional, unlike kernel.decide's lax.cond skip, so the invariant
# "perm IS the full sort" holds from the first ordered tick on). When the
# dirty fraction is large the dirty bucket's own sort approaches the full
# sort's cost for nothing — callers fall back to the full key sort above a
# dirty-fraction threshold (ops.device_state.IncrementalDecider owns that
# policy).


def node_order_keys(group_emptiest, node_valid, node_group, node_tainted,
                    node_cordoned, creation_ns, node_pods_remaining):
    """Per-lane combined-order keys from resident cluster columns — exactly
    the inputs ``kernel.decide`` feeds its sort: selection masks from
    :func:`node_selection_masks`, ``victim_primary`` from the emptiest_first
    config, creation time. ``node_pods_remaining`` is the int64 ``[N]``
    aggregate (the incremental path's maintained column). Raw columns, not
    the SoA dataclasses, so this module needs no pytree registrations."""
    ngroup, untainted_sel, tainted_sel = node_selection_masks(
        node_valid, node_group, node_tainted, node_cordoned
    )
    G = group_emptiest.shape[0]
    victim_primary = jnp.where(
        group_emptiest[ngroup], node_pods_remaining, jnp.int64(0)
    )
    return order_sort_keys(
        ngroup, tainted_sel, untainted_sel, victim_primary, creation_ns, G,
    )


order_keys_jit = jax.jit(node_order_keys)


@jax.jit
def order_sort_jit(major, k1, k2):
    """Full 4-key sort from precomputed key columns: the order-state
    bootstrap / fallback. Bit-identical to ``kernel.decide``'s sorted branch
    (same keys, same lane-index tie-break; strict order makes stability
    irrelevant)."""
    N = major.shape[0]
    iota = jax.lax.iota(_I64, N)
    out = jax.lax.sort((major, k1, k2, iota), num_keys=4, is_stable=False)
    return out[-1].astype(_I32)


def _lex_less(am, a1, a2, al, bm, b1, b2, bl):
    """Strict lexicographic ``a < b`` over 4-key tuples (vectorized)."""
    return (am < bm) | (
        (am == bm) & (
            (a1 < b1) | (
                (a1 == b1) & (
                    (a2 < b2) | ((a2 == b2) & (al < bl))
                )
            )
        )
    )


def _sorted_dirty_tuples(keys3, dirty_idx, N):
    """The dirty lanes' 4-key tuples under ``keys3``, sorted; pads (bucket
    entries >= N) get +inf-class keys and lane ``N``, so they sink below
    every real lane (real majors are < 4G, far below i64max)."""
    i64max = jnp.iinfo(jnp.int64).max
    pad = dirty_idx >= N
    safe_d = jnp.clip(dirty_idx, 0, N - 1)
    cols = [jnp.where(pad, i64max, k[safe_d]) for k in keys3]
    lane = jnp.where(pad, jnp.int32(N), safe_d).astype(_I32)
    out = jax.lax.sort((*cols, lane), num_keys=4, is_stable=False)
    return out[0], out[1], out[2], out[3]


def _rank_repair_merge(perm_old, old_major, old_k1, old_k2,
                       major, k1, k2, dirty_idx):
    """The rank-repair merge body (shared by :func:`order_repair_jit` and
    the fused :func:`order_update_jit` — ONE implementation, so the two
    entry points cannot drift): given the previous full-sort permutation,
    the key columns it was sorted under, the CURRENT key columns, and the
    compacted dirty-lane batch ``dirty_idx`` (``[Db]`` int32, pad entries
    ``N``), produce the permutation the full 4-key sort would. O(N +
    Db log N), and — deliberately — GATHER-shaped: the only [N]-payload
    scatter is the final permutation build. XLA:CPU lowers scatters to a
    scalar update loop an order of magnitude slower than its vectorized
    gathers, and the first formulation of this kernel (compacted
    clean-subsequence scatter + two output scatters) spent most of its
    ~7 ms there; positions are int32 (lane counts < 2^31) to halve the
    traffic of the O(N) passes.

    The old key columns replace the clean-subsequence compaction: perm_old
    is strictly sorted under them, so "insertion point among the CLEAN
    lanes" = (# lanes with old key < the dirty lane's new key, a binary
    search over perm_old) - (# DIRTY lanes with old key below it, a search
    over the Db-sized old-key-sorted bucket). Keys are strict (lane index
    last), so every count is unambiguous and the merge reproduces the
    unique full-sort permutation bit-for-bit."""
    N = perm_old.shape[0]
    Db = dirty_idx.shape[0]
    dmaj, dk1, dk2, dlane = _sorted_dirty_tuples((major, k1, k2),
                                                 dirty_idx, N)
    omaj, ok1_, ok2_, olane = _sorted_dirty_tuples(
        (old_major, old_k1, old_k2), dirty_idx, N)
    dpad = dlane >= N

    # (1) per dirty lane, # of ALL lanes whose OLD key sorts below its NEW
    # key: branchless binary search over perm_old, log2(N) fixed rounds of
    # a 4-key tuple compare (Db-sized gathers per round)
    lo = jnp.zeros(Db, _I32)
    hi = jnp.full(Db, N, _I32)
    for _ in range(max(1, int(N).bit_length())):
        mid = (lo + hi) >> 1
        lane_c = perm_old[jnp.clip(mid, 0, N - 1)]
        lc = jnp.clip(lane_c, 0, N - 1)
        less = _lex_less(old_major[lc], old_k1[lc], old_k2[lc], lane_c,
                         dmaj, dk1, dk2, dlane)          # old[mid] < dirty
        take = lo < hi
        lo = jnp.where(take & less, mid + 1, lo)
        hi = jnp.where(take & ~less, mid, hi)
    # (2) minus the DIRTY lanes among them (their old keys left the order):
    # the same search over the old-key-sorted dirty bucket
    lo2 = jnp.zeros(Db, _I32)
    hi2 = jnp.full(Db, Db, _I32)
    for _ in range(max(1, int(Db).bit_length())):
        mid = (lo2 + hi2) >> 1
        m = jnp.clip(mid, 0, Db - 1)
        less = _lex_less(omaj[m], ok1_[m], ok2_[m], olane[m],
                         dmaj, dk1, dk2, dlane)
        take = lo2 < hi2
        lo2 = jnp.where(take & less, mid + 1, lo2)
        hi2 = jnp.where(take & ~less, mid, hi2)
    # insertion point among the CLEAN lanes; pads forced past every real
    # clean index so the histogram below can never count them
    lo = jnp.where(dpad, jnp.int32(N), lo - lo2)
    # final dirty position = clean-before + dirty-before (own index in the
    # new-key-sorted bucket; pads sort last, so real indices are exact)
    fd = jnp.where(dpad, jnp.int32(N), lo + jnp.arange(Db, dtype=_I32))

    # -- assembly, fully GATHER-shaped (zero [N]-payload scatters: XLA:CPU
    # lowers an [N] int32 scatter to a ~3.7 ms scalar loop at 50k lanes,
    # where one more log N round of [N] gathers costs ~1 ms): the dirty
    # lanes land via a Db-sized scatter of their final positions, and each
    # remaining slot's lane is recovered DIRECTLY — clean slot j holds the
    # (j - #dirty-slots<=j)-th clean lane of perm_old (clean lanes keep
    # their relative order), found by binary search over the clean-lane
    # cumsum. This inverts the old formulation's clean-index -> slot map
    # (fc(r) = r + #dirty-insertions<=r, strictly increasing), so the
    # output permutation is unchanged bit-for-bit.
    dirty_mask = jnp.zeros(N, bool).at[dirty_idx].set(True, mode="drop")
    is_clean = ~dirty_mask[jnp.clip(perm_old, 0, N - 1)]
    cum_clean = jnp.cumsum(is_clean.astype(_I32))
    slot_lane = jnp.full(N, N, _I32).at[fd].set(dlane, mode="drop")
    cum_dirty = jnp.cumsum((slot_lane < N).astype(_I32))
    want = jnp.arange(1, N + 1, dtype=_I32) - cum_dirty  # clean rank + 1
    lo3 = jnp.zeros(N, _I32)
    hi3 = jnp.full(N, N, _I32)
    for _ in range(max(1, int(N).bit_length())):
        mid = (lo3 + hi3) >> 1
        less = cum_clean[jnp.clip(mid, 0, N - 1)] < want
        take = lo3 < hi3
        lo3 = jnp.where(take & less, mid + 1, lo3)
        hi3 = jnp.where(take & ~less, mid, hi3)
    clean_lane = perm_old[jnp.clip(lo3, 0, N - 1)]
    return jnp.where(slot_lane < N, slot_lane, clean_lane)


@partial(jax.jit, donate_argnums=(0,))
def order_repair_jit(perm_old, old_major, old_k1, old_k2,
                     major, k1, k2, dirty_idx):
    """Standalone rank-repair merge (see :func:`_rank_repair_merge`):
    ``perm_old`` (donated — the new permutation replaces it) was produced
    by the full 4-key sort under the OLD key columns; returns the
    permutation the full sort would produce under the CURRENT columns.
    Locked bit-for-bit against :func:`order_sort_jit` by
    tests/test_order_tail.py across sizes, dirty fractions, and key-tie
    pressure."""
    return _rank_repair_merge(perm_old, old_major, old_k1, old_k2,
                              major, k1, k2, dirty_idx)


def _order_update_core(group_emptiest, node_valid, node_group, node_tainted,
                       node_cordoned, creation_ns, node_pods_remaining,
                       old_major, old_k1, old_k2, perm_old, tainted_offsets,
                       bucket: int):
    """Trace-time body of :func:`order_update_jit` — also inlined by
    ``kernel.ordered_delta_decide_jit``, which fuses it with the delta
    decide into the ordered-incremental tick's SINGLE program (the
    selection masks and [N] elementwise passes CSE across the two, and the
    tick drops from two synchronous dispatches to one)."""
    N = perm_old.shape[0]
    major, k1, k2 = node_order_keys(
        group_emptiest, node_valid, node_group, node_tainted, node_cordoned,
        creation_ns, node_pods_remaining)
    dirty = (major != old_major) | (k1 != old_k1) | (k2 != old_k2)
    # compacted dirty-lane batch, gather-shaped: slot j holds the lane with
    # dirty-rank j, found by binary-searching the inclusive dirty cumsum
    # (first position with cum == j+1); slots past the count read N = pad
    cum = jnp.cumsum(dirty.astype(_I32))
    count = cum[N - 1].astype(_I32)
    slot = jnp.arange(bucket, dtype=_I32) + 1
    lo = jnp.zeros(bucket, _I32)
    hi = jnp.full(bucket, N, _I32)
    for _ in range(max(1, int(N).bit_length())):
        mid = (lo + hi) >> 1
        less = cum[jnp.clip(mid, 0, N - 1)] < slot
        take = lo < hi
        lo = jnp.where(take & less, mid + 1, lo)
        hi = jnp.where(take & ~less, mid, hi)
    dirty_idx = jnp.where(lo < N, lo, jnp.int32(N))

    perm = _rank_repair_merge(perm_old, old_major, old_k1, old_k2,
                              major, k1, k2, dirty_idx)
    scale_down = jnp.roll(perm, -tainted_offsets[-1])
    return major, k1, k2, perm, scale_down, count


@partial(jax.jit, static_argnums=(12,), donate_argnums=(7, 8, 9, 10))
def order_update_jit(group_emptiest, node_valid, node_group, node_tainted,
                     node_cordoned, creation_ns, node_pods_remaining,
                     old_major, old_k1, old_k2, perm_old, tainted_offsets,
                     bucket: int):
    """The ordered-incremental ORDER-STATE step, fused into one program
    (one dispatch, no mid-tick host round-trip — the separate keys/diff ->
    host mask readback -> host compaction -> repair -> roll chain
    serialized four dispatches and an [N]-bool transfer on the ordered
    tick's critical path): recompute every lane's keys, diff them against
    the stored columns, compact the changed lanes into a ``bucket``-sized
    batch ON DEVICE (rank-via-binary-search over the dirty cumsum —
    gathers, not an [N] scatter), run the rank-repair merge, and roll the
    repaired permutation into the scale-down order (``kernel.decide``'s
    exact assembly: tainted block first, rolled to the tail by the total
    tainted count). The steady ordered tick goes one step further and runs
    this body INSIDE its delta-decide program
    (``kernel.ordered_delta_decide_jit``); this standalone entry remains
    the kernel's unit-testable/lintable form and the direct consumer for
    callers that maintain order state without the incremental decide.

    Returns ``(major, k1, k2, perm, scale_down, count)``. ``count`` is the
    TRUE changed-lane total: when it exceeds ``bucket`` the compaction
    truncated and ``perm`` is INVALID — the caller must fall back to
    :func:`order_sort_jit` on the returned key columns (and grow the
    bucket; ops.device_state.IncrementalDecider owns that policy, plus the
    dirty-fraction threshold above which the merge stops paying). The old
    key columns and permutation are donated — replaced by the returned
    state either way. ``bucket`` is static: power-of-two growth bounds
    recompiles exactly like kernel.dirty_indices' delta buckets."""
    return _order_update_core(
        group_emptiest, node_valid, node_group, node_tainted, node_cordoned,
        creation_ns, node_pods_remaining, old_major, old_k1, old_k2,
        perm_old, tainted_offsets, bucket)


#: The persistent order-state tuple's field names, in tuple order — the
#: serialization contract ops/snapshot.py persists a decider's order state
#: under (``order.major`` ... ``order.perm``). Everything that packs or
#: unpacks the ``(major, k1, k2, perm)`` tuple by position iterates THIS,
#: so a field added to the order state breaks loudly at the snapshot layer
#: instead of silently truncating a restore.
ORDER_STATE_FIELDS = ("major", "k1", "k2", "perm")


def validate_order_state(major, k1, k2, perm, num_lanes: int) -> None:
    """Host-side structural validation of a DESERIALIZED order state (the
    snapshot restore path): per-column shape/dtype against the resident
    contract, and ``perm`` must actually be a permutation of the lane
    indices — a corrupted-but-crc-valid permutation would otherwise gather
    garbage lanes into every ordered window until the next full-sort
    fallback. O(N log N) host work, paid once per restore. Raises
    ``ValueError`` naming the violation."""
    cols = {"major": (major, np.int64), "k1": (k1, np.int64),
            "k2": (k2, np.int64), "perm": (perm, np.int32)}
    for name, (col, want_dtype) in cols.items():
        arr = np.asarray(col)
        if arr.shape != (num_lanes,):
            raise ValueError(
                f"order state column {name!r} has shape {arr.shape}, "
                f"expected ({num_lanes},)")
        if arr.dtype != want_dtype:
            raise ValueError(
                f"order state column {name!r} has dtype {arr.dtype}, "
                f"expected {np.dtype(want_dtype)}")
    if not np.array_equal(np.sort(np.asarray(perm)),
                          np.arange(num_lanes, dtype=np.int32)):
        raise ValueError("order state perm is not a permutation of the "
                         f"{num_lanes} lane indices")


__all__: Sequence[str] = (
    "order_sort_keys",
    "combined_order_sort",
    "assign_order_blocks",
    "pad_order_blocks",
    "make_sharded_order_tail",
    "node_order_keys",
    "order_keys_jit",
    "order_sort_jit",
    "order_repair_jit",
    "order_update_jit",
    "ORDER_STATE_FIELDS",
    "validate_order_state",
)
